"""Disaggregated prefill/decode serving: engine roles + KV-page transfer.

The production split the monolithic engine cannot express (ROADMAP item 2,
PAPER.md §L2–L3 reborn for inference): prefill is compute-bound and
bursty, decode is memory-bound and steady, so fleets run them on SEPARATE
engine pools and hand the prompt's KV cache across. Everything here
composes existing load-bearing pieces rather than adding a parallel
universe:

- a **prefill role** is an ordinary chunked ``CausalLMEngine`` +
  ``ContinuousBatcher`` with a prefix cache: running a prompt to its
  first token publishes the prompt's whole page chain into the role's
  ``KVBlockPool`` (PR 12 machinery, unchanged);
- **export** pins that chain (``pool.match``) and gathers its pages off
  the pool (``engine.export_prefix_pages`` — copies, so the pin drops
  right after dispatch, same stream-order argument as the chunk gather);
- **transfer** is either in-process device-to-device (the gathered
  device arrays flow straight into the decode engine's import scatter —
  ``jax.device_put`` reshards across the role meshes) or the serialized
  wire format below over the existing stdlib HTTP plumbing
  (``POST /v1/kv_transfer``, octet-stream);
- the **decode role** adopts via ``ContinuousBatcher.adopt_chain``:
  pool-index the tokens, scatter received pages into the new blocks
  BETWEEN decode steps on the loop thread — the decode executable is
  never touched, so disaggregation adds zero per-token dispatch;
- admission then re-prefills only the uncached tail, which is exactly a
  prefix-cache hit — **bit-parity with colocated serving is inherited**
  from PR 12's bit-exactness, not re-derived.

An interconnect-aware :class:`TransferBudget` sits in the admission path:
a bytes-in-flight cap queues (bounded, timed) or sheds transfers, sheds
surfacing as 429 ``Backpressure`` with the budget digest in ``/statusz``.

Role planning lives in ``parallel.mesh.plan_disagg_mesh`` (device-subset
split + per-role mesh axes); the scheduler-policy A/B gate lives in
``scripts/serve_bench.py --disagg``.

Wire format (version 1)::

    magic  b"KVPG"                      4 bytes
    version                             u16 big-endian
    header_len                          u32 big-endian
    header JSON (utf-8), keys:
        page_meta   {num_layers, block_tokens, heads, head_dim, dtype}
        n_blocks    pages carried (chain order, lane i = block i)
        token_ids   the FULL prompt ids (the decode pool re-derives its
                    own block keys from them)
        layout      axis-order tag ("lbthd" = layer,block,token,head,dim)
        crc32       zlib.crc32 of the k+v payload bytes
    k pages                             n_blocks contiguous C-order blocks
    v pages                             same shape, immediately after

Truncation, a bad magic, a version from the future, a geometry mismatch,
or a payload CRC mismatch all raise :class:`WireError` — the receiver
refuses rather than adopting garbage KV (tests/test_disagg.py pins each
refusal).

Wire format (version 2, live stream migration)::

    magic  b"KVPG"                      4 bytes
    version = 2                         u16 big-endian
    header_len                          u32 big-endian
    header JSON (utf-8), keys:
        stream      the StreamState dict (request_id, input_ids, tokens,
                    seed, temperature, eos_id, max_new_tokens, length)
        page_meta   {num_layers, cache_len, heads, head_dim, dtype} of
                    the SOURCE slot cache ({} when page-less)
        n_tokens    KV positions carried (== stream.length; 0 = page-less
                    replay — the receiver re-prefills from the tokens)
        layout      axis-order tag ("lthd" = layer,token,head,dim)
        crc32       zlib.crc32 of canonical-stream-JSON + k+v payload —
                    the CRC covers state AND pages, so a tampered token
                    list refuses exactly like a corrupt page byte
    k positions                         n_tokens contiguous C-order rows
    v positions                         same shape, immediately after

Version 1 buffers fed to :func:`deserialize_stream` (and v2 buffers fed
to :func:`deserialize_chain`) refuse on the version field — the two
formats share a magic but never a parser. The receiving side re-pads the
carried positions to its own ``cache_len`` (refusing streams longer than
its cache) and resumes decoding mid-generation via
``ContinuousBatcher.adopt_stream`` (tests/test_migrate.py pins each
refusal and the bit-parity contract).

Quantized engines (``kv_dtype="int8"``) speak the same two formats one
version up: **chain version 3** and **stream version 4** carry each page
side as its int8 ``q`` payload immediately followed by that side's
per-position float32 scales (``qk · sk · qv · sv``), and the CRC covers
the scale bytes too — a flipped scale byte refuses exactly like a
flipped page byte. Version and header dtype must agree (v1/v2 ⇒ dtype ≠
int8, v3/v4 ⇒ dtype == int8) or the parser refuses the buffer as
internally inconsistent. Cross-dtype adoption fails closed in BOTH
directions: an fp32 receiver refuses an int8 buffer (and vice versa) on
the ``page_meta`` dtype comparison, and peers predating these versions
refuse v3/v4 on the version number alone (tests/test_quant.py pins the
round-trips and both refusal directions).
"""

from __future__ import annotations

import json
import logging
import struct
import threading
import time
import zlib

import numpy as np

from distributed_tensorflow_tpu.obs.flightrec import NULL_RECORDER
from distributed_tensorflow_tpu.serve.batcher import Backpressure

__all__ = [
    "WireError",
    "WIRE_VERSION",
    "WIRE_VERSION_STREAM",
    "WIRE_VERSION_QUANT",
    "WIRE_VERSION_STREAM_QUANT",
    "serialize_chain",
    "deserialize_chain",
    "serialize_stream",
    "deserialize_stream",
    "TransferBudget",
    "DisaggServingPair",
    "make_kv_receiver",
    "post_kv_transfer",
    "StreamReceiver",
    "make_stream_receiver",
    "migrate_streams",
    "post_stream_migrate",
]

logger = logging.getLogger(__name__)

WIRE_MAGIC = b"KVPG"
WIRE_VERSION = 1
WIRE_VERSION_STREAM = 2
WIRE_VERSION_QUANT = 3  # int8 chain: q pages + f32 per-position scales
WIRE_VERSION_STREAM_QUANT = 4  # int8 stream: same payload rule as v3
_PREFIX = struct.Struct(">4sHI")  # magic, version, header_len
_LAYOUT = "lbthd"
_STREAM_LAYOUT = "lthd"


class WireError(ValueError):
    """A KV-page wire buffer the receiver must refuse (truncated, wrong
    magic/version, geometry mismatch, corrupt payload)."""


# ------------------------------------------------------------- wire format


def serialize_chain(token_ids, pages_k, pages_v, page_meta: dict) -> bytes:
    """Serialize a KV-page chain for the cross-process transport.

    ``pages_*`` are host arrays ``[num_layers, n, block_tokens, heads,
    head_dim]`` holding the chain's pages in order (NO pad lanes — the
    caller slices its export stage down to the real chain length);
    ``page_meta`` is the source engine's :meth:`page_meta` digest. The
    token ids ride in the header so the receiving pool can index the
    chain under its own trie without a side channel.

    Quantized pools pass each side as its ``{"q", "s"}`` tree (int8
    pages + float32 ``[num_layers, n, block_tokens]`` scales); the
    buffer then travels as version :data:`WIRE_VERSION_QUANT` with the
    scales appended to their side's payload and covered by the CRC.
    """
    if isinstance(pages_k, dict) != isinstance(pages_v, dict):
        raise ValueError(
            "k/v pages must both be plain arrays or both {'q','s'} trees"
        )
    quantized = isinstance(pages_k, dict)
    if quantized:
        pk = np.ascontiguousarray(pages_k["q"])
        pv = np.ascontiguousarray(pages_v["q"])
        sk = np.ascontiguousarray(np.asarray(pages_k["s"], dtype=np.float32))
        sv = np.ascontiguousarray(np.asarray(pages_v["s"], dtype=np.float32))
        if pk.dtype != np.int8:
            raise ValueError(
                f"quantized pages must be int8, got {pk.dtype.name}"
            )
        if sk.shape != pk.shape[:3] or sv.shape != pv.shape[:3]:
            raise ValueError(
                f"scale shapes {sk.shape}/{sv.shape} do not cover "
                f"[l,b,t] of pages {pk.shape}"
            )
    else:
        pk = np.ascontiguousarray(pages_k)
        pv = np.ascontiguousarray(pages_v)
        if pk.dtype == np.int8:
            raise ValueError(
                "int8 pages need their {'q','s'} scale tree — a bare "
                "int8 array cannot be dequantized on the far side"
            )
    if pk.shape != pv.shape:
        raise ValueError(f"k/v page shapes differ: {pk.shape} vs {pv.shape}")
    if pk.ndim != 5:
        raise ValueError(f"pages must be 5-D [l,b,t,h,d], got {pk.shape}")
    if len(token_ids) // max(int(pk.shape[2]), 1) != pk.shape[1]:
        raise ValueError(
            f"{len(token_ids)} token keys do not cover exactly the "
            f"{pk.shape[1]} pages carried (block_tokens={pk.shape[2]})"
        )
    if quantized:
        payload = pk.tobytes() + sk.tobytes() + pv.tobytes() + sv.tobytes()
        version = WIRE_VERSION_QUANT
    else:
        payload = pk.tobytes() + pv.tobytes()
        version = WIRE_VERSION
    header = {
        "page_meta": {
            "num_layers": int(pk.shape[0]),
            "block_tokens": int(pk.shape[2]),
            "heads": int(pk.shape[3]),
            "head_dim": int(pk.shape[4]),
            "dtype": str(pk.dtype.name),
        },
        "n_blocks": int(pk.shape[1]),
        "token_ids": [int(t) for t in token_ids],
        "layout": _LAYOUT,
        "crc32": zlib.crc32(payload) & 0xFFFFFFFF,
    }
    expect = {k: v for k, v in page_meta.items() if k != "max_chain"}
    got = dict(header["page_meta"])
    if expect != got:
        raise ValueError(
            f"pages {got} disagree with the engine's page_meta {expect}"
        )
    hbytes = json.dumps(header, separators=(",", ":")).encode("utf-8")
    return _PREFIX.pack(WIRE_MAGIC, version, len(hbytes)) + hbytes + payload


def deserialize_chain(buf: bytes):
    """Parse + verify a wire buffer: returns ``(token_ids, pages_k,
    pages_v, header)`` with host-numpy page stages (``{"q", "s"}`` trees
    for a quantized v3 buffer). Every malformation raises
    :class:`WireError` BEFORE any page bytes are trusted."""
    if len(buf) < _PREFIX.size:
        raise WireError(
            f"buffer of {len(buf)} bytes is shorter than the "
            f"{_PREFIX.size}-byte wire prefix"
        )
    magic, version, hlen = _PREFIX.unpack_from(buf)
    if magic != WIRE_MAGIC:
        raise WireError(f"bad magic {magic!r} (expected {WIRE_MAGIC!r})")
    if version not in (WIRE_VERSION, WIRE_VERSION_QUANT):
        raise WireError(
            f"wire version {version} unsupported (speaker of chain "
            f"versions {WIRE_VERSION} and {WIRE_VERSION_QUANT}); "
            "refusing rather than guessing the layout"
        )
    if len(buf) < _PREFIX.size + hlen:
        raise WireError(
            f"truncated header: need {hlen} bytes, have "
            f"{len(buf) - _PREFIX.size}"
        )
    try:
        header = json.loads(buf[_PREFIX.size:_PREFIX.size + hlen])
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise WireError(f"corrupt header JSON: {e}") from e
    try:
        meta = header["page_meta"]
        shape = (
            int(meta["num_layers"]), int(header["n_blocks"]),
            int(meta["block_tokens"]), int(meta["heads"]),
            int(meta["head_dim"]),
        )
        dtype = np.dtype(meta["dtype"])
        token_ids = [int(t) for t in header["token_ids"]]
        layout = header["layout"]
        crc = int(header["crc32"])
    except (KeyError, TypeError, ValueError) as e:
        raise WireError(f"header missing/invalid field: {e}") from e
    if layout != _LAYOUT:
        raise WireError(
            f"page layout {layout!r} unsupported (expected {_LAYOUT!r})"
        )
    if len(token_ids) // max(int(meta["block_tokens"]), 1) != shape[1]:
        raise WireError(
            f"{len(token_ids)} token keys cover "
            f"{len(token_ids) // max(int(meta['block_tokens']), 1)} blocks "
            f"but the buffer carries {shape[1]} pages — a receiving pool "
            "would index blocks whose pages never arrived"
        )
    quantized = version == WIRE_VERSION_QUANT
    if quantized != (dtype == np.dtype(np.int8)):
        raise WireError(
            f"wire version {version} carrying {dtype.name} pages is "
            f"internally inconsistent — int8 travels as version "
            f"{WIRE_VERSION_QUANT} with scale payloads, everything else "
            f"as version {WIRE_VERSION}"
        )
    if quantized:
        qbytes = int(np.prod(shape))
        sbytes = int(np.prod(shape[:3])) * 4
        nbytes = qbytes + sbytes
    else:
        nbytes = int(np.prod(shape)) * dtype.itemsize
    payload = buf[_PREFIX.size + hlen:]
    if len(payload) != 2 * nbytes:
        raise WireError(
            f"payload of {len(payload)} bytes != 2 x {nbytes} "
            f"for {shape} {dtype.name} pages"
        )
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise WireError("payload CRC mismatch: pages corrupted in flight")
    if quantized:
        def side(off):
            return {
                "q": np.frombuffer(
                    payload[off:off + qbytes], np.int8
                ).reshape(shape),
                "s": np.frombuffer(
                    payload[off + qbytes:off + nbytes], np.float32
                ).reshape(shape[:3]),
            }

        pages_k, pages_v = side(0), side(nbytes)
    else:
        pages_k = np.frombuffer(payload[:nbytes], dtype).reshape(shape)
        pages_v = np.frombuffer(payload[nbytes:], dtype).reshape(shape)
    return token_ids, pages_k, pages_v, header


# ------------------------------------------------- wire format v2 (streams)


def _canonical_state(state: dict) -> bytes:
    """The CRC-covered byte form of a stream-state dict: minimal JSON
    with sorted keys, so serializer and receiver derive identical bytes
    from identical state regardless of dict insertion order."""
    return json.dumps(state, separators=(",", ":"), sort_keys=True).encode(
        "utf-8"
    )


def serialize_stream(state, pages_k=None, pages_v=None,
                     page_meta: dict | None = None) -> bytes:
    """Serialize a live decode stream for the cross-process transport.

    ``state`` is a :class:`~.batcher.StreamState` (or its dict form);
    ``pages_*`` are the slot-export stages ``[num_layers, T, heads,
    head_dim]`` — sliced here to the state's ``length`` positions (the
    only ones a resumed slot will ever attend over) — and ``page_meta``
    is the source engine's :meth:`stream_page_meta` digest. Both pages
    ``None`` ships a page-less stream (``n_tokens=0``): the receiver
    re-prefills from the state's tokens, which is bit-identical by the
    (seed, absolute position) sampling contract, just slower.

    Quantized slot caches pass each stage as its ``{"q", "s"}`` tree
    (int8 positions + float32 ``[num_layers, T]`` scales); the buffer
    then travels as version :data:`WIRE_VERSION_STREAM_QUANT` with the
    scales in the CRC-covered payload.
    """
    sd = state.to_dict() if hasattr(state, "to_dict") else dict(state)
    sbytes = _canonical_state(sd)
    if (pages_k is None) != (pages_v is None):
        raise ValueError("pages_k and pages_v must both be given or both None")
    version = WIRE_VERSION_STREAM
    if pages_k is None:
        n, meta, payload = 0, {}, b""
    else:
        if page_meta is None:
            raise ValueError(
                "a page-carrying stream needs the source engine's "
                "stream_page_meta"
            )
        n = int(sd.get("length", 0))
        if n <= 0:
            raise ValueError(
                f"a page-carrying stream needs state length >= 1, got {n}"
            )
        if isinstance(pages_k, dict) != isinstance(pages_v, dict):
            raise ValueError(
                "k/v stages must both be plain arrays or both "
                "{'q','s'} trees"
            )
        quantized = isinstance(pages_k, dict)
        # device_get is fine here: stream serialization runs off the
        # decode loop (export already copied the slot out of the cache).
        if quantized:
            pk = np.ascontiguousarray(np.asarray(pages_k["q"])[:, :n])
            pv = np.ascontiguousarray(np.asarray(pages_v["q"])[:, :n])
            sk = np.ascontiguousarray(
                np.asarray(pages_k["s"], dtype=np.float32)[:, :n]
            )
            sv = np.ascontiguousarray(
                np.asarray(pages_v["s"], dtype=np.float32)[:, :n]
            )
            if pk.dtype != np.int8:
                raise ValueError(
                    f"quantized stages must be int8, got {pk.dtype.name}"
                )
            if sk.shape != pk.shape[:2] or sv.shape != pv.shape[:2]:
                raise ValueError(
                    f"scale shapes {sk.shape}/{sv.shape} do not cover "
                    f"[l,t] of stages {pk.shape}"
                )
            version = WIRE_VERSION_STREAM_QUANT
        else:
            pk = np.ascontiguousarray(np.asarray(pages_k)[:, :n])
            pv = np.ascontiguousarray(np.asarray(pages_v)[:, :n])
            if pk.dtype == np.int8:
                raise ValueError(
                    "int8 stages need their {'q','s'} scale tree — a "
                    "bare int8 array cannot be dequantized on the far "
                    "side"
                )
        if pk.shape != pv.shape:
            raise ValueError(f"k/v stage shapes differ: {pk.shape} vs {pv.shape}")
        if pk.ndim != 4:
            raise ValueError(f"stream pages must be 4-D [l,t,h,d], got {pk.shape}")
        meta = {
            "num_layers": int(pk.shape[0]),
            "cache_len": int(page_meta["cache_len"]),
            "heads": int(pk.shape[2]),
            "head_dim": int(pk.shape[3]),
            "dtype": str(pk.dtype.name),
        }
        if meta != dict(page_meta):
            raise ValueError(
                f"pages {meta} disagree with the engine's "
                f"stream_page_meta {dict(page_meta)}"
            )
        if quantized:
            payload = pk.tobytes() + sk.tobytes() + pv.tobytes() + sv.tobytes()
        else:
            payload = pk.tobytes() + pv.tobytes()
    header = {
        "stream": sd,
        "page_meta": meta,
        "n_tokens": n,
        "layout": _STREAM_LAYOUT,
        "crc32": zlib.crc32(sbytes + payload) & 0xFFFFFFFF,
    }
    hbytes = json.dumps(header, separators=(",", ":")).encode("utf-8")
    return (
        _PREFIX.pack(WIRE_MAGIC, version, len(hbytes))
        + hbytes + payload
    )


def deserialize_stream(buf: bytes):
    """Parse + verify a stream wire buffer: returns ``(state_dict,
    pages_k, pages_v, header)`` — pages ``None`` for a page-less stream,
    ``{"q", "s"}`` trees for a quantized v4 buffer. Every malformation
    raises :class:`WireError` BEFORE any byte of state or pages is
    trusted (fail-closed: refuse, never guess)."""
    if len(buf) < _PREFIX.size:
        raise WireError(
            f"buffer of {len(buf)} bytes is shorter than the "
            f"{_PREFIX.size}-byte wire prefix"
        )
    magic, version, hlen = _PREFIX.unpack_from(buf)
    if magic != WIRE_MAGIC:
        raise WireError(f"bad magic {magic!r} (expected {WIRE_MAGIC!r})")
    if version not in (WIRE_VERSION_STREAM, WIRE_VERSION_STREAM_QUANT):
        raise WireError(
            f"stream wire version {version} unsupported (speaker of "
            f"stream versions {WIRE_VERSION_STREAM} and "
            f"{WIRE_VERSION_STREAM_QUANT}); refusing rather than "
            "guessing the layout"
        )
    if len(buf) < _PREFIX.size + hlen:
        raise WireError(
            f"truncated header: need {hlen} bytes, have "
            f"{len(buf) - _PREFIX.size}"
        )
    try:
        header = json.loads(buf[_PREFIX.size:_PREFIX.size + hlen])
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise WireError(f"corrupt header JSON: {e}") from e
    try:
        sd = dict(header["stream"])
        n = int(header["n_tokens"])
        layout = header["layout"]
        crc = int(header["crc32"])
        length = int(sd["length"])
        [int(t) for t in sd["input_ids"]]
        [int(t) for t in sd["tokens"]]
    except (KeyError, TypeError, ValueError) as e:
        raise WireError(f"header missing/invalid field: {e}") from e
    if layout != _STREAM_LAYOUT:
        raise WireError(
            f"stream page layout {layout!r} unsupported "
            f"(expected {_STREAM_LAYOUT!r})"
        )
    quantized = version == WIRE_VERSION_STREAM_QUANT
    payload = buf[_PREFIX.size + hlen:]
    if n == 0:
        if quantized:
            raise WireError(
                "a quantized stream buffer (v4) must carry pages — "
                "page-less streams travel as version "
                f"{WIRE_VERSION_STREAM}"
            )
        if payload:
            raise WireError(
                f"page-less stream carries {len(payload)} stray payload bytes"
            )
        pk = pv = None
        shape = dtype = nbytes = qbytes = None
    else:
        if n != length:
            raise WireError(
                f"header carries {n} KV positions but the stream state's "
                f"length is {length} — a resumed slot would attend over "
                "positions that never arrived"
            )
        try:
            meta = header["page_meta"]
            shape = (
                int(meta["num_layers"]), n,
                int(meta["heads"]), int(meta["head_dim"]),
            )
            dtype = np.dtype(meta["dtype"])
        except (KeyError, TypeError, ValueError) as e:
            raise WireError(f"header missing/invalid field: {e}") from e
        if quantized != (dtype == np.dtype(np.int8)):
            raise WireError(
                f"stream wire version {version} carrying {dtype.name} "
                f"pages is internally inconsistent — int8 travels as "
                f"version {WIRE_VERSION_STREAM_QUANT} with scale "
                f"payloads, everything else as version "
                f"{WIRE_VERSION_STREAM}"
            )
        if quantized:
            qbytes = int(np.prod(shape))
            nbytes = qbytes + int(np.prod(shape[:2])) * 4
        else:
            nbytes = int(np.prod(shape)) * dtype.itemsize
        if len(payload) != 2 * nbytes:
            raise WireError(
                f"payload of {len(payload)} bytes != 2 x {nbytes} "
                f"for {shape} {dtype.name} stream pages"
            )
    if zlib.crc32(_canonical_state(sd) + payload) & 0xFFFFFFFF != crc:
        raise WireError(
            "stream CRC mismatch: state or pages corrupted in flight"
        )
    if n and quantized:
        def side(off):
            return {
                "q": np.frombuffer(
                    payload[off:off + qbytes], np.int8
                ).reshape(shape),
                "s": np.frombuffer(
                    payload[off + qbytes:off + nbytes], np.float32
                ).reshape(shape[:2]),
            }

        pk, pv = side(0), side(nbytes)
    elif n:
        pk = np.frombuffer(payload[:nbytes], dtype).reshape(shape)
        pv = np.frombuffer(payload[nbytes:], dtype).reshape(shape)
    return sd, pk, pv, header


# --------------------------------------------------------- transfer budget


class TransferBudget:
    """Interconnect-aware bytes-in-flight cap for KV-page transfers.

    The admission-path guard: a transfer :meth:`acquire`\\ s its byte
    count before moving anything. Over the cap it WAITS (bounded queue,
    bounded time — interconnects recover in milliseconds, admission
    shouldn't shed on a blip); a full waiter queue or a timeout SHEDS as
    :class:`~.batcher.Backpressure` (the server maps it to 429 +
    Retry-After, same as queue sheds). ``digest()`` feeds ``/statusz``.
    """

    def __init__(self, cap_bytes: int, *, max_queued: int = 8,
                 timeout_s: float = 2.0):
        if cap_bytes < 1:
            raise ValueError(f"cap_bytes must be >= 1, got {cap_bytes}")
        self.cap_bytes = int(cap_bytes)
        self.max_queued = int(max_queued)
        self.timeout_s = float(timeout_s)
        self._cv = threading.Condition()
        self._in_flight = 0
        self._queued = 0
        self._granted = 0
        self._shed = 0

    def acquire(self, nbytes: int) -> None:
        """Reserve ``nbytes`` of transfer headroom or raise
        ``Backpressure``. A single transfer larger than the whole cap can
        never fit and sheds immediately."""
        nbytes = int(nbytes)
        if nbytes <= 0:
            return
        deadline = time.monotonic() + self.timeout_s
        with self._cv:
            if nbytes > self.cap_bytes or self._queued >= self.max_queued:
                self._shed += 1
                raise Backpressure(self.timeout_s)
            self._queued += 1
            try:
                while self._in_flight + nbytes > self.cap_bytes:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._cv.wait(remaining):
                        self._shed += 1
                        raise Backpressure(self.timeout_s)
            finally:
                self._queued -= 1
            self._in_flight += nbytes
            self._granted += 1

    def release(self, nbytes: int) -> None:
        nbytes = int(nbytes)
        if nbytes <= 0:
            return
        with self._cv:
            self._in_flight = max(self._in_flight - nbytes, 0)
            self._cv.notify_all()

    def digest(self) -> dict:
        """The ``/statusz`` ``kv_transfer`` section."""
        with self._cv:
            return {
                "cap_bytes": self.cap_bytes,
                "in_flight_bytes": self._in_flight,
                "queued": self._queued,
                "granted_total": self._granted,
                "shed_total": self._shed,
            }


# --------------------------------------------------------- role orchestration


class DisaggServingPair:
    """One prefill role + one decode role behind a single submit surface.

    Both roles are ordinary engine+batcher stacks (built on the device
    subsets a :func:`~distributed_tensorflow_tpu.parallel.mesh.plan_disagg_mesh`
    planned, or sim engines in the bench); the pair owns only the
    hand-off: run the prompt on the prefill role to its first token,
    move the published page chain under the transfer budget, adopt it on
    the decode role, then submit the UNCHANGED request there — the
    decode role's admission re-prefills just the uncached tail, so the
    stream is bit-identical to a colocated engine's by the prefix-cache
    parity contract.

    ``transport="d2d"`` hands the gathered device pages straight to the
    decode engine's import scatter (same process, different device
    subsets); ``transport="wire"`` round-trips the serialized format —
    in-process it is the loopback rehearsal of the cross-process path
    (the bench's parity arm), cross-process the caller POSTs the buffer
    via :func:`post_kv_transfer` instead of constructing a pair.

    Engines without page export (sim engines) degrade to pool-only
    adoption: the chain is indexed on the decode pool with no page
    scatter, which is exact for sims whose prefill is a pure function of
    the full prompt.
    """

    def __init__(
        self,
        *,
        prefill_batcher,
        decode_batcher,
        prefill_engine=None,
        decode_engine=None,
        budget: TransferBudget | None = None,
        transport: str = "d2d",
        metrics=None,
        recorder=None,
    ):
        if transport not in ("d2d", "wire"):
            raise ValueError(
                f"transport must be 'd2d' or 'wire', got {transport!r}"
            )
        self.prefill = prefill_batcher
        self.decode = decode_batcher
        self._pre_engine = prefill_engine
        self._dec_engine = decode_engine
        self.budget = budget
        self.transport = transport
        self.metrics = metrics
        self.recorder = recorder if recorder is not None else NULL_RECORDER
        self._pre_pool = getattr(
            prefill_engine, "prefix_cache", None
        ) or getattr(prefill_batcher, "_pool", None)
        if self._pre_pool is None:
            raise ValueError(
                "prefill role needs a prefix cache (its pool IS the "
                "publication surface a transfer exports from)"
            )
        if prefill_engine is not None and decode_engine is not None and (
            callable(getattr(prefill_engine, "export_prefix_pages", None))
        ):
            pm = prefill_engine.page_meta()
            dm = decode_engine.page_meta()
            if pm != dm:
                raise ValueError(
                    f"role page geometries differ: prefill {pm} vs "
                    f"decode {dm} — chains cannot transfer"
                )

    # ------------------------------------------------------------ transfer

    def transfer(self, token_ids, request_id: str = "") -> int:
        """Move ``token_ids``'s published chain from the prefill pool to
        the decode role; returns the number of blocks the decode side
        newly adopted (0 = nothing published or already cached). Budget
        sheds raise ``Backpressure`` (recorded as ``kv_transfer_reject``);
        transfer itself records start/done events plus the role-labelled
        byte/latency families."""
        pool = self._pre_pool
        m = pool.match(token_ids)
        try:
            if not m.blocks:
                return 0
            # The decode pool must never index a block whose pages were
            # not carried: trim the token keys to EXACTLY the matched
            # chain's coverage, so its insert allocates n_blocks blocks
            # and not one more (the uncovered tail re-prefills there).
            token_ids = [
                int(t)
                for t in token_ids[: len(m.blocks) * pool.block_tokens]
            ]
            nbytes = len(m.blocks) * pool.bytes_per_block
            if self.budget is not None:
                try:
                    self.budget.acquire(nbytes)
                except Backpressure:
                    self.recorder.record(
                        "kv_transfer_reject", request_id,
                        cause="budget", bytes=nbytes,
                    )
                    raise
            try:
                t0 = time.monotonic()
                self.recorder.record(
                    "kv_transfer_start", request_id,
                    blocks=len(m.blocks), bytes=nbytes,
                    transport=self.transport,
                )
                adopted = self._move(token_ids, m.blocks)
                dt = time.monotonic() - t0
            finally:
                if self.budget is not None:
                    self.budget.release(nbytes)
            if self.metrics is not None:
                self.metrics.kv_transfer_bytes.inc("prefill", nbytes)
                self.metrics.kv_transfer_bytes.inc("decode", nbytes)
                self.metrics.kv_transfer_seconds.observe("prefill", dt)
                self.metrics.kv_transfer_seconds.observe("decode", dt)
            self.recorder.record(
                "kv_transfer_done", request_id,
                blocks=len(m.blocks), adopted=adopted, bytes=nbytes,
                ms=round(dt * 1e3, 3),
            )
            return adopted
        finally:
            pool.release(m)  # idempotent; pin held across the export

    def _move(self, token_ids, blocks) -> int:
        engine = self._pre_engine
        if engine is None or not callable(
            getattr(engine, "export_prefix_pages", None)
        ):
            # Sim / pool-only roles: index the chain, no pages to carry.
            return self.decode.adopt_chain(token_ids).result()
        pk, pv = engine.export_prefix_pages(blocks)
        if self.transport == "wire":
            # Loopback rehearsal of the cross-process path: fetch, frame,
            # parse, verify — byte-for-byte what POST /v1/kv_transfer
            # carries. device_get here is off the decode loop (this
            # module is not a jaxlint hot module) and overlaps both
            # roles' device work.
            import jax

            n = len(blocks)
            hk, hv = jax.device_get((pk, pv))
            buf = serialize_chain(
                token_ids,
                _slice_chain(hk, n),
                _slice_chain(hv, n),
                engine.page_meta(),
            )
            ids, wk, wv, _ = deserialize_chain(buf)
            m = self._dec_engine.page_meta()["max_chain"]
            return self.decode.adopt_chain(
                ids, _pad_chain(wk, m), _pad_chain(wv, m)
            ).result()
        # d2d: gathered device stages flow straight into the decode
        # engine's import scatter (device_put reshards across role
        # meshes; no host round-trip).
        return self.decode.adopt_chain(token_ids, pk, pv).result()

    # ------------------------------------------------------------- serving

    def submit(self, payload: dict, request_id: str | None = None):
        """Disaggregated serve of one request: prefill role to first
        token, chain transfer, decode role for the real stream. Blocks
        through prefill + transfer (callers thread per request, as the
        bench does); returns the decode role's Future — the stream it
        resolves to is bit-identical to a colocated engine's."""
        pre_payload = dict(payload)
        pre_payload["max_new_tokens"] = 1
        self.prefill.submit(pre_payload, request_id=request_id).result()
        try:
            self.transfer(
                payload["input_ids"],
                request_id=request_id or "",
            )
        except Backpressure:
            # Budget shed: the request still serves, just without the
            # chain — the decode role re-prefills the whole prompt.
            # Degraded latency, never a failed request.
            pass
        return self.decode.submit(payload, request_id=request_id)

    def generate(self, payload: dict, request_id: str | None = None):
        """Blocking convenience: :meth:`submit` + result."""
        return self.submit(payload, request_id=request_id).result()

    def close(self, drain: bool = True) -> None:
        self.prefill.close(drain=drain)
        self.decode.close(drain=drain)


def _slice_chain(pages, n: int):
    """First ``n`` chain lanes of a host page stage (plain array or
    quantized ``{"q", "s"}`` tree — every leaf shares axis 1)."""
    if isinstance(pages, dict):
        return {k: np.asarray(v)[:, :n] for k, v in pages.items()}
    return np.asarray(pages)[:, :n]


def _pad_chain(pages, max_chain: int):
    """Pad a ``[l, n, t, h, d]`` chain stage (or each leaf of a
    quantized ``{"q", "s"}`` tree — scales share the chain axis) to the
    import cell's fixed ``max_chain`` lanes (pad lanes are dropped by
    sentinel ids)."""
    if isinstance(pages, dict):
        return {k: _pad_chain(v, max_chain) for k, v in pages.items()}
    n = pages.shape[1]
    if n > max_chain:
        raise WireError(
            f"chain of {n} blocks exceeds the importer's max chain "
            f"{max_chain}"
        )
    if n == max_chain:
        return pages
    pad = np.zeros(
        (pages.shape[0], max_chain - n, *pages.shape[2:]), pages.dtype
    )
    return np.concatenate([pages, pad], axis=1)


# ------------------------------------------------------- cross-process wire


def make_kv_receiver(batcher, engine, *, budget: TransferBudget | None = None,
                     metrics=None, recorder=None):
    """The decode-process half of the cross-process transport: a
    ``bytes -> dict`` callable the HTTP server mounts at
    ``POST /v1/kv_transfer``. Verifies the wire buffer, checks geometry
    against the local engine, budget-gates the bytes, and adopts via the
    batcher (loop-thread import, like every adoption). Raises
    ``WireError`` (400) on refusal, ``Backpressure`` (429) on shed."""
    recorder = recorder if recorder is not None else NULL_RECORDER

    def receive(body: bytes) -> dict:
        try:
            token_ids, pk, pv, header = deserialize_chain(body)
        except WireError as e:
            recorder.record("kv_transfer_reject", "", cause="wire",
                            error=str(e))
            raise
        meta = engine.page_meta()
        got = dict(header["page_meta"])
        expect = {k: v for k, v in meta.items() if k != "max_chain"}
        if got != expect:
            recorder.record("kv_transfer_reject", "", cause="geometry")
            raise WireError(
                f"page geometry {got} does not match this engine's "
                f"{expect}"
            )
        nbytes = len(body)
        if budget is not None:
            try:
                budget.acquire(nbytes)
            except Backpressure:
                recorder.record("kv_transfer_reject", "", cause="budget",
                                bytes=nbytes)
                raise
        n_blocks = int(header["n_blocks"])
        try:
            t0 = time.monotonic()
            recorder.record("kv_transfer_start", "", blocks=n_blocks,
                            bytes=nbytes, transport="wire")
            adopted = batcher.adopt_chain(
                token_ids,
                _pad_chain(pk, meta["max_chain"]),
                _pad_chain(pv, meta["max_chain"]),
            ).result()
            dt = time.monotonic() - t0
        finally:
            if budget is not None:
                budget.release(nbytes)
        if metrics is not None:
            metrics.kv_transfer_bytes.inc("decode", nbytes)
            metrics.kv_transfer_seconds.observe("decode", dt)
        recorder.record("kv_transfer_done", "", blocks=n_blocks,
                        adopted=adopted, bytes=nbytes,
                        ms=round(dt * 1e3, 3))
        return {"adopted_blocks": adopted, "bytes": nbytes}

    return receive


def post_kv_transfer(host: str, port: int, buf: bytes, *,
                     timeout_s: float = 10.0) -> dict:
    """Prefill-process half of the cross-process transport: POST a
    serialized chain to a decode server's ``/v1/kv_transfer``. Returns
    the adoption digest; raises ``Backpressure`` on a 429 shed and
    ``WireError`` on a 400 refusal (mirroring the in-process paths)."""
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    try:
        conn.request(
            "POST", "/v1/kv_transfer", body=buf,
            headers={"Content-Type": "application/octet-stream"},
        )
        resp = conn.getresponse()
        body = resp.read()
        try:
            out = json.loads(body)
        except json.JSONDecodeError:
            out = {"error": body[:200].decode("utf-8", "replace")}
        if resp.status == 429:
            raise Backpressure(
                float(resp.headers.get("Retry-After", 1.0))
            )
        if resp.status == 400:
            raise WireError(out.get("error", "kv transfer refused"))
        if resp.status != 200:
            raise RuntimeError(
                f"kv transfer failed: HTTP {resp.status} {out}"
            )
        return out
    finally:
        conn.close()


# ------------------------------------------------- cross-process migration


def _pad_stream_stage(stage, cache_len: int):
    """Pad a ``[l, n, h, d]`` stream stage (or each leaf of a quantized
    ``{"q", "s"}`` tree — scales share the position axis) to the
    receiver's full ``cache_len`` positions (the slot-import cell
    scatters whole slots; pad positions sit beyond ``length`` and are
    never attended)."""
    if isinstance(stage, dict):
        return {k: _pad_stream_stage(v, cache_len) for k, v in stage.items()}
    n = stage.shape[1]
    if n > cache_len:
        raise WireError(
            f"stream carries {n} KV positions but this engine's cache "
            f"holds {cache_len}"
        )
    if n == cache_len:
        return stage
    pad = np.zeros(
        (stage.shape[0], cache_len - n, *stage.shape[2:]), stage.dtype
    )
    return np.concatenate([stage, pad], axis=1)


class StreamReceiver:
    """The survivor half of live stream migration: a ``bytes -> dict``
    callable the HTTP server mounts at ``POST /v1/stream_migrate``, plus
    the pending registry ``POST /v1/stream_wait`` blocks on.

    Verifies the v2 wire buffer, checks slot geometry against the local
    engine, budget-gates the bytes (same :class:`TransferBudget` as KV
    chains — stream payloads and chain payloads share one interconnect),
    and resumes via ``batcher.adopt_stream``. The adoption future —
    which resolves with the COMPLETED generation — is registered under
    the stream's original request id so the migration orchestrator can
    collect the finished result from this replica with
    ``POST /v1/stream_wait`` instead of replaying from scratch. Raises
    ``WireError`` (400) on refusal, ``Backpressure`` (429) on shed.
    """

    _RACETRACE_ATTRS = ("_pending",)

    def __init__(self, batcher, engine=None, *,
                 budget: TransferBudget | None = None,
                 metrics=None, recorder=None):
        self.batcher = batcher
        self.engine = engine
        self.budget = budget
        self.metrics = metrics
        self.recorder = recorder if recorder is not None else NULL_RECORDER
        self._lock = threading.Lock()
        self._pending: dict[str, object] = {}  # request_id -> Future

    def _reject(self, cause: str, err: Exception) -> None:
        self.recorder.record(
            "stream_migrate_reject", "", cause=cause, error=str(err)
        )
        if self.metrics is not None:
            self.metrics.stream_migrations.inc("rejected")

    def __call__(self, body: bytes) -> dict:
        from distributed_tensorflow_tpu.serve.batcher import StreamState

        try:
            sd, pk, pv, header = deserialize_stream(body)
        except WireError as e:
            self._reject("wire", e)
            raise
        try:
            state = StreamState.from_dict(sd)
        except (KeyError, TypeError, ValueError) as e:
            self._reject("state", e)
            raise WireError(f"stream state invalid: {e}") from e
        if pk is not None:
            engine = self.engine
            if engine is None or not getattr(engine, "stream_migrate", False):
                e = WireError(
                    "this engine cannot import stream pages (built without "
                    "stream_migrate); retry page-less"
                )
                self._reject("no_import", e)
                raise e
            meta = engine.stream_page_meta()
            got = {
                k: v for k, v in dict(header["page_meta"]).items()
                if k != "cache_len"
            }
            expect = {k: v for k, v in meta.items() if k != "cache_len"}
            if got != expect:
                e = WireError(
                    f"stream page geometry {got} does not match this "
                    f"engine's {expect}"
                )
                self._reject("geometry", e)
                raise e
            try:
                pk = _pad_stream_stage(pk, int(meta["cache_len"]))
                pv = _pad_stream_stage(pv, int(meta["cache_len"]))
            except WireError as e:
                self._reject("geometry", e)
                raise
        nbytes = len(body)
        if self.budget is not None:
            try:
                self.budget.acquire(nbytes)
            except Backpressure as e:
                self._reject("budget", e)
                raise
        # Release as soon as the adoption is enqueued: the wire bytes are
        # landed host-side by then, and holding the budget across a whole
        # resumed generation would starve every later migration.
        try:
            try:
                fut = self.batcher.adopt_stream(state, pk, pv)
            except Backpressure as e:
                self._reject("budget", e)
                raise
            except (ValueError, RuntimeError) as e:
                self._reject("state", e)
                raise WireError(f"stream refused: {e}") from e
        finally:
            if self.budget is not None:
                self.budget.release(nbytes)
        with self._lock:
            self._pending[state.request_id] = fut
        if self.metrics is not None:
            self.metrics.stream_migrations.inc("adopted")
        return {
            "adopted": True,
            "request_id": state.request_id,
            "pages": pk is not None,
            "bytes": nbytes,
            "resume_at": len(state.tokens),
        }

    def wait(self, request_id: str, timeout_s: float | None = None) -> dict:
        """Block for an adopted stream's finished generation (the
        ``/v1/stream_wait`` body). Raises :class:`KeyError` for an id
        this replica never adopted (server maps it to 404 — the caller
        falls back to a resume_tokens replay)."""
        import concurrent.futures

        with self._lock:
            fut = self._pending.get(request_id)
        if fut is None:
            raise KeyError(request_id)
        try:
            out = fut.result(timeout_s)
        except (concurrent.futures.TimeoutError, TimeoutError):
            # Still generating: keep the registration so a later wait
            # (or a retry after the orchestrator's own timeout) can
            # still collect the stream instead of replaying it.
            raise
        except Exception:
            with self._lock:
                self._pending.pop(request_id, None)
            raise
        with self._lock:
            self._pending.pop(request_id, None)
        return out

    def digest(self) -> dict:
        """The ``/statusz`` ``stream_migrate`` section."""
        with self._lock:
            return {"pending_streams": len(self._pending)}


def make_stream_receiver(batcher, engine=None, *,
                         budget: TransferBudget | None = None,
                         metrics=None, recorder=None) -> StreamReceiver:
    """Factory mirroring :func:`make_kv_receiver` for the stream path."""
    return StreamReceiver(
        batcher, engine, budget=budget, metrics=metrics, recorder=recorder
    )


def migrate_streams(batcher, engine, targets, *, metrics=None,
                    recorder=None, fault_injector=None,
                    timeout_s: float = 30.0) -> dict:
    """Victim-side migration orchestration (``POST /migratez``): export
    every live stream, push each to a survivor, and resolve the
    victim-held client futures with a ``status: "migrated"`` digest the
    router follows up on (``POST /v1/stream_wait`` against the target,
    or a ``resume_tokens`` replay when the target dies too).

    ``targets`` is a list of ``(host, port)`` pairs (the router's pick);
    streams round-robin across them. A push that refuses pages
    (``WireError`` — e.g. a geometry-mismatched survivor) retries
    page-less to the same target before moving on; a stream no target
    accepts re-adopts LOCALLY so it finishes here rather than dying —
    migration degrades, it never loses a stream. ``fault_injector`` is
    the serving :class:`~distributed_tensorflow_tpu.serve.faultinject.FaultInjector`
    (``wire_corrupt`` flips a byte of the nth outbound buffer — the
    receiver's CRC refusal is the thing under drill).
    """
    recorder = recorder if recorder is not None else NULL_RECORDER
    targets = [(str(h), int(p)) for h, p in targets]
    if not targets:
        raise ValueError("migrate_streams needs at least one target")
    exported = batcher.export_streams(timeout_s)
    meta = (
        engine.stream_page_meta()
        if getattr(engine, "stream_migrate", False) else None
    )
    migrated, readopted = 0, 0
    n_sent = 0
    outcomes = []
    for i, exp in enumerate(exported):
        state = exp.state
        bufs = []
        if exp.pages_k is not None and meta is not None:
            bufs.append(serialize_stream(
                state, exp.pages_k, exp.pages_v, meta
            ))
        bufs.append(serialize_stream(state))  # page-less fallback
        landed = None
        for attempt in range(len(targets)):
            host, port = targets[(i + attempt) % len(targets)]
            for buf in bufs:
                n_sent += 1
                if fault_injector is not None and fault_injector.check_wire(
                    n_sent
                ):
                    # Corrupt the last payload byte (or the header when
                    # page-less): the receiver must refuse on CRC.
                    buf = buf[:-1] + bytes([buf[-1] ^ 0xFF])
                try:
                    out = post_stream_migrate(
                        host, port, buf, timeout_s=timeout_s
                    )
                except WireError:
                    continue  # refused (pages or corruption): next form
                except Exception:  # noqa: BLE001 — shed, dead target, ...
                    break  # this target is out; try the next one
                landed = (host, port, out)
                break
            if landed is not None:
                break
        if landed is not None:
            host, port, out = landed
            migrated += 1
            if metrics is not None:
                metrics.stream_migrations.inc("migrated")
            outcomes.append({
                "request_id": state.request_id,
                "outcome": "migrated",
                "target": f"{host}:{port}",
                "pages": bool(out.get("pages")),
            })
            if exp.future is not None:
                exp.future.set_result({
                    "status": "migrated",
                    "target": f"{host}:{port}",
                    "request_id": state.request_id,
                    "tokens": list(state.tokens),
                    "n_tokens": len(state.tokens),
                    "prompt_len": len(state.input_ids),
                })
        else:
            # No survivor took it: keep the stream alive HERE (the drain
            # waits a little longer for it, but nothing is lost) and let
            # the original future resolve from the re-adopted run.
            readopted += 1
            if metrics is not None:
                metrics.stream_migrations.inc("readopted")
            outcomes.append({
                "request_id": state.request_id,
                "outcome": "readopted",
            })
            fut = batcher.adopt_stream(state, exp.pages_k, exp.pages_v)
            if exp.future is not None:
                _chain_future(fut, exp.future)
    digest = {
        "exported": len(exported),
        "migrated": migrated,
        "readopted": readopted,
        "streams": outcomes,
    }
    recorder.record(
        "stream_export", "", exported=len(exported), migrated=migrated,
        readopted=readopted,
    )
    return digest


def _chain_future(src, dst) -> None:
    """Mirror ``src``'s eventual result/exception onto ``dst``."""

    def _copy(f):
        err = f.exception()
        if err is not None:
            dst.set_exception(err)
        else:
            dst.set_result(f.result())

    src.add_done_callback(_copy)


def post_stream_migrate(host: str, port: int, buf: bytes, *,
                        timeout_s: float = 10.0) -> dict:
    """Victim-process half of live migration: POST a serialized stream
    to a survivor's ``/v1/stream_migrate``. Returns the adoption digest;
    raises ``Backpressure`` on a 429 shed and ``WireError`` on a 400
    refusal (mirroring the in-process paths)."""
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    try:
        conn.request(
            "POST", "/v1/stream_migrate", body=buf,
            headers={"Content-Type": "application/octet-stream"},
        )
        resp = conn.getresponse()
        body = resp.read()
        try:
            out = json.loads(body)
        except json.JSONDecodeError:
            out = {"error": body[:200].decode("utf-8", "replace")}
        if resp.status == 429:
            raise Backpressure(
                float(resp.headers.get("Retry-After", 1.0))
            )
        if resp.status == 400:
            raise WireError(out.get("error", "stream migrate refused"))
        if resp.status != 200:
            raise RuntimeError(
                f"stream migrate failed: HTTP {resp.status} {out}"
            )
        return out
    finally:
        conn.close()
