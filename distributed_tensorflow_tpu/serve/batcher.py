"""Dynamic micro-batcher: the queue between user requests and the engine.

Semantics (the classic serving recipe, e.g. TF-Serving's BatchingSession —
the piece the reference's train-only harness never had):

- Requests enqueue with a ``Future``; a single flusher thread groups them.
- A batch flushes when it reaches ``max_batch`` rows OR when the OLDEST
  queued request has waited ``max_delay_ms`` — latency is bounded by the
  deadline, throughput by the batch size, and the tradeoff is two knobs.
- The queue is BOUNDED: past ``max_queue`` pending requests, ``submit``
  raises :class:`Backpressure` with a retry-after hint. Overload degrades
  to explicit rejection the client can retry, never to an unbounded queue
  marching toward OOM.

The batcher is engine-agnostic: ``run_batch(payloads) -> results`` is any
callable (serve/engine.py provides the real ones; tests pass stubs).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from collections.abc import Callable, Sequence
from concurrent.futures import Future

from distributed_tensorflow_tpu.obs.metrics import ServeMetrics


class Backpressure(RuntimeError):
    """Queue full — reject now, retry after ``retry_after_s``."""

    def __init__(self, retry_after_s: float):
        super().__init__(
            f"request queue full; retry after {retry_after_s * 1e3:.0f} ms"
        )
        self.retry_after_s = retry_after_s


@dataclasses.dataclass(frozen=True)
class BatcherConfig:
    max_batch: int = 8          # flush when this many requests are queued
    max_delay_ms: float = 8.0   # ...or when the oldest has waited this long
    max_queue: int = 64         # bounded depth; beyond -> Backpressure

    def __post_init__(self):
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {self.max_batch}")
        if self.max_delay_ms < 0:
            raise ValueError("max_delay_ms must be >= 0")
        if self.max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {self.max_queue}")


class _Pending:
    __slots__ = ("payload", "future", "t_enqueue")

    def __init__(self, payload):
        self.payload = payload
        self.future: Future = Future()
        self.t_enqueue = time.monotonic()


class DynamicBatcher:
    """Thread-safe request queue with size/deadline flushing.

    ``run_batch`` runs on the flusher thread — one batch in flight at a
    time, which is the right shape for a single-accelerator engine (the
    executable is serial anyway) and keeps ordering deterministic.
    """

    def __init__(
        self,
        run_batch: Callable[[list], Sequence],
        config: BatcherConfig | None = None,
        metrics: ServeMetrics | None = None,
    ):
        self.config = config or BatcherConfig()
        self.metrics = metrics or ServeMetrics()
        self._run_batch = run_batch
        self._cv = threading.Condition()
        self._queue: deque[_Pending] = deque()
        self._closed = False
        self._thread = threading.Thread(
            target=self._loop, name="serve-batcher", daemon=True
        )
        self._thread.start()

    def submit(self, payload) -> Future:
        """Enqueue one request; returns its Future (result = engine output).

        Raises :class:`Backpressure` when the queue is at ``max_queue`` —
        the retry-after hint is one max-delay window, the time one flush
        takes to drain ``max_batch`` slots.
        """
        with self._cv:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if len(self._queue) >= self.config.max_queue:
                self.metrics.rejected.inc()
                # One flush window, floored at 1 ms so a zero-delay config
                # still hands clients a usable (non-zero) retry hint.
                raise Backpressure(max(self.config.max_delay_ms / 1e3, 1e-3))
            pending = _Pending(payload)
            self._queue.append(pending)
            self.metrics.requests.inc()
            self.metrics.queue_depth.set(len(self._queue))
            self._cv.notify_all()
        return pending.future

    def _take_batch(self) -> list[_Pending] | None:
        """Block until a batch is due (size or deadline) or close drains."""
        max_delay = self.config.max_delay_ms / 1e3
        with self._cv:
            while True:
                if self._queue:
                    if len(self._queue) >= self.config.max_batch or self._closed:
                        break
                    remaining = (
                        self._queue[0].t_enqueue + max_delay - time.monotonic()
                    )
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
                elif self._closed:
                    return None
                else:
                    self._cv.wait()
            batch = [
                self._queue.popleft()
                for _ in range(min(len(self._queue), self.config.max_batch))
            ]
            self.metrics.queue_depth.set(len(self._queue))
            return batch

    def _loop(self):
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            self.metrics.batches.inc()
            self.metrics.batch_occupancy.observe(len(batch))
            try:
                results = self._run_batch([p.payload for p in batch])
            except Exception as e:  # noqa: BLE001 — fail the batch, not the server
                self.metrics.errors.inc()
                for p in batch:
                    if not p.future.cancelled():
                        p.future.set_exception(e)
                continue
            now = time.monotonic()
            for p, r in zip(batch, results):
                self.metrics.latency.observe(now - p.t_enqueue)
                if not p.future.cancelled():
                    p.future.set_result(r)

    def close(self, drain: bool = True) -> None:
        """Stop the flusher. ``drain=True`` serves what's queued first;
        otherwise pending futures fail with a RuntimeError."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            if not drain:
                while self._queue:
                    p = self._queue.popleft()
                    p.future.set_exception(RuntimeError("batcher closed"))
            self._cv.notify_all()
        self._thread.join(timeout=30)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
