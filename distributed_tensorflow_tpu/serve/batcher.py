"""Dynamic micro-batcher: the queue between user requests and the engine.

Semantics (the classic serving recipe, e.g. TF-Serving's BatchingSession —
the piece the reference's train-only harness never had):

- Requests enqueue with a ``Future``; a flusher thread groups them.
- A batch flushes when it reaches ``max_batch`` rows OR when the OLDEST
  queued request has waited ``max_delay_ms`` — latency is bounded by the
  deadline, throughput by the batch size, and the tradeoff is two knobs.
- The queue is BOUNDED: past ``max_queue`` pending requests, ``submit``
  raises :class:`Backpressure` with a retry-after hint. Overload degrades
  to explicit rejection the client can retry, never to an unbounded queue
  marching toward OOM.
- Optional BUCKET-AWARE queues (``bucket_for``): requests group per
  engine bucket so short requests flush together instead of riding a
  long batchmate's padded bucket. Deadline semantics stay global (the
  flusher always waits on the globally-oldest request, then flushes its
  bucket) and the ``max_queue`` bound counts ALL buckets together.
- Optional OVERLAPPED dispatch (``dispatch``/``fetch``): when the engine
  splits its hot path, the flusher thread only assembles and launches —
  a separate completion thread blocks on ``fetch`` — so up to
  ``max_in_flight`` batches pipeline host assembly against device
  compute. Results deliver in dispatch order (FIFO completion queue).

The batcher is engine-agnostic: ``run_batch(payloads) -> results`` is any
callable (serve/engine.py provides the real ones; tests pass stubs), and
the overlap/bucket hooks are optional keyword callables.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import queue
import threading
import time
from collections import deque
from collections.abc import Callable, Sequence
from concurrent.futures import Future

from distributed_tensorflow_tpu.obs.flightrec import NULL_RECORDER
from distributed_tensorflow_tpu.obs.metrics import ServeMetrics
from distributed_tensorflow_tpu.obs.trace import NULL_TRACER
from distributed_tensorflow_tpu.serve.spec import SlotSpec

logger = logging.getLogger(__name__)


class Backpressure(RuntimeError):
    """Queue full — reject now, retry after ``retry_after_s``."""

    def __init__(self, retry_after_s: float):
        super().__init__(
            f"request queue full; retry after {retry_after_s * 1e3:.0f} ms"
        )
        self.retry_after_s = retry_after_s


def drain_retry_after_s(
    queued_units: float,
    unit_rate: float,
    floor_s: float,
    cap_s: float = 30.0,
) -> float:
    """Retry-After for an admission shed, from actual drain arithmetic.

    ``queued_units / unit_rate`` is how long the work already queued takes
    to drain at the recently observed service rate (units and rate must
    agree: tokens owed over tokens/s for the continuous batcher, requests
    over requests/s for the flush batcher). Floored at ``floor_s`` (one
    flush window — the old fixed hint — so an idle or just-started server
    never hands out a zero), capped at ``cap_s`` so a momentary stall
    can't tell clients to go away for minutes. A non-positive rate means
    nothing has drained inside the measurement window; the floor is the
    only honest answer then.
    """
    if unit_rate <= 0.0 or queued_units <= 0.0:
        return floor_s
    return min(max(queued_units / unit_rate, floor_s), cap_s)


VALID_SCHED = ("fifo", "edf")


@dataclasses.dataclass(frozen=True)
class BatcherConfig:
    max_batch: int = 8          # flush when this many requests are queued
    max_delay_ms: float = 8.0   # ...or when the oldest has waited this long
    max_queue: int = 64         # bounded depth; beyond -> Backpressure
    max_in_flight: int = 2      # dispatched-not-fetched batches (needs an
                                # engine with dispatch/fetch; else 1)
    bucket_queues: bool = False  # per-bucket queues (needs bucket_for)
    sched: str = "fifo"         # admission order: "fifo" | "edf"
                                # (earliest-deadline-first within priority
                                # class; continuous batcher only)
    preempt: bool = False       # evict a lower-priority slot when a queued
                                # higher-priority request would miss its
                                # deadline (needs sched="edf")
    preempt_margin_ms: float = 20.0  # preempt when now + margin crosses the
                                # waiter's deadline (headroom for the park/
                                # re-prefill round trip)
    default_priority: int = 1   # class for requests that don't send one
                                # (0 is the most urgent; larger = later)

    def __post_init__(self):
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {self.max_batch}")
        if self.max_delay_ms < 0:
            raise ValueError("max_delay_ms must be >= 0")
        if self.max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {self.max_queue}")
        if self.max_in_flight < 1:
            raise ValueError(
                f"max_in_flight must be >= 1, got {self.max_in_flight}"
            )
        if self.sched not in VALID_SCHED:
            raise ValueError(
                f"sched must be one of {VALID_SCHED}, got {self.sched!r}"
            )
        if self.preempt and self.sched != "edf":
            raise ValueError(
                "preempt=True requires sched='edf' — preemption exists to "
                "rescue deadline-bearing waiters, which FIFO cannot order"
            )
        if self.preempt_margin_ms < 0:
            raise ValueError("preempt_margin_ms must be >= 0")
        if self.default_priority < 0:
            raise ValueError(
                f"default_priority must be >= 0, got {self.default_priority}"
            )


class _Pending:
    __slots__ = (
        "payload", "future", "t_enqueue", "t_taken", "request_id",
        "priority", "deadline_abs", "preempted",
    )

    def __init__(self, payload, request_id=None, default_priority=0):
        self.payload = payload
        self.future: Future = Future()
        self.t_enqueue = time.monotonic()
        self.t_taken = 0.0          # stamped when the flusher takes the batch
        self.request_id = request_id
        # DynamicBatcher accepts arbitrary payloads (any object run_batch
        # understands); only mapping payloads can carry scheduling fields.
        fields = payload if isinstance(payload, dict) else {}
        self.priority = int(fields.get("priority", default_priority))
        # Absolute TTFT deadline (monotonic clock); None = best-effort.
        ddl = fields.get("deadline_ms")
        self.deadline_abs = (
            self.t_enqueue + float(ddl) / 1e3 if ddl is not None else None
        )
        self.preempted = 0          # park/resume round trips survived


class DynamicBatcher:
    """Thread-safe request queue with size/deadline flushing.

    Without ``dispatch``/``fetch``, ``run_batch`` runs on the flusher
    thread — one batch in flight at a time, the right shape for an engine
    that blocks anyway. With them, the flusher assembles+launches and a
    completion thread fetches, bounded by ``config.max_in_flight``.
    """

    # Shared mutable state watched by obs.sanitizer.sanitize_races in the
    # pipelining tests; every access must be ordered by self._cv.
    # _served is deliberately NOT watched: it is ordered by _cv like the
    # rest, but instrumenting a per-flush hot-path write would eat into
    # the racetrace overhead budget for zero extra race coverage.
    _RACETRACE_ATTRS = ("_queues", "_count", "_closed", "_n_inflight")

    def __init__(
        self,
        run_batch: Callable[[list], Sequence],
        config: BatcherConfig | None = None,
        metrics: ServeMetrics | None = None,
        *,
        dispatch: Callable | None = None,
        fetch: Callable | None = None,
        bucket_for: Callable | None = None,
        tracer=None,
        recorder=None,
        layout: str = "",
    ):
        self.config = config or BatcherConfig()
        if self.config.sched != "fifo":
            raise ValueError(
                "DynamicBatcher flushes whole batches and holds no slots to "
                "reorder or preempt; sched policies need the continuous "
                f"batcher (got sched={self.config.sched!r})"
            )
        self.metrics = metrics or ServeMetrics()
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.recorder = recorder if recorder is not None else NULL_RECORDER
        # The engine's mesh-layout label; keys the per-layout phase
        # histograms (ServeMetrics.layout_phase). Empty = unlabelled.
        self._layout = layout
        self._req_ids = itertools.count()
        self._run_batch = run_batch
        self._dispatch = dispatch
        self._fetch = fetch
        self._pipelined = dispatch is not None and fetch is not None
        self._bucket_for = bucket_for if self.config.bucket_queues else None
        self._cv = threading.Condition()
        self._queues: dict = {}      # bucket key -> deque[_Pending]
        self._count = 0              # total pending across buckets
        self._served = 0             # lifetime completed requests
        self._closed = False
        self._inflight_sem = threading.BoundedSemaphore(
            self.config.max_in_flight
        )
        self._n_inflight = 0
        self._completion: queue.Queue = queue.Queue()
        self._fetch_thread = None
        if self._pipelined:
            self._fetch_thread = threading.Thread(
                target=self._completion_loop,
                name="serve-batcher-fetch",
                daemon=True,
            )
            self._fetch_thread.start()
        self._thread = threading.Thread(
            target=self._loop, name="serve-batcher", daemon=True
        )
        self._thread.start()

    def submit(self, payload, request_id: str | None = None) -> Future:
        """Enqueue one request; returns its Future (result = engine output).

        ``request_id`` is the trace correlation key: callers (the HTTP
        front end) pass theirs through; otherwise one is minted here, and
        either way it rides the request end to end — on the returned
        Future (``.request_id``, plus ``.phases`` once resolved), in every
        span the request produces, and in rejection/failure accounting.

        Raises :class:`Backpressure` when the queue is at ``max_queue`` —
        the retry-after hint is one max-delay window, the time one flush
        takes to drain ``max_batch`` slots. The rejection carries the
        ``request_id`` so shed load stays attributable in logs.
        """
        key = self._bucket_for(payload) if self._bucket_for else None
        if request_id is None:
            request_id = f"r-{next(self._req_ids):08d}"
        metrics = self.metrics  # local: instruments carry their own locks
        with self._cv:
            if self._closed:
                metrics.rejected_by_cause.inc("closed")
                if metrics.windowed:
                    metrics.bad_w.add(1.0)
                self.recorder.record(
                    "request_reject", request_id, cause="closed"
                )
                raise RuntimeError("batcher is closed")
            if self._count >= self.config.max_queue:
                metrics.rejected.inc()
                metrics.rejected_by_cause.inc("backpressure")
                if metrics.windowed:
                    metrics.rejected_w.add(1.0)
                    metrics.bad_w.add(1.0)
                self.tracer.instant(
                    "rejected", "serve", request_id=request_id,
                    cause="backpressure", queue_depth=self._count,
                )
                self.recorder.record(
                    "request_reject", request_id, cause="backpressure",
                    queue_depth=self._count,
                )
                # Drain-time hint: queued requests over the recent
                # completion rate, floored at one flush window (1 ms min
                # so a zero-delay config still hands out a non-zero hint).
                exc = Backpressure(drain_retry_after_s(
                    float(self._count),
                    self.metrics.ok_w.rate(10.0),
                    max(self.config.max_delay_ms / 1e3, 1e-3),
                ))
                exc.request_id = request_id
                raise exc
            pending = _Pending(payload, request_id)
            pending.future.request_id = request_id
            self._queues.setdefault(key, deque()).append(pending)
            self._count += 1
            metrics.requests.inc()
            metrics.queue_depth.set(self._count)
            self._cv.notify_all()
        if metrics.windowed:
            metrics.requests_w.add(1.0)
        self.recorder.record("request_admit", request_id)
        return pending.future

    def status(self) -> dict:
        """Live stack view for the health tracker / probe body: one
        consistent read of the state the flusher mutates under ``_cv``."""
        with self._cv:
            return {
                "closed": self._closed,
                "mode": "flush",
                "served": self._served,
                "queue_depth": self._count,
                "max_queue": self.config.max_queue,
                "in_flight": self._n_inflight,
                "max_in_flight": self.config.max_in_flight,
            }

    # ------------------------------------------------------------- flusher

    def _full_bucket(self):
        """(found, key) for a bucket at max_batch, oldest head first
        (fairness). A plain key can't signal absence: the single-queue
        mode's bucket key IS None."""
        found, best = False, None
        for key, q in self._queues.items():
            if len(q) >= self.config.max_batch and (
                not found
                or q[0].t_enqueue < self._queues[best][0].t_enqueue
            ):
                found, best = True, key
        return found, best

    def _oldest_bucket(self):
        return min(
            self._queues, key=lambda k: self._queues[k][0].t_enqueue
        )

    def _take_batch(self) -> list[_Pending] | None:
        """Block until a batch is due (size or deadline) or close drains.

        The deadline is GLOBAL: the wait tracks the oldest request across
        all buckets, so a lone request in a cold bucket still flushes
        within ``max_delay_ms`` of arrival.
        """
        max_delay = self.config.max_delay_ms / 1e3
        with self._cv:
            while True:
                if self._count:
                    full, key = self._full_bucket()
                    if full or self._closed:
                        if not full:
                            key = self._oldest_bucket()
                        break
                    key = self._oldest_bucket()
                    remaining = (
                        self._queues[key][0].t_enqueue
                        + max_delay
                        - time.monotonic()
                    )
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
                elif self._closed:
                    return None
                else:
                    self._cv.wait()
            q = self._queues[key]
            batch = [
                q.popleft()
                for _ in range(min(len(q), self.config.max_batch))
            ]
            if not q:
                del self._queues[key]
            self._count -= len(batch)
            self.metrics.queue_depth.set(self._count)
            now = time.monotonic()
            for p in batch:
                p.t_taken = now  # queue_wait phase ends here
            return batch

    def _fail(self, batch: list[_Pending], exc: BaseException) -> None:
        metrics = self.metrics  # local: instruments carry their own locks
        metrics.errors.inc()
        metrics.rejected_by_cause.inc("engine_failure", len(batch))
        if metrics.windowed:
            metrics.bad_w.add(float(len(batch)))
        for p in batch:
            self.tracer.instant(
                "engine_failure", "serve", request_id=p.request_id,
                error=type(exc).__name__,
            )
            self.recorder.record(
                "engine_failure", p.request_id, error=type(exc).__name__,
            )
            if not p.future.cancelled():
                p.future.set_exception(exc)
        logger.warning(
            "batch of %d failed (%s): request_ids=%s",
            len(batch), type(exc).__name__, [p.request_id for p in batch],
        )
        self.recorder.trigger("engine_failure")

    def _deliver(self, batch: list[_Pending], results,
                 marks: list[tuple[str, float]] = (), final_phase="fetch",
                 layout: str | None = None):
        """Resolve futures + record the per-request phase breakdown.

        ``marks`` are the batch-level phase boundaries measured by the
        flusher/completion threads, as ``(phase_name, t_end)`` in dispatch
        order; each request's first phase is its own ``queue_wait``
        (enqueue -> taken) and its last (``final_phase``) ends at the
        delivery timestamp. Boundaries are CONTIGUOUS, so the phase sum
        equals the measured enqueue->reply latency by construction — the
        serve_bench tripwire fails loudly if instrumentation ever drifts
        ``layout`` labels the per-layout phase twins (defaults to the
        batcher's engine layout; an in-flight handle that knows better —
        e.g. a mesh-sharded dispatch — overrides per batch).
        """
        if layout is None:
            layout = self._layout
        if len(results) != len(batch):
            # An engine that answers short would leave the excess futures
            # pending FOREVER under a bare zip — fail the whole batch
            # loudly instead (the satellite fix for the silent drop).
            self._fail(
                batch,
                RuntimeError(
                    f"engine returned {len(results)} results for a batch "
                    f"of {len(batch)} requests"
                ),
            )
            return
        now = time.monotonic()
        tracer, metrics = self.tracer, self.metrics
        t_taken = batch[0].t_taken  # one flush: all rows taken together
        if tracer.enabled:
            t = t_taken
            for name, t_end in marks:
                tracer.record(name, t, t_end, cat="serve",
                              args={"rows": len(batch)})
                t = t_end
            tracer.record(final_phase, t, now, cat="serve",
                          args={"rows": len(batch)})
        windowed = metrics.windowed
        latencies: list[float] = []
        phase_values: dict[str, list[float]] = {}
        per_request: list[dict] = []
        for p in batch:
            latency = now - p.t_enqueue
            metrics.latency.observe(latency)
            latencies.append(latency)
            # Exact per-request latency for the serve_bench SLO-math gate
            # (windowed-histogram attainment vs the exact log).
            p.future.latency_s = latency
            phases = {"queue_wait": p.t_taken - p.t_enqueue}
            t = p.t_taken
            for name, t_end in marks:
                phases[name] = t_end - t
                t = t_end
            phases[final_phase] = now - t
            for name, dt in phases.items():
                phase_values.setdefault(name, []).append(dt)
            per_request.append(phases)
            tracer.record("request", p.t_enqueue, now, cat="serve",
                          request_id=p.request_id)
            tracer.record("queue_wait", p.t_enqueue, p.t_taken, cat="serve",
                          request_id=p.request_id)
        # Whole-batch metric recording BEFORE resolving futures (a reader
        # joining on a future must see its batch's samples), with the
        # windowed series taking each lock once per flush, not per request.
        for name, vals in phase_values.items():
            metrics.observe_phase_batch(name, vals, layout, now)
        if windowed:
            metrics.latency_w.observe_many(latencies, now)
            metrics.ok_w.add(float(len(batch)), now)
        for p, r, phases in zip(batch, results, per_request):
            if not p.future.cancelled():
                p.future.phases = phases
                p.future.set_result(r)
        with self._cv:
            self._served += len(batch)
        if self.recorder.enabled:
            for p in batch:
                self.recorder.record(
                    "request_complete", p.request_id,
                    latency_ms=round((now - p.t_enqueue) * 1e3, 3),
                )

    def _loop(self):
        while True:
            batch = self._take_batch()
            if batch is None:
                if self._pipelined:
                    self._completion.put(None)  # unblock the fetch thread
                return
            self.metrics.batches.inc()
            self.metrics.batch_occupancy.observe(len(batch))
            if not self._pipelined:
                # The serial path runs its batch ON this thread, so without
                # in-flight accounting a request could be inside the engine
                # while both queue_depth and in_flight read 0 — drain
                # probes (router hot-swap) had to demand two consecutive
                # zero-work reads to close that blind spot. Count the
                # running batch like the pipelined path does and the
                # blind spot is gone.
                with self._cv:
                    self._n_inflight += 1
                    self.metrics.in_flight.set(self._n_inflight)
                try:
                    try:
                        results = self._run_batch(
                            [p.payload for p in batch]
                        )
                    except Exception as e:  # noqa: BLE001 — fail the batch, not the server
                        self._fail(batch, e)
                        continue
                    # Serial path: run_batch blocks through assemble +
                    # device + fetch, so the breakdown collapses to
                    # queue_wait -> run.
                    self._deliver(batch, results, final_phase="run")
                finally:
                    # Not decremented until futures resolve: a drain probe
                    # reading zero must mean NOTHING is owed to a caller.
                    with self._cv:
                        self._n_inflight -= 1
                        self.metrics.in_flight.set(self._n_inflight)
                continue
            # Overlapped path: launch, hand off to the completion thread,
            # and immediately assemble the next batch. The semaphore
            # bounds dispatched-but-unfetched batches to max_in_flight.
            self._inflight_sem.acquire()
            try:
                handle = self._dispatch([p.payload for p in batch])
            except Exception as e:  # noqa: BLE001
                self._inflight_sem.release()
                self._fail(batch, e)
                continue
            t_disp = time.monotonic()
            with self._cv:
                self._n_inflight += 1
                self.metrics.in_flight.set(self._n_inflight)
            self._completion.put((batch, handle, t_disp))

    def _completion_loop(self):
        while True:
            item = self._completion.get()
            if item is None:
                return
            batch, handle, t_disp = item
            try:
                results = self._fetch(handle)
            except Exception as e:  # noqa: BLE001
                self._fail(batch, e)
            else:
                # Phase boundaries: real engines stamp t_assembled (host
                # buffers filled) on dispatch and t_got (device_get
                # returned) on fetch; handles without them degrade to
                # coarser-but-still-contiguous boundaries.
                t_got = getattr(handle, "t_got", None) or time.monotonic()
                t_asm = getattr(handle, "t_assembled", None) or t_disp
                self._deliver(
                    batch,
                    results,
                    marks=[
                        ("batch_assemble", t_asm),
                        ("dispatch", t_disp),
                        ("device", t_got),
                    ],
                    layout=getattr(handle, "layout", "") or self._layout,
                )
            finally:
                with self._cv:
                    self._n_inflight -= 1
                    self.metrics.in_flight.set(self._n_inflight)
                self._inflight_sem.release()

    def close(self, drain: bool = True, join_timeout_s: float = 30.0) -> None:
        """Stop the flusher. ``drain=True`` serves what's queued first;
        otherwise pending futures fail with a RuntimeError.

        Raises ``RuntimeError`` if the worker threads are still alive after
        ``join_timeout_s`` — a wedged engine must be VISIBLE, not a
        silently leaked daemon thread.
        """
        with self._cv:
            if self._closed:
                return
            self._closed = True
            if not drain:
                while self._queues:
                    _, q = self._queues.popitem()
                    while q:
                        p = q.popleft()
                        p.future.set_exception(RuntimeError("batcher closed"))
                self._count = 0
            self._cv.notify_all()
        self._thread.join(timeout=join_timeout_s)
        if self._fetch_thread is not None:
            self._fetch_thread.join(timeout=join_timeout_s)
        stuck = [
            t.name
            for t in (self._thread, self._fetch_thread)
            if t is not None and t.is_alive()
        ]
        if stuck:
            msg = (
                f"batcher thread(s) {stuck} still running after "
                f"{join_timeout_s:.0f}s close timeout — engine likely wedged"
            )
            logger.error(msg)
            raise RuntimeError(msg)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class _Slot:
    """Host bookkeeping for one KV-cache slot's occupant. Every field is
    owned by ``ContinuousBatcher._cv``; ``gen`` disambiguates a reused slot
    from the occupant an in-flight step was dispatched for."""

    __slots__ = (
        "pending", "gen", "prompt_len", "length", "max_new", "eos_id",
        "temperature", "seed", "tokens", "n_dispatched", "t_first",
        "t_last_tok", "prefilling", "chunk_pos", "cached_len", "chain",
        "slot_id", "spec", "prompt_ids", "draft", "verifying",
        "resume", "full_prompt", "admit_len", "preempting",
        "preempt_exempt",
    )

    def __init__(self, pending: _Pending, gen: int, payload: dict,
                 default_max_new: int):
        self.pending = pending
        self.gen = gen
        self.prompt_len = len(payload["input_ids"])
        # Migration replay (serve/disagg.py stream wire): already-delivered
        # generated tokens ride as ``resume_tokens`` — the prefill treats
        # them as prompt suffix (so the next sample lands at the SAME
        # absolute position the uninterrupted stream would use), while
        # ``prompt_len`` and the result's token list keep the client's
        # original view (tokens accumulate across retry hops).
        self.resume = [int(t) for t in payload.get("resume_tokens", ())]
        self.full_prompt = (
            [int(t) for t in payload["input_ids"]] + self.resume
        )
        self.admit_len = len(self.full_prompt)
        self.length = self.admit_len    # cache pages written (advances at
        self.n_dispatched = 0           # DISPATCH, so steps pipeline)
        self.max_new = int(payload.get("max_new_tokens", default_max_new))
        eos = payload.get("eos_id")
        self.eos_id = None if eos is None else int(eos)
        self.temperature = float(payload.get("temperature", 0.0))
        self.seed = int(payload.get("seed", 0))
        self.tokens: list[int] = list(self.resume)
        self.t_first = 0.0
        self.t_last_tok = 0.0
        # Chunked-prefill bookkeeping (chunked engines only): prompt
        # tokens already in cache pages (cached prefix + dispatched
        # chunks), the pinned prefix-cache match, and whether chunk
        # dispatches remain before the slot may join decode steps.
        self.prefilling = False
        self.chunk_pos = 0
        self.cached_len = 0
        self.chain = None
        self.slot_id = -1  # table index, stamped at admission (flight rec)
        # Speculative-decoding bookkeeping (spec-enabled engines only):
        # the per-occupancy SlotSpec state machine, the prompt as a plain
        # int list (drafting history = prompt_ids + tokens), the draft
        # awaiting its verify verdict, and whether a verify step is in
        # flight — a verifying slot never re-dispatches until the verdict
        # fetches (spec-mode slots advance at FETCH, not dispatch).
        self.spec: SlotSpec | None = None
        self.prompt_ids: list[int] = []
        self.draft: list[int] | None = None
        self.verifying = False
        # Priority-preemption bookkeeping: a marked victim stops taking
        # new decode/verify/chunk dispatches and parks once its in-flight
        # steps settle; an exempt slot was chosen once but could not park
        # (pool full, un-bucketable resume) and runs to completion.
        self.preempting = False
        self.preempt_exempt = False


@dataclasses.dataclass
class StreamState:
    """The host half of a live generation's checkpoint (serve/disagg.py
    ships it next to the slot's KV pages): everything a peer replica needs
    to resume the stream bit-identically — prompt, every token generated
    so far (client-visible, accumulated across hops), the sampling key
    material, and ``length`` = the cache positions the exported pages
    cover (``len(input_ids) + len(tokens) - 1``: the newest token's KV is
    written by the NEXT decode step, exactly as on the source)."""

    request_id: str
    input_ids: list
    tokens: list
    seed: int = 0
    temperature: float = 0.0
    eos_id: int | None = None
    max_new_tokens: int = 32
    length: int = 0

    def to_dict(self) -> dict:
        return {
            "request_id": str(self.request_id),
            "input_ids": [int(t) for t in self.input_ids],
            "tokens": [int(t) for t in self.tokens],
            "seed": int(self.seed),
            "temperature": float(self.temperature),
            "eos_id": None if self.eos_id is None else int(self.eos_id),
            "max_new_tokens": int(self.max_new_tokens),
            "length": int(self.length),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "StreamState":
        eos = d.get("eos_id")
        return cls(
            request_id=str(d["request_id"]),
            input_ids=[int(t) for t in d["input_ids"]],
            tokens=[int(t) for t in d["tokens"]],
            seed=int(d.get("seed", 0)),
            temperature=float(d.get("temperature", 0.0)),
            eos_id=None if eos is None else int(eos),
            max_new_tokens=int(d["max_new_tokens"]),
            length=int(d.get("length", 0)),
        )

    def replay_payload(self) -> dict:
        """The ``/v1/generate`` payload that resumes this stream WITHOUT
        pages: the generated tokens ride as ``resume_tokens`` and the
        target re-prefills prompt+prefix at absolute positions — the
        failover path when the stream's pages died with its replica."""
        out = {
            "input_ids": list(self.input_ids),
            "max_new_tokens": int(self.max_new_tokens),
            "temperature": float(self.temperature),
            "seed": int(self.seed),
        }
        if self.tokens:
            out["resume_tokens"] = list(self.tokens)
        if self.eos_id is not None:
            out["eos_id"] = int(self.eos_id)
        return out


@dataclasses.dataclass
class ExportedStream:
    """One live stream lifted out of a batcher: its :class:`StreamState`,
    the slot's KV pages when the engine could export them (device arrays
    ``[nl, cache_len, heads, head_dim]``; ``None`` for queued / still-
    prefilling streams, which replay page-less), and the victim-held
    client future the migrator resolves once the stream lands elsewhere
    (or re-adopts locally on push failure)."""

    state: StreamState
    pages_k: object | None = None
    pages_v: object | None = None
    future: Future | None = None


class _ExportRequest:
    """Cross-thread handshake for ``export_streams``: the HTTP thread
    parks on ``event`` while the decode-loop thread quiesces in-flight
    steps, captures every live stream, and posts the results."""

    __slots__ = ("event", "results")

    def __init__(self):
        self.event = threading.Event()
        self.results: list[ExportedStream] = []


class ContinuousBatcher:
    """Slot-table scheduler over a decode engine: continuous batching.

    Where :class:`DynamicBatcher` flushes a batch and waits for it, this
    batcher owns a fixed table of ``engine.slots`` KV-cache slots and runs
    an endless decode loop over whichever slots are live: new requests are
    admitted into FREE slots between decode steps (a prefill dispatch
    joins them to the in-flight batch), and a finished sequence frees its
    slot immediately — the next queued request takes it on the very next
    iteration, so occupancy never collapses to the slowest member the way
    a static batch does. ``admission="flush"`` keeps the same machinery
    but only admits when the table is EMPTY — the static-batching baseline
    the serve_bench decode A/B measures against.

    Threading mirrors the pipelined DynamicBatcher: the decode-loop thread
    is the only engine dispatcher (the engine's device-state swap is
    single-writer by that contract), a completion thread fetches each
    step's sampled tokens, and ``max_in_flight`` bounds
    dispatched-but-unfetched steps — host lengths advance at DISPATCH
    time, so step k+1 launches against step k's still-un-fetched device
    state and the token fetch overlaps the next step's compute. Slot reuse
    while stale steps are in flight is safe on both sides: host-side a
    per-slot generation tag drops stale tokens, device-side every cache
    page is re-written (by the new occupant's prefill or decode) before
    anything reads it, and dispatch order means stale writes land first.

    Per-request results resolve on the submit Future as ``{"tokens",
    "n_tokens", "prompt_len", "bucket"}`` with contiguous phases
    ``queue_wait -> prefill -> decode`` summing to wall latency by
    construction; per-token observability rides the ``decode_step`` phase
    family (inter-token latencies), the ``ttft`` histogram, and the
    ``tokens`` / ``tokens_w`` counters.

    On a CHUNKED engine (``prefill_chunks`` + ``prefill_chunk_size``)
    admission consults the engine's prefix-cache trie — a hit pins the
    matched page chain and shortens the prompt to its un-cached suffix —
    and prefill becomes a sequence of bounded chunk dispatches, at most
    one chunk batch per loop iteration interleaved with the decode step,
    so in-flight slots' ITL stays bounded by one chunk's compute during
    long-prompt admission. The final chunk samples the first token
    (``t_first``/``ttft`` semantics unchanged) and publishes the finished
    prefix pages back to the pool; chunk dispatches ride a batch-level
    ``prefill_chunk`` phase/span while per-request phases keep the same
    contiguous taxonomy (the ``prefill`` phase simply covers every chunk).

    On a SPECULATIVE engine (``spec_tokens > 0``, exposing ``verify`` and
    a ``spec`` config — serve/spec.py) each occupied slot carries a
    :class:`~distributed_tensorflow_tpu.serve.spec.SlotSpec`: the loop
    drafts from the slot's own prompt+generated history, dispatches ONE
    fixed-shape ``[slots, k+1]`` verify step for every speculating slot,
    and at fetch emits the accepted prefix plus the verified model token —
    1..k+1 tokens per step, bit-identical to the plain stream (exact-match
    acceptance against deterministic per-(seed, position) sampling).
    Spec-mode slots advance ``length`` at FETCH and never overlap their
    own steps (the verdict decides the next position); backed-off slots
    (low acceptance EMA) ride the plain pipelined decode path unchanged,
    re-probing periodically once their outstanding steps drain. The ITL
    histogram stays PER TOKEN: a verify step that emits m+1 tokens
    contributes m+1 samples splitting the step's wall interval.
    """

    # Watched by obs.sanitizer.sanitize_races in tests/test_serve_decode.py
    # and tests/test_serve_spec.py; every access must be ordered by
    # self._cv.
    _RACETRACE_ATTRS = (
        "_queue", "_count", "_closed", "_slots", "_n_active", "_n_inflight",
        "_steps", "_tokens_emitted", "_spec_drafted", "_spec_accepted",
        "_spec_rejects", "_adoptions", "_stream_adopts", "_export_req",
        "_class_queued", "_preempt_parked", "_preempt_resumed",
        "_preempt_aborted",
    )

    def __init__(
        self,
        engine,
        config: BatcherConfig | None = None,
        metrics: ServeMetrics | None = None,
        *,
        admission: str = "continuous",
        tracer=None,
        recorder=None,
        layout: str = "",
    ):
        if admission not in ("continuous", "flush"):
            raise ValueError(
                f"admission must be 'continuous' or 'flush', got {admission!r}"
            )
        self.config = config or BatcherConfig()
        if self.config.preempt and admission != "continuous":
            raise ValueError(
                "preempt=True requires admission='continuous' — flush "
                "admission only ever fills an empty table, so there is "
                "never an occupied slot to preempt for a waiter"
            )
        self.metrics = metrics or ServeMetrics()
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.recorder = recorder if recorder is not None else NULL_RECORDER
        self._layout = layout or getattr(engine, "layout", "")
        self._engine = engine
        self._admission = admission
        self._admit_cap = min(self.config.max_batch, engine.max_batch)
        self._default_max_new = getattr(engine, "max_new_tokens", 32)
        # Chunked-prefill engines expose prefill_chunks + a chunk size;
        # admission then consults the prefix trie and dispatches bounded
        # chunks interleaved with decode steps instead of one monolithic
        # prefill. Legacy engines (and stubs) keep the original path.
        self._chunked = (
            callable(getattr(engine, "prefill_chunks", None))
            and getattr(engine, "prefill_chunk_size", 0) > 0
        )
        self._chunk_size = getattr(engine, "prefill_chunk_size", 0)
        self._pool = (
            getattr(engine, "prefix_cache", None) if self._chunked else None
        )
        if self._pool is not None and self.recorder.enabled:
            # Evictions happen inside the pool's allocator; hand it the
            # recorder so prefix_evict events land in the same ring.
            self._pool.recorder = self.recorder
        # Speculative decoding: engines built with spec_tokens > 0 expose
        # a verify dispatch + a SpecConfig (engine.spec); per-slot SlotSpec
        # state is built at admission. Stubs and spec-off engines keep the
        # plain decode path untouched.
        self._spec_cfg = (
            getattr(engine, "spec", None)
            if callable(getattr(engine, "verify", None)) else None
        )
        self._spec_k = (
            self._spec_cfg.spec_tokens if self._spec_cfg is not None else 0
        )
        # Draft-length cache-headroom guard; engines without a fixed
        # cache_len (stubs) are unconstrained.
        self._cache_len = getattr(engine, "cache_len", 1 << 30)
        # Quantized-serving capacity gauge: engines that know their KV
        # storage dtype publish bytes/token once at attach (static for the
        # engine's lifetime; the dtype label keeps mixed fleets legible).
        if callable(getattr(engine, "kv_bytes_per_token", None)):
            self.metrics.kv_bytes_per_token.set(
                getattr(engine, "kv_dtype", "float32"),
                engine.kv_bytes_per_token(),
            )
        # tokens_per_step numerator/denominator for status(): emitted
        # tokens over decode+verify step completions — the speculation
        # win at a glance. Spec accounting totals live here too.
        self._steps = 0
        self._tokens_emitted = 0
        self._spec_drafted = 0
        self._spec_accepted = 0
        self._spec_rejects = 0
        # Backoff flips detected at PLAN time (empty-draft EMA decay);
        # only the decode-loop thread touches this list (_take_work fills,
        # _loop drains to the flight recorder outside _cv).
        self._plan_events: list[tuple[str, int, str, float]] = []
        self._req_ids = itertools.count()
        self._gens = itertools.count(1)
        self._cv = threading.Condition()
        self._queue: deque[_Pending] = deque()
        # Pending KV-chain adoptions (serve/disagg.py): processed on the
        # decode-loop thread BETWEEN steps, because publishing a chain
        # swaps the engine's pool refs — same single-dispatcher rule as
        # every other engine touch.
        self._adoptions: deque = deque()
        # Live-stream migration (serve/disagg.py stream wire): pending
        # mid-generation adoptions awaiting a free slot, and the at-most-
        # one outstanding export request the decode loop services once
        # in-flight steps quiesce. Same single-dispatcher rule: slot
        # import / export cells only ever dispatch from the loop thread.
        self._stream_adopts: deque = deque()
        self._export_req: _ExportRequest | None = None
        # Serving-side fault injection (serve/faultinject.py): hooks fire
        # on the decode-step dispatch clock. None = no chaos.
        self.fault_injector = None
        self._dispatched_steps = 0
        # Priority scheduling state (all under _cv): per-class queued
        # counts backing the serve_sched_queue_depth gauge, plus lifetime
        # park / resume / aborted-park totals for status()["sched"].
        self._class_queued: dict[int, int] = {}
        self._preempt_parked = 0
        self._preempt_resumed = 0
        self._preempt_aborted = 0
        self._count = 0
        self._served = 0             # lifetime completed requests
        self._closed = False
        self._slots: list[_Slot | None] = [None] * engine.slots
        self._n_active = 0
        self._n_inflight = 0
        self._inflight_sem = threading.BoundedSemaphore(
            self.config.max_in_flight
        )
        self._completion: queue.Queue = queue.Queue()
        self._fetch_thread = threading.Thread(
            target=self._completion_loop, name="serve-decode-fetch",
            daemon=True,
        )
        self._fetch_thread.start()
        self._thread = threading.Thread(
            target=self._loop, name="serve-decode", daemon=True
        )
        self._thread.start()

    def submit(self, payload, request_id: str | None = None) -> Future:
        """Enqueue one generation request (same Future/Backpressure contract
        as :meth:`DynamicBatcher.submit`); it joins the slot table at the
        next admission point — between decode steps, not behind a flush."""
        if request_id is None:
            request_id = f"r-{next(self._req_ids):08d}"
        metrics = self.metrics  # local: instruments carry their own locks
        with self._cv:
            if self._closed:
                metrics.rejected_by_cause.inc("closed")
                if metrics.windowed:
                    metrics.bad_w.add(1.0)
                self.recorder.record(
                    "request_reject", request_id, cause="closed"
                )
                raise RuntimeError("batcher is closed")
            if self._count >= self.config.max_queue:
                metrics.rejected.inc()
                metrics.rejected_by_cause.inc("backpressure")
                if metrics.windowed:
                    metrics.rejected_w.add(1.0)
                    metrics.bad_w.add(1.0)
                self.tracer.instant(
                    "rejected", "serve", request_id=request_id,
                    cause="backpressure", queue_depth=self._count,
                )
                self.recorder.record(
                    "request_reject", request_id, cause="backpressure",
                    queue_depth=self._count,
                )
                # Drain-time hint: tokens the queue still owes over the
                # recent token rate — a queue of heavy generations backs
                # clients off longer than the same depth of light ones.
                exc = Backpressure(drain_retry_after_s(
                    float(sum(
                        max(
                            1,
                            int(q.payload.get(
                                "max_new_tokens", self._default_max_new
                            )) - len(q.payload.get("resume_tokens", ())
                                     or ()),
                        )
                        for q in self._queue
                    )),
                    self.metrics.tokens_w.rate(10.0),
                    max(self.config.max_delay_ms / 1e3, 1e-3),
                ))
                exc.request_id = request_id
                raise exc
            pending = _Pending(payload, request_id,
                               self.config.default_priority)
            pending.future.request_id = request_id
            self._queue.append(pending)
            self._count += 1
            self._class_delta(pending.priority, +1)
            metrics.requests.inc()
            metrics.queue_depth.set(self._count)
            self._cv.notify_all()
        if metrics.windowed:
            metrics.requests_w.add(1.0)
        self.recorder.record("request_admit", request_id)
        return pending.future

    def adopt_chain(self, token_ids, pages_k=None, pages_v=None) -> Future:
        """Adopt a transferred KV-page chain into this batcher's prefix
        pool (serve/disagg.py decode role). Indexes ``token_ids``'s full
        blocks in the pool and — when ``pages_*`` stages are given
        (``[nl, max_chain, block_tokens, heads, head_dim]``, chain order)
        — scatters the received pages into the newly allocated blocks via
        the engine's AOT import cell. ``pages_* = None`` is the pool-only
        form for engines whose prefill is position-independent (sim
        engines; tests).

        Runs on the decode-loop thread BETWEEN steps (the import swaps
        the engine's pool refs, and the decode executable is never
        touched — no per-token dispatch joins the hot path); this call
        only enqueues and returns a Future resolving to the number of
        newly imported blocks (0 = chain already fully cached)."""
        fut: Future = Future()
        with self._cv:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if self._pool is None:
                raise RuntimeError(
                    "engine has no prefix cache to adopt a chain into"
                )
            self._adoptions.append((token_ids, pages_k, pages_v, fut))
            self._cv.notify_all()
        return fut

    def adopt_stream(self, state: StreamState, pages_k=None,
                     pages_v=None) -> Future:
        """Resume a migrated live stream here (serve/disagg.py receiver).

        With ``pages_*`` (``[nl, cache_len, heads, head_dim]`` stages —
        host numpy from the wire, device arrays from a local re-adopt)
        the stream enters a KV slot MID-GENERATION: the decode loop claims
        a free slot between steps, scatters the pages via the engine's
        slot-import cell, and the very next decode step continues the
        generation — no prefill, no re-computed tokens. Without pages it
        degrades to a page-less replay: the state's generated prefix
        re-enqueues as ``resume_tokens`` and the target re-prefills at
        absolute positions. Both paths are bit-identical to the
        uninterrupted stream by the (seed, position) sampling contract.

        Returns a Future resolving to the standard generate result with
        the FULL accumulated token list (resumed + newly generated)."""
        if pages_k is not None:
            if not getattr(self._engine, "stream_migrate", False):
                raise RuntimeError(
                    "engine built without stream_migrate=True (no "
                    "slot-import cell); retry page-less"
                )
            need = len(state.input_ids) + int(state.max_new_tokens)
            if need > self._cache_len:
                raise ValueError(
                    f"stream of {need} prompt+max_new tokens exceeds the "
                    f"{self._cache_len}-token cache pages here"
                )
            if state.length != len(state.input_ids) + len(state.tokens) - 1:
                raise ValueError(
                    f"stream length {state.length} inconsistent with "
                    f"{len(state.input_ids)} prompt + {len(state.tokens)} "
                    "generated tokens"
                )
            fut: Future = Future()
            fut.request_id = state.request_id
            with self._cv:
                if self._closed:
                    raise RuntimeError("batcher is closed")
                self._stream_adopts.append((state, pages_k, pages_v, fut))
                self._cv.notify_all()
            self.recorder.record(
                "stream_adopt", state.request_id,
                n_tokens=len(state.tokens), pages=True,
            )
            return fut
        fut = self.submit(state.replay_payload(),
                          request_id=state.request_id)
        self.recorder.record(
            "stream_adopt", state.request_id,
            n_tokens=len(state.tokens), pages=False,
        )
        return fut

    def export_streams(self, timeout_s: float = 30.0) -> list[ExportedStream]:
        """Checkpoint and REMOVE every live stream (occupied slots, queued
        requests, pending stream adoptions) for migration to a peer
        replica. Blocks while the decode loop stops dispatching, lets
        in-flight steps land (so every slot is settled — no donation
        races, no half-fetched tokens), then gathers each decoding slot's
        KV lane through the engine's slot-export cell. Streams that have
        no exportable pages (still prefilling, never admitted, or a
        pages-less engine) come back as page-less states that replay via
        ``resume_tokens``. The freed slots re-enter service immediately —
        callers own pushing the exports somewhere (serve/server.py
        ``/migratez``) and resolving each stream's victim-held future."""
        req = _ExportRequest()
        with self._cv:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if self._export_req is not None:
                raise RuntimeError("stream export already in progress")
            self._export_req = req
            self._cv.notify_all()
        if not req.event.wait(timeout_s):
            with self._cv:
                if self._export_req is req:
                    # Never picked up (loop wedged): withdraw the request.
                    self._export_req = None
                    raise TimeoutError(
                        f"stream export not serviced within {timeout_s:.0f}s"
                    )
            # Lost the race — the loop is mid-capture; give it a beat.
            if not req.event.wait(timeout_s):
                raise TimeoutError(
                    f"stream export not serviced within {2 * timeout_s:.0f}s"
                )
        return req.results

    def status(self) -> dict:
        metrics = self.metrics
        with self._cv:
            out = {
                "closed": self._closed,
                "mode": self._admission,
                "served": self._served,
                "queue_depth": self._count,
                "max_queue": self.config.max_queue,
                "in_flight": self._n_inflight,
                "max_in_flight": self.config.max_in_flight,
                "slots": len(self._slots),
                "slots_active": self._n_active,
                # Device bytes the active occupants' slot-table pages pin
                # (slots_active x the engine's per-slot share) — the same
                # number /memz accounts under kv_slot_cache, scaled to
                # live occupancy so the two surfaces agree.
                "kv_active_bytes": self._n_active * getattr(
                    self._engine, "slot_page_bytes", 0
                ),
                # Emitted tokens per decode/verify step completion: 1.0 on
                # a plain engine, >1 when speculation is winning.
                "tokens_per_step": (
                    self._tokens_emitted / self._steps
                    if self._steps else 0.0
                ),
                # Drain-progress estimate (/drainz, /statusz): tokens the
                # live occupants + queue still owe at worst case (every
                # stream runs to max_new). Operators and the router read
                # this to see why a drain is slow — and when to migrate
                # instead of waiting.
                "tokens_remaining": sum(
                    max(0, s.max_new - len(s.tokens))
                    for s in self._slots if s is not None
                ) + sum(
                    max(
                        1,
                        int(p.payload.get(
                            "max_new_tokens", self._default_max_new
                        )) - len(p.payload.get("resume_tokens", ()) or ()),
                    )
                    for p in self._queue
                ),
            }
            if self._pool is not None:
                # KV-pressure digest for /statusz + the fleet view: pool
                # occupancy and lifetime hit rate (lock order _cv -> pool,
                # same as admission's trie match).
                st = self._pool.stats()
                lookups = metrics.prefix_lookups.value
                out["prefix_cache"] = {
                    "blocks": st["blocks"],
                    "blocks_used": st["blocks_used"],
                    "bytes_used": st["bytes_used"],
                    "capacity_bytes": st["capacity_bytes"],
                    "evictions": st["evictions"],
                    "lookups": lookups,
                    "hits": metrics.prefix_hits.value,
                    "hit_rate": (
                        metrics.prefix_hits.value / lookups
                        if lookups else 0.0
                    ),
                    "tokens_saved": metrics.prefix_tokens_saved.value,
                }
            if self._spec_k:
                # Speculation digest for /statusz: per-mode verify width,
                # live acceptance EMA across occupants, lifetime totals.
                digests = [
                    s.spec.digest() for s in self._slots
                    if s is not None and s.spec is not None
                ]
                backed = sum(1 for d in digests if d["backed_off"])
                emas = [d["acceptance_ema"] for d in digests]
                out["speculation"] = {
                    "spec_tokens": self._spec_k,
                    "min_match": self._spec_cfg.min_match,
                    # Verify width by slot mode: full speculation drafts
                    # k tokens, a backed-off slot runs plain decode (k=0).
                    "mode_k": {"speculating": self._spec_k, "backed_off": 0},
                    "slots_speculating": len(digests) - backed,
                    "slots_backed_off": backed,
                    "acceptance_ema": (
                        sum(emas) / len(emas) if emas else 1.0
                    ),
                    "draft_tokens": self._spec_drafted,
                    "accepted_tokens": self._spec_accepted,
                    "rejects": self._spec_rejects,
                    "acceptance_rate": (
                        self._spec_accepted / self._spec_drafted
                        if self._spec_drafted else 0.0
                    ),
                }
            # Priority-scheduling digest for /statusz + the fleet view:
            # policy knobs, per-class queue depth and slot occupancy, and
            # lifetime park / resume / aborted-park totals.
            classes: dict[int, dict] = {}
            for pri, n in self._class_queued.items():
                classes.setdefault(pri, {"queued": 0, "active": 0})
                classes[pri]["queued"] = n
            preempting_now = 0
            for s in self._slots:
                if s is None:
                    continue
                pri = s.pending.priority
                classes.setdefault(pri, {"queued": 0, "active": 0})
                classes[pri]["active"] += 1
                if s.preempting:
                    preempting_now += 1
            out["sched"] = {
                "policy": self.config.sched,
                "preempt": self.config.preempt,
                "preempt_margin_ms": self.config.preempt_margin_ms,
                "classes": {str(k): v for k, v in sorted(classes.items())},
                "preempting_now": preempting_now,
                "parked_waiting": sum(1 for q in self._queue if q.preempted),
                "preempt_parked": self._preempt_parked,
                "preempt_resumed": self._preempt_resumed,
                "preempt_aborted": self._preempt_aborted,
            }
            return out

    # --------------------------------------------------------- decode loop

    def _class_delta(self, priority: int, d: int) -> None:
        """Queue-change bookkeeping for one priority class (under ``_cv``):
        keeps the per-class counts and the ``serve_sched_queue_depth``
        gauge in lockstep with the queue itself."""
        n = self._class_queued.get(priority, 0) + d
        if n <= 0:
            self._class_queued.pop(priority, None)
            n = 0
        else:
            self._class_queued[priority] = n
        self.metrics.sched_queue_depth.set(str(priority), n)

    def _clear_queue_classes(self) -> None:
        """Zero every per-class gauge after a bulk queue strip (stream
        export, non-drain close)."""
        for pri in list(self._class_queued):
            self.metrics.sched_queue_depth.set(str(pri), 0)
        self._class_queued.clear()

    def _pop_next_locked(self) -> _Pending:
        """Take the next admission from the queue under the configured
        policy. FIFO pops the head; EDF scans for the most urgent entry —
        lowest priority class first, earliest deadline within the class
        (deadline-less entries sort behind every deadline holder), FIFO
        order as the final tie-break. O(queue) per admission, bounded by
        ``max_queue``."""
        if self.config.sched == "fifo" or len(self._queue) == 1:
            p = self._queue.popleft()
        else:
            best_ix, best_key = 0, None
            for ix, q in enumerate(self._queue):
                key = (
                    q.priority,
                    q.deadline_abs if q.deadline_abs is not None
                    else float("inf"),
                    q.t_enqueue,
                )
                if best_key is None or key < best_key:
                    best_key, best_ix = key, ix
            p = self._queue[best_ix]
            del self._queue[best_ix]
        self._class_delta(p.priority, -1)
        return p

    def _steppable(self, s: _Slot | None) -> bool:
        """Include the slot in the next decode step? Occupied, fully
        prefilled, and not every requested token already dispatched (a
        slot whose last tokens are still in flight rides along inactive
        until they fetch). A slot with a verify step in flight is parked
        until the verdict lands, and a preemption victim stops taking new
        steps so its in-flight work can settle and park."""
        return (
            s is not None
            and not s.prefilling
            and not s.verifying
            and not s.preempting
            and s.n_dispatched < s.max_new
        )

    def _take_work(self):
        """Block until there is something to dispatch; returns ``("work",
        admissions, chunk_rows, step, verify, adopts, stream_rows,
        park_rows)`` — any may be empty/None — or ``("export", ...)`` when
        a stream export quiesced, or None when closed and fully drained.
        All bookkeeping (slot assignment, trie match, chunk/length
        advance, draft assembly, preemption mark/park) happens HERE under
        ``_cv``; the caller just dispatches.

        On a chunked engine an admission does NOT dispatch a prefill:
        the slot enters ``prefilling`` (its prompt possibly shortened by a
        pinned prefix-cache match) and each loop iteration plans at most
        ONE chunk batch — up to ``admit_cap`` rows, one ``chunk_size``
        slice each — followed by a decode step over the fully-prefilled
        slots. That interleaving is what bounds decode ITL during
        long-prompt admission to one chunk's compute.

        On a speculative engine each iteration additionally plans at most
        ONE verify batch covering every speculating slot that has a
        non-empty draft and no outstanding steps (spec-mode slots advance
        at fetch, so in-order slots always satisfy ``n_dispatched ==
        len(tokens)``; a slot with a draft in hand waits for its
        pipelined plain steps to drain first). Empty-draft and backed-off
        slots keep riding the plain pipelined decode step — speculation
        only ever trades pipelining for verify width when the drafter
        actually has a proposal."""
        metrics = self.metrics
        with self._cv:
            while True:
                if (
                    self._closed
                    and not self._queue
                    and not self._stream_adopts
                    and self._n_active == 0
                ):
                    while self._adoptions:
                        *_, fut = self._adoptions.popleft()
                        if not fut.cancelled():
                            fut.set_exception(
                                RuntimeError("batcher closed")
                            )
                    if self._export_req is not None:
                        # Nothing left to export — unblock the waiter.
                        req = self._export_req
                        self._export_req = None
                        req.event.set()
                    return None
                if self._export_req is not None:
                    # Stream export pending: stop dispatching and let the
                    # in-flight steps land, so every slot is SETTLED
                    # (tokens fetched, lengths final, no donation in
                    # flight) when the capture runs.
                    if self._n_inflight:
                        self._cv.wait()
                        continue
                    req = self._export_req
                    self._export_req = None
                    exported = []
                    for i, s in enumerate(self._slots):
                        if s is None:
                            continue
                        self._slots[i] = None
                        self._n_active -= 1
                        if self._pool is not None and s.chain is not None:
                            self._pool.release(s.chain)  # idempotent unpin
                        exported.append((i, s))
                    queued = list(self._queue)
                    self._queue.clear()
                    self._clear_queue_classes()
                    adopts_q = list(self._stream_adopts)
                    self._stream_adopts.clear()
                    self._count = 0
                    metrics.queue_depth.set(0)
                    metrics.slots_active.set(self._n_active)
                    return ("export", req, exported, queued, adopts_q)
                # Chain adoptions drain first — a popped adoption's pool
                # insert + page import runs before the NEXT pass's trie
                # matches, so admissions planned after this pass can hit
                # the transferred chain.
                adopts = []
                while self._adoptions:
                    adopts.append(self._adoptions.popleft())
                # Migrated streams claim free slots BEFORE fresh
                # admissions — they are the oldest work in the house, and
                # their slot-import dispatch precedes everything else this
                # pass plans, so the decode step planned below can already
                # include them.
                stream_rows = []
                while self._stream_adopts:
                    free_ix = next(
                        (i for i, s in enumerate(self._slots)
                         if s is None),
                        None,
                    )
                    if free_ix is None:
                        break
                    state, pk, pv, fut = self._stream_adopts.popleft()
                    now = time.monotonic()
                    pend = _Pending(state.replay_payload(),
                                    state.request_id)
                    pend.future = fut
                    pend.t_taken = now
                    slot = _Slot(pend, next(self._gens), pend.payload,
                                 self._default_max_new)
                    # Mid-generation occupant: its pages land via the
                    # slot-import cell (no prefill), so the next decode
                    # step continues at the stream's absolute position.
                    slot.length = state.length
                    slot.n_dispatched = len(slot.tokens)
                    slot.t_first = now
                    slot.t_last_tok = now
                    if self._spec_k:
                        # Fresh SlotSpec: spec state resets cleanly on
                        # migration (drafting history is rebuilt from
                        # prompt + accumulated tokens, EMA starts over).
                        slot.spec = SlotSpec(self._spec_cfg)
                        slot.prompt_ids = [
                            int(t) for t in state.input_ids
                        ]
                    slot.slot_id = free_ix
                    self._slots[free_ix] = slot
                    self._n_active += 1
                    stream_rows.append((free_ix, slot, pk, pv))
                if stream_rows:
                    metrics.slots_active.set(self._n_active)
                # -------------------------------------- priority preemption
                # MARK: when a queued deadline holder would miss its
                # deadline waiting for a natural slot free, pick a strictly
                # lower-priority occupant per uncovered urgent waiter and
                # flag it. A marked victim takes no further chunk/verify/
                # decode dispatches (see _steppable); it PARKS below once
                # its in-flight steps settle. Already-marked and exempt
                # slots count as arriving capacity, so one waiter never
                # marks the whole table.
                if self.config.preempt and self._queue:
                    free_n = sum(1 for s in self._slots if s is None)
                    marked_n = sum(
                        1 for s in self._slots
                        if s is not None and s.preempting
                    )
                    now = time.monotonic()
                    margin = self.config.preempt_margin_ms / 1e3
                    urgent = sorted(
                        (q for q in self._queue
                         if q.deadline_abs is not None
                         and now + margin >= q.deadline_abs),
                        key=lambda q: (q.priority, q.deadline_abs,
                                       q.t_enqueue),
                    )
                    need = len(urgent) - free_n - marked_n
                    for w in urgent:
                        if need <= 0:
                            break
                        victim = None
                        for s in self._slots:
                            if (
                                s is None
                                or s.preempting
                                or s.preempt_exempt
                                or s.pending.priority <= w.priority
                            ):
                                continue
                            # Lowest-urgency class first; within it, the
                            # occupant with the least generated progress
                            # (cheapest park + re-prefill round trip).
                            if victim is None or (
                                s.pending.priority,
                                -len(s.tokens),
                            ) > (
                                victim.pending.priority,
                                -len(victim.tokens),
                            ):
                                victim = s
                        if victim is None:
                            continue
                        victim.preempting = True
                        need -= 1
                # PARK: settle-and-evict every marked victim whose steps
                # have landed. The victim's client future survives — its
                # _Pending re-enqueues with the generated tokens as
                # resume_tokens (the PR 18 replay contract: bit-identical
                # by (seed, absolute position) sampling) — and, when the
                # prefix pool can hold the full settled sequence, the
                # slot's KV lane publishes into parked pool pages first so
                # the resume's re-prefill is a near-pure cache hit. A pool
                # too full to cover the whole parked sequence ABORTS the
                # preemption instead (the victim finishes; it is never
                # lost) — re-prefilling against garbage or half-parked
                # pages is how bit-parity dies.
                park_rows = []
                if self.config.preempt:
                    for i, s in enumerate(self._slots):
                        if s is None or not s.preempting:
                            continue
                        if s.prefilling:
                            # Mid-prefill victims park page-less NOW: any
                            # in-flight chunk's completion drops on the
                            # gen tag, nothing generated is lost (tokens
                            # == the resume prefix it arrived with), and
                            # the pinned prefix match unpins below.
                            settled = True
                        else:
                            settled = (
                                not s.verifying
                                and s.n_dispatched == len(s.tokens)
                            )
                        if not settled:
                            continue
                        p = s.pending
                        reason, new_blocks = "pageless", []
                        if (
                            not s.prefilling
                            and s.tokens
                            and self._pool is not None
                            and callable(getattr(
                                self._engine, "insert_prefix", None
                            ))
                        ):
                            # Settled lane covers positions 0..length-1
                            # (the newest token's KV is written by the
                            # step that was never dispatched).
                            key = (
                                s.full_prompt + s.tokens[len(s.resume):]
                            )[: s.length]
                            cap = getattr(self._engine, "_max_chain", None)
                            if cap is not None:
                                key = key[: cap * self._pool.block_tokens]
                            want = len(key) // self._pool.block_tokens
                            if want > 0:
                                # Lock order _cv -> pool, same as the
                                # admission trie match.
                                new_blocks, covered = self._pool.index(key)
                                if covered >= want:
                                    reason = "paged"
                                else:
                                    # Park-pool-full: whatever prefix DID
                                    # index still gets its page copy below
                                    # (it is valid data the pool now
                                    # advertises), but the victim keeps
                                    # its slot and finishes. Exempt, so
                                    # the next pass marks someone else.
                                    s.preempting = False
                                    s.preempt_exempt = True
                                    self._preempt_aborted += 1
                                    park_rows.append(
                                        ("abort", i, s, "park_full",
                                         new_blocks)
                                    )
                                    continue
                        if reason == "pageless" and not self._chunked:
                            # Monolithic prefill buckets the resumed
                            # prompt (original + every generated token);
                            # an un-bucketable resume cannot replay here.
                            try:
                                self._engine.bucket_for(
                                    s.prompt_len + len(s.tokens)
                                )
                            except Exception:  # noqa: BLE001
                                s.preempting = False
                                s.preempt_exempt = True
                                self._preempt_aborted += 1
                                park_rows.append(
                                    ("abort", i, s, "bucket_overflow", [])
                                )
                                continue
                        pl = dict(p.payload)
                        if s.tokens:
                            pl["resume_tokens"] = [int(t) for t in s.tokens]
                        p.payload = pl
                        p.preempted += 1
                        self._slots[i] = None
                        self._n_active -= 1
                        if self._pool is not None and s.chain is not None:
                            self._pool.release(s.chain)  # idempotent unpin
                        self._queue.append(p)
                        self._count += 1
                        self._class_delta(p.priority, +1)
                        self._preempt_parked += 1
                        park_rows.append(("park", i, s, reason, new_blocks))
                    if park_rows:
                        metrics.queue_depth.set(self._count)
                        metrics.slots_active.set(self._n_active)
                admissions = []
                free = [
                    i for i, s in enumerate(self._slots) if s is None
                ]
                may_admit = self._queue and free and (
                    self._admission == "continuous" or self._n_active == 0
                )
                if may_admit:
                    now = time.monotonic()
                    for slot_id in free[: min(len(self._queue),
                                              self._admit_cap)]:
                        p = self._pop_next_locked()
                        self._count -= 1
                        if p.preempted:
                            self._preempt_resumed += 1
                        p.t_taken = now  # queue_wait phase ends here
                        slot = _Slot(
                            p, next(self._gens), p.payload,
                            self._default_max_new,
                        )
                        if self._chunked:
                            slot.prefilling = True
                            if self._pool is not None:
                                # Lock order _cv -> pool (never reversed);
                                # the match pins its chain until the
                                # gather chunk dispatches. A resumed
                                # stream matches on its FULL effective
                                # prompt (original + resume tokens).
                                m = self._pool.match(slot.full_prompt)
                                slot.chain = m
                                slot.cached_len = m.cached_len
                                metrics.prefix_lookups.inc()
                                if m.cached_len:
                                    metrics.prefix_hits.inc()
                                    metrics.prefix_tokens_saved.inc(
                                        m.cached_len
                                    )
                            slot.chunk_pos = slot.cached_len
                        else:
                            # Prefill's first sampled token (resumed
                            # tokens are pre-seeded, not dispatched).
                            slot.n_dispatched = len(slot.tokens) + 1
                        if self._spec_k:
                            slot.spec = SlotSpec(self._spec_cfg)
                            slot.prompt_ids = [
                                int(t) for t in p.payload["input_ids"]
                            ]
                        slot.slot_id = slot_id
                        self._slots[slot_id] = slot
                        self._n_active += 1
                        admissions.append((slot_id, slot))
                    metrics.queue_depth.set(self._count)
                    metrics.slots_active.set(self._n_active)
                chunk_rows = None
                if self._chunked:
                    planned = []
                    for i, s in enumerate(self._slots):
                        if s is None or not s.prefilling:
                            continue
                        if len(planned) >= self._admit_cap:
                            break
                        start = s.chunk_pos
                        n = min(self._chunk_size, s.admit_len - start)
                        s.chunk_pos = start + n
                        final = s.chunk_pos >= s.admit_len
                        first = start == s.cached_len
                        if final:
                            s.prefilling = False
                            # First token rides the final chunk (resumed
                            # tokens are pre-seeded, not dispatched).
                            s.n_dispatched = len(s.tokens) + 1
                        planned.append(
                            (i, s, start, n, first, final)
                        )
                    if planned:
                        chunk_rows = planned
                verify = None
                spec_plain: set[int] = set()
                if self._spec_k:
                    # One verify batch over every speculating slot.
                    # Drafting happens here under _cv (the drafter is a
                    # pure function of slot state). A slot whose draft
                    # comes up EMPTY takes a plain (pipelined) decode row
                    # this step instead — a k=0 verify would just be a
                    # non-overlapped decode step — and the missed
                    # opportunity feeds the acceptance EMA so undraftable
                    # streams back off entirely WITHOUT ever paying the
                    # drain stall: only a slot with a draft actually
                    # worth verifying waits for its in-flight plain
                    # steps to land (and re-drafts against the full
                    # history once they have).
                    vrows = []
                    for i, s in enumerate(self._slots):
                        if (
                            not self._steppable(s)
                            or s.spec is None
                            or not s.spec.speculating
                            # Prefill token still in flight: drafts anchor
                            # on the GENERATED history (the match that
                            # matters most appears right after the first
                            # token), so don't burn the step on a
                            # prompt-only draft — wait the one fetch.
                            or not s.tokens
                        ):
                            continue
                        # Never draft past the generation budget (the
                        # verified token always emits, so at most
                        # max_new - emitted - 1 drafts can matter) or
                        # the cache (positions length..length+d must
                        # stay writable).
                        cap = min(
                            s.max_new - len(s.tokens) - 1,
                            self._cache_len - 1 - s.length,
                        )
                        d = s.spec.propose(s.prompt_ids + s.tokens, cap)
                        if not d:
                            flip = s.spec.record(0, 0)
                            if flip is not None:
                                self._plan_events.append((
                                    s.pending.request_id, i, flip,
                                    s.spec.ema,
                                ))
                            spec_plain.add(i)
                            continue
                        if s.n_dispatched != len(s.tokens):
                            # Draft in hand but plain steps still in
                            # flight: stall one pass to drain (history
                            # is missing the in-flight tokens, so the
                            # draft re-proposes once they land).
                            continue
                        vrows.append((i, s, d))
                    if vrows:
                        n = len(self._slots)
                        drafts = [[0] * self._spec_k for _ in range(n)]
                        vlengths = [0] * n
                        n_input = [0] * n
                        vtemps = [0.0] * n
                        vseeds = [0] * n
                        vtags = []
                        for i, s, d in vrows:
                            drafts[i][: len(d)] = [int(t) for t in d]
                            vlengths[i] = s.length
                            n_input[i] = len(d) + 1
                            vtemps[i] = s.temperature
                            vseeds[i] = s.seed
                            s.draft = d
                            s.verifying = True  # length advances at FETCH
                            vtags.append((i, s.gen))
                        verify = (
                            drafts, vlengths, n_input, vtemps, vseeds, vtags
                        )
                step = None
                rows = [
                    (i, s) for i, s in enumerate(self._slots)
                    if self._steppable(s)
                    # Spec-mode slots route through verify (a probe-due
                    # backed-off slot drains here too) unless this step's
                    # draft came up empty; backed-off and empty-draft
                    # slots ride the pipelined plain path.
                    and (
                        s.spec is None
                        or not s.spec.speculating
                        or i in spec_plain
                    )
                ]
                if rows:
                    n = len(self._slots)
                    lengths = [0] * n
                    active = [False] * n
                    temps = [0.0] * n
                    seeds = [0] * n
                    tags = []
                    for i, s in rows:
                        lengths[i] = s.length
                        active[i] = True
                        temps[i] = s.temperature
                        seeds[i] = s.seed
                        s.length += 1         # advances at dispatch: steps
                        s.n_dispatched += 1   # pipeline without the fetch
                        tags.append((i, s.gen))
                        if s.spec is not None:
                            s.spec.note_plain_step()  # probe clock
                    step = (lengths, active, temps, seeds, tags)
                if (admissions or chunk_rows or step or verify or adopts
                        or stream_rows or park_rows):
                    return ("work", admissions, chunk_rows, step, verify,
                            adopts, stream_rows, park_rows)
                self._cv.wait()

    def _fail_slots(self, tagged: list[tuple[int, int]],
                    exc: BaseException) -> None:
        """Fail + free the (slot, gen) occupants (engine dispatch/fetch
        blew up under them)."""
        metrics = self.metrics  # local: instruments carry their own locks
        victims = []
        with self._cv:
            for slot_id, gen in tagged:
                s = self._slots[slot_id]
                if s is None or s.gen != gen:
                    continue
                self._slots[slot_id] = None
                self._n_active -= 1
                if self._pool is not None and s.chain is not None:
                    self._pool.release(s.chain)  # idempotent unpin
                victims.append((slot_id, s.pending))
            metrics.slots_active.set(self._n_active)
            self._cv.notify_all()
        if not victims:
            return
        metrics.errors.inc()
        metrics.rejected_by_cause.inc("engine_failure", len(victims))
        if metrics.windowed:
            metrics.bad_w.add(float(len(victims)))
        for slot_id, p in victims:
            self.tracer.instant(
                "engine_failure", "serve", request_id=p.request_id,
                error=type(exc).__name__,
            )
            self.recorder.record(
                "engine_failure", p.request_id, slot=slot_id,
                error=type(exc).__name__,
            )
            self.recorder.record("slot_free", p.request_id, slot=slot_id,
                                 cause="engine_failure")
            if not p.future.cancelled():
                p.future.set_exception(exc)
        logger.warning(
            "decode dispatch failed (%s): request_ids=%s",
            type(exc).__name__, [p.request_id for _, p in victims],
        )
        self.recorder.trigger("engine_failure")

    def _loop(self):
        engine = self._engine
        while True:
            work = self._take_work()
            if work is None:
                self._completion.put(None)  # unblock the fetch thread
                return
            if work[0] == "export":
                _, req, exported, queued, adopts_q = work
                self._service_export(req, exported, queued, adopts_q)
                continue
            (_, admissions, chunk_rows, step, verify, adopts, stream_rows,
             park_rows) = work
            if stream_rows:
                # Slot-page import dispatches FIRST: the adopted slots may
                # already ride this pass's verify/decode step, and stream
                # order guarantees their lanes hold the migrated KV before
                # anything reads them.
                for slot_id, s, pk, pv in stream_rows:
                    try:
                        engine.import_slot_pages(
                            slot_id, pk, pv, int(s.tokens[-1])
                        )
                    except Exception as e:  # noqa: BLE001 — fail the stream, not the loop
                        self._fail_slots([(slot_id, s.gen)], e)
                        continue
                    self.recorder.record(
                        "slot_alloc", s.pending.request_id, slot=slot_id,
                        prompt_len=s.prompt_len, migrated=True,
                    )
            if adopts:
                # Between-steps adoption (serve/disagg.py): index the
                # chain in the pool, then scatter received pages into the
                # freshly allocated blocks BEFORE anything else this pass
                # dispatches — the import is in the stream ahead of any
                # later chunk that could gather those blocks, so the
                # kvpool publish-before-match contract holds.
                for token_ids, pages_k, pages_v, fut in adopts:
                    try:
                        new = self._pool.insert(token_ids)
                        if new and pages_k is not None:
                            engine.import_prefix_pages(new, pages_k, pages_v)
                        self.metrics.kv_pool_bytes.set(
                            self._pool.stats()["bytes_used"]
                        )
                    except Exception as e:  # noqa: BLE001 — fail the adoption, not the loop
                        if not fut.cancelled():
                            fut.set_exception(e)
                    else:
                        if not fut.cancelled():
                            fut.set_result(len(new))
            if park_rows:
                # Park-publish dispatches BEFORE any admission prefill or
                # chunk gather this pass: insert_prefix copies the parked
                # victim's lane pages into its freshly indexed pool blocks,
                # and stream order guarantees the copy reads the lane (and
                # fills the blocks a same-pass re-admission may already
                # have matched) before anything overwrites or gathers
                # them. Bookkeeping already happened under _cv.
                for what, slot_id, s, reason, new_blocks in park_rows:
                    if new_blocks:
                        try:
                            engine.insert_prefix(slot_id, new_blocks)
                        except Exception:  # noqa: BLE001 — pool keeps the
                            # blocks; their bytes are stale, so drop them
                            # from the trie rather than serve garbage.
                            logger.exception(
                                "park-publish of slot %d failed; evicting "
                                "the parked chain", slot_id,
                            )
                            self._pool.forget(
                                (s.full_prompt + s.tokens[len(s.resume):])
                                [: s.length]
                            )
                        else:
                            self.metrics.kv_pool_bytes.set(
                                self._pool.stats()["bytes_used"]
                            )
                    if what == "park":
                        self.metrics.preemptions.inc(reason)
                        self.recorder.record(
                            "slot_preempt", s.pending.request_id,
                            slot=slot_id, reason=reason,
                            n_tokens=len(s.tokens),
                            parked_blocks=len(new_blocks),
                        )
                    else:
                        self.metrics.preemptions.inc(reason)
                        self.recorder.record(
                            "slot_preempt", s.pending.request_id,
                            slot=slot_id, reason=reason, aborted=True,
                            n_tokens=len(s.tokens),
                        )
            if self._plan_events:
                # Backoff flips noted while planning (same thread, so no
                # lock needed); recorded here, outside _cv.
                for req_id, slot_id, flip, ema in self._plan_events:
                    self.recorder.record(
                        "spec_backoff", req_id, slot=slot_id,
                        engaged=(flip == "engage"),
                        acceptance=round(ema, 4),
                    )
                self._plan_events.clear()
            if admissions:
                self.metrics.batches.inc()
                self.metrics.batch_occupancy.observe(len(admissions))
                if self.recorder.enabled:
                    # Outside _cv: _take_work already published the slots.
                    for i, s in admissions:
                        self.recorder.record(
                            "slot_alloc", s.pending.request_id,
                            slot=i, prompt_len=s.prompt_len,
                        )
                        if s.pending.preempted:
                            self.recorder.record(
                                "slot_resume", s.pending.request_id,
                                slot=i, rounds=s.pending.preempted,
                                resume_tokens=len(s.resume),
                                cached_tokens=s.cached_len,
                            )
                        if s.cached_len:
                            self.recorder.record(
                                "prefix_hit", s.pending.request_id,
                                slot=i, cached_tokens=s.cached_len,
                            )
            if admissions and not self._chunked:
                self._inflight_sem.acquire()
                tags = [(i, s.gen) for i, s in admissions]
                try:
                    handle = engine.prefill([
                        {
                            "slot": i,
                            "input_ids": s.full_prompt,
                            "temperature": s.temperature,
                            "seed": s.seed,
                        }
                        for i, s in admissions
                    ])
                except Exception as e:  # noqa: BLE001 — fail the rows, not the server
                    # Fail ONLY the admitted rows; the step planned below
                    # still dispatches (its bookkeeping already advanced,
                    # and the failed slots' lanes are dead via the gen tag).
                    self._inflight_sem.release()
                    self._fail_slots(tags, e)
                else:
                    with self._cv:
                        self._n_inflight += 1
                        self.metrics.in_flight.set(self._n_inflight)
                    self._completion.put(
                        ("prefill", tags, handle, time.monotonic())
                    )
            if chunk_rows:
                self._inflight_sem.acquire()
                tags = [(i, s.gen) for i, s, *_ in chunk_rows]
                try:
                    handle = engine.prefill_chunks([
                        {
                            "slot": i,
                            "input_ids": s.full_prompt,
                            "start": start,
                            "n_tokens": n,
                            "length": s.admit_len,
                            "chain": (
                                s.chain.blocks
                                if first and s.chain is not None else ()
                            ),
                            "temperature": s.temperature,
                            "seed": s.seed,
                        }
                        for i, s, start, n, first, final in chunk_rows
                    ])
                except Exception as e:  # noqa: BLE001
                    self._inflight_sem.release()
                    self._fail_slots(tags, e)
                else:
                    with self._cv:
                        self._n_inflight += 1
                        self.metrics.in_flight.set(self._n_inflight)
                    self._completion.put(
                        (
                            "chunk",
                            [
                                (i, s.gen, final)
                                for i, s, _, _, _, final in chunk_rows
                            ],
                            handle,
                            time.monotonic(),
                        )
                    )
                    # Prefix bookkeeping AFTER the dispatch is enqueued:
                    # the gather is in the stream, so pins drop (a later
                    # insert may evict + rewrite those pages — stream
                    # order keeps the gather reading the old bytes), and
                    # a final chunk's completed pages publish to the pool.
                    if self._pool is not None:
                        touched = False
                        for i, s, start, n, first, final in chunk_rows:
                            if first and s.chain is not None:
                                self._pool.release(s.chain)
                            if final:
                                # A resumed stream's effective prompt
                                # (prompt + resume_tokens) can run past
                                # the engine's publishable chain; publish
                                # the longest prefix the insert cell
                                # carries rather than raise on the loop
                                # thread.
                                key = s.full_prompt
                                cap = getattr(engine, "_max_chain", None)
                                if cap is not None:
                                    key = key[
                                        : cap * self._pool.block_tokens
                                    ]
                                new = self._pool.insert(key)
                                if new:
                                    engine.insert_prefix(i, new)
                                touched = True
                        if touched:
                            self.metrics.kv_pool_bytes.set(
                                self._pool.stats()["bytes_used"]
                            )
            if verify:
                # Dispatched BEFORE the decode step: the planned verify
                # rows are parked (verifying=True) and would wedge if a
                # decode failure's `continue` skipped their dispatch.
                drafts, vlengths, n_input, vtemps, vseeds, vtags = verify
                self._inflight_sem.acquire()
                try:
                    handle = engine.verify(
                        drafts, vlengths, n_input, vtemps, vseeds
                    )
                except Exception as e:  # noqa: BLE001
                    self._inflight_sem.release()
                    self._fail_slots(vtags, e)
                else:
                    with self._cv:
                        self._n_inflight += 1
                        self.metrics.in_flight.set(self._n_inflight)
                    self._completion.put(
                        ("verify", vtags, handle, time.monotonic())
                    )
            if step:
                lengths, active, temps, seeds, tags = step
                inj = self.fault_injector
                if inj is not None:
                    # Chaos hooks fire on the decode-step DISPATCH clock
                    # (serve/faultinject.py): slow_decode_step sleeps
                    # here, replica_kill dumps + SIGKILLs, dispatch_error
                    # raises and the step's slots fail like a real engine
                    # blow-up.
                    self._dispatched_steps += 1
                    try:
                        inj.on_decode_step(self._dispatched_steps)
                    except Exception as e:  # noqa: BLE001 — injected: fail the step's slots
                        self._fail_slots(tags, e)
                        continue
                self._inflight_sem.acquire()
                try:
                    handle = engine.decode(lengths, active, temps, seeds)
                except Exception as e:  # noqa: BLE001
                    self._inflight_sem.release()
                    self._fail_slots(tags, e)
                    continue
                with self._cv:
                    self._n_inflight += 1
                    self.metrics.in_flight.set(self._n_inflight)
                self._completion.put(
                    ("decode", tags, handle, time.monotonic())
                )

    def _service_export(self, req: _ExportRequest, exported, queued,
                        adopts_q) -> None:
        """Decode-loop thread: turn the quiesced occupants into
        :class:`ExportedStream` records — gathering each settled decoding
        slot's KV lane through the engine's AOT slot-export cell — then
        wake the ``export_streams`` caller. Streams without exportable
        pages (still prefilling, queued, or a migration-less engine)
        export as page-less states that replay via ``resume_tokens``."""
        engine = self._engine
        can_pages = getattr(engine, "stream_migrate", False)
        out: list[ExportedStream] = []
        for slot_id, s in exported:
            p = s.pending
            state = StreamState(
                request_id=p.request_id,
                input_ids=[int(t) for t in p.payload["input_ids"]],
                tokens=list(s.tokens),
                seed=s.seed,
                temperature=s.temperature,
                eos_id=s.eos_id,
                max_new_tokens=s.max_new,
                length=s.length,
            )
            pk = pv = None
            if can_pages and not s.prefilling and s.tokens:
                try:
                    pk, pv = engine.export_slot_pages(slot_id)
                except Exception:  # noqa: BLE001 — degrade to page-less replay
                    logger.exception(
                        "slot %d page export failed; stream %s migrates "
                        "page-less", slot_id, p.request_id,
                    )
                    pk = pv = None
            if pk is None:
                state.length = 0  # page-less: the replay re-prefills
            out.append(ExportedStream(state, pk, pv, p.future))
            self.recorder.record(
                "stream_export", p.request_id, slot=slot_id,
                n_tokens=len(s.tokens), pages=pk is not None,
            )
        for p in queued:
            pl = p.payload
            eos = pl.get("eos_id")
            state = StreamState(
                request_id=p.request_id,
                input_ids=[int(t) for t in pl["input_ids"]],
                tokens=[int(t) for t in pl.get("resume_tokens", ())],
                seed=int(pl.get("seed", 0)),
                temperature=float(pl.get("temperature", 0.0)),
                eos_id=None if eos is None else int(eos),
                max_new_tokens=int(
                    pl.get("max_new_tokens", self._default_max_new)
                ),
            )
            out.append(ExportedStream(state, None, None, p.future))
            self.recorder.record(
                "stream_export", p.request_id, queued=True, pages=False,
            )
        for state, pk, pv, fut in adopts_q:
            # A migrated-in stream caught mid-handoff migrates onward
            # with the pages it arrived with.
            out.append(ExportedStream(state, pk, pv, fut))
            self.recorder.record(
                "stream_export", state.request_id, queued=True,
                pages=pk is not None,
            )
        req.results = out
        req.event.set()

    # ---------------------------------------------------------- completion

    def _append_token(self, slot_id: int, s: _Slot, token: int,
                      t_got: float, finished: list) -> None:
        """Record one fetched token; on eos/max_new, resolve the future and
        free the slot IMMEDIATELY (in-flight steps for the old occupant are
        dropped by the gen tag; their cache writes are dead stores)."""
        s.tokens.append(token)
        s.t_last_tok = t_got
        done = (
            len(s.tokens) >= s.max_new
            or (s.eos_id is not None and token == s.eos_id)
        )
        if done:
            self._slots[slot_id] = None
            self._n_active -= 1
            if self._pool is not None and s.chain is not None:
                self._pool.release(s.chain)  # idempotent: normally
            finished.append(s)               # already unpinned at dispatch

    def _resolve(self, finished: list[_Slot], now: float) -> None:
        """Resolve finished occupants' futures outside ``_cv`` with the
        DynamicBatcher delivery contract: contiguous phases, exact
        ``latency_s``, batch-held metric locks, metrics before futures."""
        metrics, tracer = self.metrics, self.tracer
        latencies = []
        phase_values: dict[str, list[float]] = {}
        for s in finished:
            p = s.pending
            latency = now - p.t_enqueue
            metrics.latency.observe(latency)
            latencies.append(latency)
            p.future.latency_s = latency
            phases = {
                "queue_wait": p.t_taken - p.t_enqueue,
                "prefill": s.t_first - p.t_taken,
                "decode": now - s.t_first,
            }
            for name, dt in phases.items():
                phase_values.setdefault(name, []).append(dt)
            p.future.phases = phases
            tracer.record("request", p.t_enqueue, now, cat="serve",
                          request_id=p.request_id)
            tracer.record("queue_wait", p.t_enqueue, p.t_taken, cat="serve",
                          request_id=p.request_id)
            tracer.record("prefill", p.t_taken, s.t_first, cat="serve",
                          request_id=p.request_id)
            tracer.record("decode", s.t_first, now, cat="serve",
                          request_id=p.request_id)
        for name, vals in phase_values.items():
            metrics.observe_phase_batch(name, vals, self._layout, now)
        if metrics.windowed:
            metrics.latency_w.observe_many(latencies, now)
            metrics.ok_w.add(float(len(finished)), now)
        for s in finished:
            p = s.pending
            if not p.future.cancelled():
                p.future.set_result({
                    "tokens": list(s.tokens),
                    "n_tokens": len(s.tokens),
                    "prompt_len": s.prompt_len,
                    "bucket": self._engine.bucket_for(s.prompt_len),
                })
        with self._cv:
            self._served += len(finished)
        if self.recorder.enabled:
            for s in finished:
                self.recorder.record("slot_free", s.pending.request_id,
                                     slot=s.slot_id)
                self.recorder.record(
                    "request_complete", s.pending.request_id,
                    slot=s.slot_id, n_tokens=len(s.tokens),
                    latency_ms=round((now - s.pending.t_enqueue) * 1e3, 3),
                )

    def _completion_loop(self):
        engine, metrics = self._engine, self.metrics
        while True:
            item = self._completion.get()
            if item is None:
                return
            kind, tags, handle, t_disp = item
            try:
                tok = engine.fetch_step(handle)
            except Exception as e:  # noqa: BLE001
                self._fail_slots(
                    [(t[0], t[1]) for t in tags] if kind == "chunk"
                    else tags, e,
                )
                with self._cv:
                    self._n_inflight -= 1
                    metrics.in_flight.set(self._n_inflight)
                self._inflight_sem.release()
                continue
            t_got = getattr(handle, "t_got", 0.0) or time.monotonic()
            finished: list[_Slot] = []
            itls: list[float] = []
            ttfts: list[float] = []
            n_tokens = 0
            slot_steps = 0
            drafted = accepted = v_rejects = 0
            spec_events: list[tuple[str, int, str, float]] = []
            with self._cv:
                if kind == "prefill":
                    for r, (slot_id, gen) in enumerate(tags):
                        s = self._slots[slot_id]
                        if s is None or s.gen != gen:
                            continue
                        s.t_first = t_got
                        ttfts.append(t_got - s.pending.t_enqueue)
                        n_tokens += 1
                        self._append_token(
                            slot_id, s, int(tok[r]), t_got, finished
                        )
                elif kind == "chunk":
                    # Only rows whose chunk completed the prompt carry a
                    # sampled first token; mid-prompt rows' lanes are
                    # garbage by design and nothing reads them.
                    for r, (slot_id, gen, final) in enumerate(tags):
                        if not final:
                            continue
                        s = self._slots[slot_id]
                        if s is None or s.gen != gen:
                            continue
                        s.t_first = t_got
                        ttfts.append(t_got - s.pending.t_enqueue)
                        n_tokens += 1
                        self._append_token(
                            slot_id, s, int(tok[r]), t_got, finished
                        )
                elif kind == "verify":
                    # tok is the [slots, k+1] verified-token matrix. The
                    # acceptance rule (longest exact-match prefix) is
                    # recomputed host-side from the slot's own draft; it
                    # agrees with the device's cumprod-match by
                    # construction, so the device last_token stays
                    # coherent without a round-trip.
                    for slot_id, gen in tags:
                        s = self._slots[slot_id]
                        if s is None or s.gen != gen:
                            continue
                        slot_steps += 1
                        s.verifying = False
                        d = s.draft or []
                        s.draft = None
                        m = 0
                        for t in d:
                            if int(tok[slot_id, m]) == int(t):
                                m += 1
                            else:
                                break
                        drafted += len(d)
                        accepted += m
                        if m < len(d):
                            v_rejects += 1
                        flip = s.spec.record(len(d), m)
                        if flip is not None:
                            spec_events.append((
                                s.pending.request_id, slot_id, flip,
                                s.spec.ema,
                            ))
                        # Rollback is free: host length advances only past
                        # the accepted run; the k-m rejected K/V entries
                        # sit beyond `length`, masked dead, and the slot's
                        # next real tokens overwrite them.
                        s.length += m + 1
                        # ITL stays per TOKEN: the emitted run splits the
                        # step's wall interval into m+1 equal samples.
                        dt = (t_got - s.t_last_tok) / (m + 1)
                        for j in range(m + 1):
                            itls.append(dt)
                            n_tokens += 1
                            self._append_token(
                                slot_id, s, int(tok[slot_id, j]), t_got,
                                finished,
                            )
                            if self._slots[slot_id] is not s:
                                break  # eos/max_new mid-run: surplus drops
                        if self._slots[slot_id] is s:
                            s.n_dispatched = len(s.tokens)
                else:
                    for slot_id, gen in tags:
                        s = self._slots[slot_id]
                        if s is None or s.gen != gen:
                            continue
                        slot_steps += 1
                        itls.append(t_got - s.t_last_tok)
                        n_tokens += 1
                        self._append_token(
                            slot_id, s, int(tok[slot_id]), t_got, finished
                        )
                if kind in ("decode", "verify"):
                    # tokens_per_step is per SLOT-step (a decode/verify
                    # execution of one live slot lane), so a plain engine
                    # reads exactly 1.0 and the ratio isolates the
                    # speculation win from batch occupancy.
                    self._steps += slot_steps
                    self._tokens_emitted += n_tokens
                    if kind == "verify":
                        self._spec_drafted += drafted
                        self._spec_accepted += accepted
                        self._spec_rejects += v_rejects
                self._n_inflight -= 1
                metrics.in_flight.set(self._n_inflight)
                metrics.slots_active.set(self._n_active)
                self._cv.notify_all()
            self._inflight_sem.release()
            # Metric recording outside _cv (instruments self-lock), before
            # futures resolve so a joiner sees its own samples.
            if kind == "decode":
                metrics.decode_steps.inc()
                if self.tracer.enabled:
                    self.tracer.record(
                        "decode_step", t_disp, t_got, cat="serve",
                        args={"rows": len(itls)},
                    )
                if itls:
                    metrics.observe_phase_batch(
                        "decode_step", itls, self._layout, t_got
                    )
                    for dt in itls:
                        metrics.itl.observe(dt)
            elif kind == "verify":
                # Same per-token taxonomy as decode_step: the itls list
                # already carries one sample per EMITTED token (each an
                # equal split of its slot's step interval), so phase-sum
                # == wall still holds and ITL percentiles show the
                # speculation win directly.
                if self.tracer.enabled:
                    self.tracer.record(
                        "verify_step", t_disp, t_got, cat="serve",
                        args={"rows": len(tags), "drafted": drafted,
                              "accepted": accepted},
                    )
                if itls:
                    metrics.observe_phase_batch(
                        "verify_step", itls, self._layout, t_got
                    )
                    for dt in itls:
                        metrics.itl.observe(dt)
                if drafted:
                    metrics.draft_tokens.inc(drafted)
                    metrics.accepted_tokens.inc(accepted)
                    if metrics.windowed:
                        metrics.drafted_w.add(float(drafted), t_got)
                        metrics.accepted_w.add(float(accepted), t_got)
                if v_rejects:
                    metrics.spec_rejects.inc(v_rejects)
                for req_id, slot_id, flip, ema in spec_events:
                    self.recorder.record(
                        "spec_backoff", req_id, slot=slot_id,
                        engaged=(flip == "engage"),
                        acceptance=round(ema, 4),
                    )
            elif kind == "chunk":
                # Batch-level span/phase twin of decode_step: one sample
                # per chunk dispatch. Per-request phases stay the
                # contiguous queue_wait -> prefill -> decode (a request's
                # prefill span covers all its chunks), so phase-sum ==
                # wall latency still holds by construction.
                metrics.observe_phase_batch(
                    "prefill_chunk", [t_got - t_disp], self._layout, t_got
                )
                if self.tracer.enabled:
                    self.tracer.record(
                        "prefill_chunk", t_disp, t_got, cat="serve",
                        args={"rows": len(tags)},
                    )
            for dt in ttfts:
                metrics.ttft.observe(dt)
            if n_tokens:
                metrics.tokens.inc(n_tokens)
                if metrics.windowed:
                    metrics.tokens_w.add(float(n_tokens), t_got)
            if finished:
                self._resolve(finished, t_got)

    def close(self, drain: bool = True, join_timeout_s: float = 30.0) -> None:
        """Stop the decode loop. ``drain=True`` admits + finishes what's
        queued first; otherwise queued futures fail (in-flight sequences
        still run to completion — their slots empty the table, which is
        what lets the loop exit)."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            if not drain:
                while self._queue:
                    p = self._queue.popleft()
                    p.future.set_exception(RuntimeError("batcher closed"))
                self._clear_queue_classes()
                while self._stream_adopts:
                    *_, fut = self._stream_adopts.popleft()
                    if not fut.cancelled():
                        fut.set_exception(RuntimeError("batcher closed"))
                self._count = 0
                self.metrics.queue_depth.set(0)
            self._cv.notify_all()
        self._thread.join(timeout=join_timeout_s)
        self._fetch_thread.join(timeout=join_timeout_s)
        stuck = [
            t.name
            for t in (self._thread, self._fetch_thread)
            if t.is_alive()
        ]
        if stuck:
            msg = (
                f"batcher thread(s) {stuck} still running after "
                f"{join_timeout_s:.0f}s close timeout — engine likely wedged"
            )
            logger.error(msg)
            raise RuntimeError(msg)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
