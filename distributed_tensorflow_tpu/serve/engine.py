"""Inference engines: checkpoint-loaded, mesh-sharded, AOT-compiled forwards.

Design (the serving half of the training engine's "one trace, one
executable" rule): every forward an engine will ever run is lowered and
compiled at STARTUP — one executable per sequence bucket for BERT, one per
image geometry for the classifiers — so no user request ever pays a trace
or an XLA compile. Requests of arbitrary length pad up to the smallest
bucket that fits (``BertInferenceEngine.buckets``, default {128, 256, 512}
clamped to the model's ``max_position``); partial batches pad with inert
rows to the fixed ``max_batch`` so the executable's shapes never vary.

Placement mirrors training: params live replicated on the serving mesh
(the DP-only analog of ``place_state``), batches shard their leading dim
over the data axes when ``max_batch`` divides the DP width and fall back
to replicated otherwise — a 7-row flush must degrade to redundant compute,
never to a shape error.

Checkpoints come from training via :func:`ckpt.restore_serving_state`: the
template TrainState rebuilds the training structure, tensorstore reshards
sharded arrays onto the serving mesh on read.
"""

from __future__ import annotations

import logging
import math

import jax
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_tpu.parallel.mesh import (
    batch_sharding,
    build_mesh,
    data_axes,
    replicated_sharding,
)

logger = logging.getLogger(__name__)


class RequestError(ValueError):
    """A malformed or un-servable request (maps to HTTP 400, not 500)."""


def _batch_sharding_or_replicated(mesh, max_batch: int):
    """Shard the batch dim over the DP axes when the fixed batch divides the
    DP width; otherwise serve replicated (small-batch engines on wide
    meshes must work, just without the speedup)."""
    n = math.prod(mesh.shape[a] for a in data_axes(mesh)) if data_axes(mesh) else 1
    if n > 1 and max_batch % n == 0:
        return batch_sharding(mesh)
    if n > 1:
        logger.info(
            "serve batch %d not divisible by %d-way DP mesh; "
            "replicating inference batches", max_batch, n,
        )
    return replicated_sharding(mesh)


class _AotEngine:
    """Shared AOT plumbing: compile-per-shape at startup, place-and-call."""

    def __init__(self, mesh, max_batch: int):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.mesh = mesh if mesh is not None else build_mesh({"data": -1})
        self.max_batch = max_batch
        self._param_sharding = replicated_sharding(self.mesh)
        self._batch_sharding = _batch_sharding_or_replicated(
            self.mesh, max_batch
        )

    def _place(self, tree):
        return jax.device_put(tree, self._param_sharding)

    def _struct(self, shape, dtype):
        return jax.ShapeDtypeStruct(
            shape, dtype, sharding=self._batch_sharding
        )

    def _put(self, x):
        return jax.device_put(x, self._batch_sharding)


class BertInferenceEngine(_AotEngine):
    """MLM scoring / masked-token prediction / sentence embedding over a
    trained :class:`BertForPreTraining` checkpoint.

    Request payload (numpy, one example per request):

    - ``input_ids``: ``[l]`` int — already-tokenized ids, ``l`` <= the
      largest bucket. Positions holding the MASK id are what
      ``pred_ids`` answers for.
    - ``token_type_ids``: optional ``[l]`` int (default zeros).
    - ``mlm_targets``: optional ``[l]`` int, ``-1`` = unscored. When any
      position is >= 0 the response carries ``score`` — the mean log-prob
      of the targets (MLM pseudo-log-likelihood), the standard
      BERT-as-scorer surface.

    Response per request: ``pred_ids [l]`` (argmax token at every
    position), ``score`` (float or None), ``embedding [H]`` (pooled [CLS]),
    ``nsp_probs [2]``, ``bucket`` (the padded length actually run).
    """

    def __init__(
        self,
        model,
        params,
        mesh=None,
        *,
        buckets: tuple[int, ...] = (128, 256, 512),
        max_batch: int = 8,
        return_logits: bool = False,
    ):
        super().__init__(mesh, max_batch)
        self.model = model
        cfg = model.cfg
        self.buckets = tuple(
            sorted({min(int(b), cfg.max_position) for b in buckets})
        )
        if not self.buckets:
            raise ValueError("need at least one sequence bucket")
        self.return_logits = return_logits
        self.params = self._place(params)
        # AOT-compile one executable per bucket NOW: startup pays every
        # trace/compile, the request path pays none (jit cache lookups
        # included — these are Compiled objects, not jit wrappers).
        self._compiled = {}
        for L in self.buckets:
            b = (self.max_batch, L)
            self._compiled[L] = (
                jax.jit(self._forward)
                .lower(
                    self.params,
                    self._struct(b, jnp.int32),
                    self._struct(b, jnp.bool_),
                    self._struct(b, jnp.int32),
                    self._struct(b, jnp.int32),
                )
                .compile()
            )
        logger.info(
            "BERT engine ready: buckets=%s max_batch=%d (%d executables)",
            self.buckets, self.max_batch, len(self._compiled),
        )

    def _forward(self, params, input_ids, attention_mask, token_type_ids,
                 mlm_targets):
        mlm_logits, nsp_logits, pooled = self.model.apply(
            {"params": params},
            input_ids,
            attention_mask,
            token_type_ids,
            method="serve_outputs",
        )
        # Per-ROW MLM statistics, f32 on the fly from the storage dtype —
        # the same masking/clamp recipe as the training loss (_mlm_stats),
        # but without the cross-row reduction: serving scores examples.
        weights = (mlm_targets >= 0).astype(jnp.float32)
        m = jnp.max(mlm_logits, axis=-1, keepdims=True)
        shifted = mlm_logits.astype(jnp.float32) - m.astype(jnp.float32)
        lse = jnp.log(jnp.sum(jnp.exp(shifted), axis=-1)) + m[..., 0].astype(
            jnp.float32
        )
        tgt_logit = jnp.take_along_axis(
            mlm_logits, jnp.maximum(mlm_targets, 0)[..., None], axis=-1
        )[..., 0].astype(jnp.float32)
        ce = (lse - tgt_logit) * weights
        out = {
            "pred_ids": jnp.argmax(mlm_logits, axis=-1).astype(jnp.int32),
            "nll": jnp.sum(ce, axis=-1),
            "count": jnp.sum(weights, axis=-1),
            "embedding": pooled.astype(jnp.float32),
            "nsp_probs": jax.nn.softmax(nsp_logits, axis=-1),
        }
        if self.return_logits:
            out["mlm_logits"] = mlm_logits
        return out

    def bucket_for(self, length: int) -> int:
        for b in self.buckets:
            if length <= b:
                return b
        raise RequestError(
            f"sequence length {length} exceeds the largest bucket "
            f"{self.buckets[-1]}"
        )

    def validate(self, payload: dict) -> None:
        """Reject un-servable payloads BEFORE they enqueue — a bad request
        must fail alone, never poison the batch it would have ridden in."""
        ids = np.asarray(payload.get("input_ids", ()))
        if ids.ndim != 1 or ids.size == 0:
            raise RequestError("input_ids must be a non-empty 1-D id list")
        self.bucket_for(ids.shape[0])
        for k in ("token_type_ids", "mlm_targets"):
            if k in payload and np.asarray(payload[k]).shape != ids.shape:
                raise RequestError(f"{k} shape must match input_ids")

    def run_batch(self, payloads: list[dict]) -> list[dict]:
        """Execute one micro-batch (the batcher's flush callback).

        Pads every row to the batch's bucket — the smallest bucket holding
        the LONGEST member (mixed-length batches pay the longest member's
        bucket) — and pads missing rows to ``max_batch`` with inert rows
        (mask True only at position 0: fully-masked rows would softmax
        over zero keys; the padded rows' outputs are sliced off anyway,
        but NaNs should never exist in a served buffer).
        """
        if len(payloads) > self.max_batch:
            raise ValueError(
                f"batch of {len(payloads)} exceeds max_batch {self.max_batch}"
            )
        lens = [np.asarray(p["input_ids"]).shape[0] for p in payloads]
        L = self.bucket_for(max(lens))
        B = self.max_batch
        ids = np.zeros((B, L), np.int32)
        mask = np.zeros((B, L), bool)
        types = np.zeros((B, L), np.int32)
        targets = np.full((B, L), -1, np.int32)
        for r, (p, l) in enumerate(zip(payloads, lens)):
            ids[r, :l] = np.asarray(p["input_ids"], np.int32)
            mask[r, :l] = True
            if "token_type_ids" in p:
                types[r, :l] = np.asarray(p["token_type_ids"], np.int32)
            if "mlm_targets" in p:
                targets[r, :l] = np.asarray(p["mlm_targets"], np.int32)
        mask[len(payloads):, 0] = True
        out = self._compiled[L](
            self.params,
            self._put(ids),
            self._put(mask),
            self._put(types),
            self._put(targets),
        )
        out = jax.device_get(out)
        results = []
        for r, l in enumerate(lens):
            count = float(out["count"][r])
            res = {
                "pred_ids": out["pred_ids"][r, :l],
                "score": (-float(out["nll"][r]) / count) if count else None,
                "embedding": out["embedding"][r],
                "nsp_probs": out["nsp_probs"][r],
                "bucket": L,
            }
            if self.return_logits:
                res["mlm_logits"] = out["mlm_logits"][r, :l]
            results.append(res)
        return results


class ImageClassifierEngine(_AotEngine):
    """Top-k classification over a trained image-classifier checkpoint
    (LeNet/ResNet/Inception — anything with ``apply(vars, image,
    train=False) -> logits``).

    Request payload: ``image`` ``[H, W, C]`` float32 at the engine's
    geometry (the model's training geometry — there is one image "bucket").
    Response: ``top_ids [k]``, ``top_probs [k]``.
    """

    def __init__(
        self,
        model,
        params,
        model_state=None,
        mesh=None,
        *,
        image_shape: tuple[int, int, int],
        max_batch: int = 8,
        top_k: int = 5,
    ):
        super().__init__(mesh, max_batch)
        self.model = model
        self.image_shape = tuple(image_shape)
        self.top_k = top_k
        self.variables = self._place(
            {"params": params, **(model_state or {})}
        )
        self._compiled_fn = (
            jax.jit(self._forward)
            .lower(
                self.variables,
                self._struct((self.max_batch, *self.image_shape), jnp.float32),
            )
            .compile()
        )
        logger.info(
            "image engine ready: shape=%s max_batch=%d top_k=%d",
            self.image_shape, self.max_batch, top_k,
        )

    def _forward(self, variables, image):
        logits = self.model.apply(variables, image, train=False)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        k = min(self.top_k, probs.shape[-1])
        top_probs, top_ids = jax.lax.top_k(probs, k)
        return {"top_ids": top_ids.astype(jnp.int32), "top_probs": top_probs}

    def validate(self, payload: dict) -> None:
        img = np.asarray(payload.get("image", ()))
        if img.shape != self.image_shape:
            raise RequestError(
                f"image shape {img.shape} != engine geometry {self.image_shape}"
            )

    def run_batch(self, payloads: list[dict]) -> list[dict]:
        if len(payloads) > self.max_batch:
            raise ValueError(
                f"batch of {len(payloads)} exceeds max_batch {self.max_batch}"
            )
        imgs = np.zeros((self.max_batch, *self.image_shape), np.float32)
        for r, p in enumerate(payloads):
            imgs[r] = np.asarray(p["image"], np.float32)
        out = jax.device_get(self._compiled_fn(self.variables, self._put(imgs)))
        return [
            {"top_ids": out["top_ids"][r], "top_probs": out["top_probs"][r]}
            for r in range(len(payloads))
        ]
