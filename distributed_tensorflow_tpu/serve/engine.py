"""Inference engines: checkpoint-loaded, mesh-sharded, AOT-compiled forwards.

Design (the serving half of the training engine's "one trace, one
executable" rule): every forward an engine will ever run is lowered and
compiled at STARTUP — one executable per (batch tier x sequence bucket)
for BERT, one per (batch tier x image geometry) for the classifiers — so
no user request ever pays a trace or an XLA compile. Requests of
arbitrary length pad up to the smallest bucket that fits
(``BertInferenceEngine.buckets``, default {128, 256, 512} clamped to the
model's ``max_position``); partial batches pad with inert rows to the
SMALLEST batch tier that holds them (``batch_tiers``, default {1, 2, 4, 8}
clamped to ``max_batch``), so a lone request runs a 1-row executable
instead of paying a full ``max_batch``-row forward.

The request path is split ``assemble -> dispatch -> fetch``: ``dispatch``
stages host buffers (drawn from a reusable pool) into the right
executable and returns an :class:`InFlightBatch` of device refs WITHOUT
blocking; ``fetch`` is the only point that calls ``jax.device_get``. The
batcher exploits the split to pipeline batch k+1's host assembly against
batch k's device compute (``max_in_flight``). ``run_batch`` remains the
blocking composition of the two for direct callers.

Placement mirrors training: on a DP-only mesh params live replicated (the
serving analog of ``place_state``); on a mesh with ``model`` / ``expert`` /
``pipeline`` axes the BERT engine shards them with the SAME
``bert_param_specs`` contract training uses, and every executable in the
grid becomes a ``shard_map`` of the forward over those bound axes —
Megatron TP attention/FFN, replicated-dispatch expert-parallel MoE, and
the GPipe schedule all reuse the train-side module code unchanged. The
grid is therefore (batch tier x bucket x mesh layout): one engine serves
one layout (``layout_label``), and the layout rides every dispatch into
the metrics. Batches shard their leading dim over the data axes when the
tier divides the DP width and fall back to replicated otherwise — a 7-row
flush must degrade to redundant compute, never to a shape error.

Checkpoints come from training via :func:`ckpt.restore_serving_state`: the
template TrainState rebuilds the training structure and carries the TARGET
layout's shardings, so tensorstore reads each shard straight into place —
no single-device staging round-trip.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.models.causal_lm import sample_tokens
from distributed_tensorflow_tpu.models.quant import (
    cast_params,
    dequantize_params,
    fp32_equiv_nbytes,
    is_quantized_tree,
    normalize_quant_dtype,
    quantize_kv,
    quantize_params,
)
from distributed_tensorflow_tpu.obs.memory import default_registry, tree_nbytes
from distributed_tensorflow_tpu.parallel.mesh import (
    batch_sharding,
    build_mesh,
    data_axes,
    layout_label,
    replicated_sharding,
)

logger = logging.getLogger(__name__)


class RequestError(ValueError):
    """A malformed or un-servable request (maps to HTTP 400, not 500)."""


def plan_serve_mesh(
    tp: int = 1,
    pp: int = 1,
    ep: int = 1,
    n_devices: int | None = None,
) -> tuple[dict, bool]:
    """Serving-mesh spec for the requested model parallelism, with graceful
    degradation: returns ``(spec, fell_back)``.

    The model axes need ``tp * pp * ep`` devices and the remainder goes to
    data parallelism, so the product must divide the device count. When it
    does not (dev box with fewer chips than the production flags assume),
    serving falls back to single-chip-per-replica DP with a warning —
    a wrong-sized ``--tp`` must degrade to slower serving, never die in an
    XLA shape error at startup.
    """
    if n_devices is None:
        n_devices = len(jax.devices())
    need = max(tp, 1) * max(pp, 1) * max(ep, 1)
    if need <= 1:
        return {"data": -1}, False
    if need > n_devices or n_devices % need:
        logger.warning(
            "requested serving mesh (tp=%d pp=%d ep=%d) needs %d devices "
            "to divide the %d available; falling back to single-chip "
            "data-parallel serving",
            tp, pp, ep, need, n_devices,
        )
        return {"data": -1}, True
    spec = {"data": -1}
    if pp > 1:
        spec["pipeline"] = pp
    if ep > 1:
        spec["expert"] = ep
    if tp > 1:
        spec["model"] = tp
    return spec, False


def _batch_sharding_or_replicated(mesh, max_batch: int):
    """Shard the batch dim over the DP axes when the fixed batch divides the
    DP width; otherwise serve replicated (small-batch engines on wide
    meshes must work, just without the speedup)."""
    n = math.prod(mesh.shape[a] for a in data_axes(mesh)) if data_axes(mesh) else 1
    if n > 1 and max_batch % n == 0:
        return batch_sharding(mesh)
    if n > 1:
        logger.info(
            "serve batch %d not divisible by %d-way DP mesh; "
            "replicating inference batches", max_batch, n,
        )
    return replicated_sharding(mesh)


def _normalize_tiers(tiers, max_batch: int) -> tuple[int, ...]:
    """Clamp the tier ladder to ``max_batch`` and guarantee a full-batch
    rung — the grid must always hold a ``max_batch``-row flush."""
    tiers = tuple(tiers) if tiers else (1, 2, 4, 8)
    t = {min(int(x), max_batch) for x in tiers if int(x) >= 1}
    t.add(max_batch)
    return tuple(sorted(t))


@dataclasses.dataclass
class InFlightBatch:
    """A dispatched-but-unfetched batch: device refs + host bookkeeping.

    ``out`` holds un-materialized device arrays (dispatch is async); the
    staging buffers ride along so ``fetch`` can return them to the pool
    once the transfer out is complete.
    """

    out: dict
    key: tuple          # (tier, bucket) executable key
    n: int              # real rows (the rest of the tier is padding)
    meta: list          # per-row bookkeeping (e.g. unpadded lengths)
    buffers: tuple      # host staging arrays to recycle on fetch
    # Mesh layout the batch was dispatched on (``out`` holds refs sharded
    # per that layout); the batcher keys per-layout phase histograms on it.
    layout: str = ""
    # Phase-boundary stamps (time.monotonic) the batcher turns into the
    # per-request breakdown: host staging buffers filled (ends the
    # batch_assemble phase) / jax.device_get returned (ends device).
    t_assembled: float = 0.0
    t_got: float = 0.0


@dataclasses.dataclass
class CompileRecord:
    """One AOT grid-cell compile: what it was, what it cost, whether it
    landed. ``size_bytes`` is the executable's generated-code size where
    the backend's ``memory_analysis()`` reports it, else None."""

    key: str
    seconds: float
    size_bytes: int | None = None
    ok: bool = True
    error: str | None = None


class _AotEngine:
    """Shared AOT plumbing: compile-per-shape at startup, place-and-call.

    Subclasses provide ``dispatch``/``fetch``; this base owns the tier
    ladder, per-tier batch shardings, the staging-buffer pool, and the
    per-dispatch metrics recording (``self.metrics`` is wired by
    :class:`serve.server.Client`; it stays ``None`` for bare engines).

    Every grid-cell compile routes through :meth:`_compile_cell`, which
    times it into a :class:`CompileRecord`; :meth:`grid_status` aggregates
    the records into the ``GET /compilez`` digest and the warm fraction
    the warmup-gated readiness contract reads. Large device residencies
    (params, KV caches, staging buffers) register with ``self.memory`` —
    the process-wide :class:`~..obs.memory.MemoryRegistry` unless a caller
    injects its own — so ``GET /memz`` accounts this engine's footprint.
    """

    # Grid records and the staging pool are written by worker threads and
    # read by HTTP handlers; _grid_lock / _buf_lock order every access.
    _RACETRACE_ATTRS = ("_buf_pool", "_compile_records", "_cells_planned")

    def __init__(self, mesh, max_batch: int, batch_tiers=None, memory=None):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.mesh = mesh if mesh is not None else build_mesh({"data": -1})
        self.layout = layout_label(self.mesh)
        self.max_batch = max_batch
        self.batch_tiers = _normalize_tiers(batch_tiers, max_batch)
        self.metrics = None
        self.memory = memory if memory is not None else default_registry()
        self._param_sharding = replicated_sharding(self.mesh)
        self._tier_sharding = {
            t: _batch_sharding_or_replicated(self.mesh, t)
            for t in self.batch_tiers
        }
        self._buf_lock = threading.Lock()
        self._buf_pool: dict[tuple, list[tuple]] = {}
        self._grid_lock = threading.Lock()
        self._compile_records: list[CompileRecord] = []
        self._cells_planned = 0

    # -- AOT grid observability ----------------------------------------

    def _plan_cells(self, n: int) -> None:
        """Announce ``n`` upcoming grid cells BEFORE compiling them, so a
        mid-warmup ``grid_status`` reports a warm fraction < 1 instead of
        pretending the cells it has not seen yet do not exist."""
        with self._grid_lock:
            self._cells_planned += int(n)

    def _compile_cell(self, key: str, build):
        """Run one grid-cell compile (``build`` returns the Compiled
        object), recording wall time, executable size, and failure. A
        failed compile records then re-raises — startup still dies loudly,
        but the record survives into any dump a wrapper takes."""
        t0 = time.monotonic()
        try:
            exe = build()
        except Exception as e:
            with self._grid_lock:
                self._compile_records.append(CompileRecord(
                    key=key, seconds=time.monotonic() - t0, ok=False,
                    error=f"{type(e).__name__}: {e}",
                ))
            raise
        seconds = time.monotonic() - t0
        size = None
        try:
            ma = exe.memory_analysis()
            size = int(getattr(ma, "generated_code_size_in_bytes", 0)) or None
        except Exception:  # noqa: BLE001 — size is best-effort per backend
            size = None
        with self._grid_lock:
            self._compile_records.append(
                CompileRecord(key=key, seconds=seconds, size_bytes=size)
            )
        return exe

    def grid_status(self) -> dict:
        """The ``GET /compilez`` digest: cell counts, cumulative compile
        seconds, warm fraction, the coldest (most expensive) cell, and the
        full per-cell record list."""
        with self._grid_lock:
            records = list(self._compile_records)
            planned = self._cells_planned
        compiled = sum(1 for r in records if r.ok)
        failed = len(records) - compiled
        total = max(planned, len(records))
        ok_records = [r for r in records if r.ok]
        coldest = max(ok_records, key=lambda r: r.seconds, default=None)
        return {
            "cells_total": total,
            "cells_compiled": compiled,
            "cells_failed": failed,
            "compile_seconds_total": sum(r.seconds for r in records),
            "warm_fraction": (compiled / total) if total else 1.0,
            "coldest_cell": (
                {"key": coldest.key, "seconds": coldest.seconds}
                if coldest is not None else None
            ),
            "cells": [dataclasses.asdict(r) for r in records],
        }

    def tier_for(self, n: int) -> int:
        """Smallest compiled batch tier holding ``n`` rows."""
        for t in self.batch_tiers:
            if n <= t:
                return t
        raise ValueError(
            f"batch of {n} exceeds max_batch {self.max_batch}"
        )

    def _place(self, tree):
        return jax.device_put(tree, self._param_sharding)

    def _struct(self, shape, dtype, tier: int):
        return jax.ShapeDtypeStruct(
            shape, dtype, sharding=self._tier_sharding[tier]
        )

    def _put(self, x, tier: int):
        return jax.device_put(x, self._tier_sharding[tier])

    def _take_buffers(self, key: tuple, make) -> tuple:
        """Pop a staging-buffer set for ``key`` or allocate a fresh one.
        Buffers return to the pool in ``fetch`` (after ``device_get``, when
        reuse provably cannot race the transfer in)."""
        with self._buf_lock:
            pool = self._buf_pool.get(key)
            if pool:
                return pool.pop()
        buffers = make()
        # Fresh allocation: grow the staging-buffer reservation. Outside
        # _buf_lock — the registry has its own lock and must never nest.
        self.memory.add("staging_buffers", tree_nbytes(buffers))
        return buffers

    def _give_buffers(self, key: tuple, buffers: tuple) -> None:
        with self._buf_lock:
            self._buf_pool.setdefault(key, []).append(buffers)

    def mesh_info(self) -> dict:
        """Mesh topology digest (``GET /statusz``): which layout this engine
        serves, the axis sizes behind it, and the chips one batch spans."""
        return {
            "layout": self.layout,
            "mesh_shape": dict(self.mesh.shape),
            "devices_per_engine": int(self.mesh.size),
            "platform": self.mesh.devices.flat[0].platform,
        }

    def _record_dispatch(self, tier: int, bucket, n: int) -> None:
        m = self.metrics
        if m is None:
            return
        m.tier_hits.inc(tier)
        m.layout_tier_hits.inc(f"{self.layout}/{tier}")
        if bucket is not None:
            m.bucket_hits.inc(bucket)
            m.layout_bucket_hits.inc(f"{self.layout}/{bucket}")
        m.tier_occupancy.observe(tier, n)
        m.padded_rows.inc(tier - n)

    # -- blocking compatibility surface --------------------------------

    def run_batch(self, payloads: list[dict]) -> list[dict]:
        """Blocking execute: ``fetch(dispatch(payloads))``."""
        return self.fetch(self.dispatch(payloads))


def _make_bert_forward(model, return_logits: bool):
    """The serving forward for one model variant (closure, not a method:
    per-tier pipeline variants each need their own)."""

    def forward(params, input_ids, attention_mask, token_type_ids,
                mlm_targets):
        # Int8 weight mode: unpack {"_q8","_q8_scale"} kernels in-graph —
        # HBM holds int8; XLA fuses the convert into each matmul read.
        params = dequantize_params(params, model.cfg.dtype)
        mlm_logits, nsp_logits, pooled = model.apply(
            {"params": params},
            input_ids,
            attention_mask,
            token_type_ids,
            method="serve_outputs",
        )
        # Per-ROW MLM statistics, f32 on the fly from the storage dtype —
        # the same masking/clamp recipe as the training loss (_mlm_stats),
        # but without the cross-row reduction: serving scores examples.
        weights = (mlm_targets >= 0).astype(jnp.float32)
        m = jnp.max(mlm_logits, axis=-1, keepdims=True)
        shifted = mlm_logits.astype(jnp.float32) - m.astype(jnp.float32)
        lse = jnp.log(jnp.sum(jnp.exp(shifted), axis=-1)) + m[..., 0].astype(
            jnp.float32
        )
        tgt_logit = jnp.take_along_axis(
            mlm_logits, jnp.maximum(mlm_targets, 0)[..., None], axis=-1
        )[..., 0].astype(jnp.float32)
        ce = (lse - tgt_logit) * weights
        out = {
            "pred_ids": jnp.argmax(mlm_logits, axis=-1).astype(jnp.int32),
            "nll": jnp.sum(ce, axis=-1),
            "count": jnp.sum(weights, axis=-1),
            "embedding": pooled.astype(jnp.float32),
            "nsp_probs": jax.nn.softmax(nsp_logits, axis=-1),
        }
        if return_logits:
            out["mlm_logits"] = mlm_logits
        return out

    return forward


class BertInferenceEngine(_AotEngine):
    """MLM scoring / masked-token prediction / sentence embedding over a
    trained :class:`BertForPreTraining` checkpoint.

    Request payload (numpy, one example per request):

    - ``input_ids``: ``[l]`` int — already-tokenized ids, ``l`` <= the
      largest bucket. Positions holding the MASK id are what
      ``pred_ids`` answers for.
    - ``token_type_ids``: optional ``[l]`` int (default zeros).
    - ``mlm_targets``: optional ``[l]`` int, ``-1`` = unscored. When any
      position is >= 0 the response carries ``score`` — the mean log-prob
      of the targets (MLM pseudo-log-likelihood), the standard
      BERT-as-scorer surface.

    Response per request: ``pred_ids [l]`` (argmax token at every
    position), ``score`` (float or None), ``embedding [H]`` (pooled [CLS]),
    ``nsp_probs [2]``, ``bucket`` (the padded length actually run).

    Mesh layouts: pass a DP-only mesh (or None) and the engine behaves as
    before — replicated params, plain-jit executables. Pass a mesh carrying
    ``model`` / ``expert`` / ``pipeline`` axes and the engine becomes
    model-parallel: params shard per ``bert_param_specs`` (the training
    contract, so ``restore_serving_state`` can place a checkpoint straight
    into this layout) and every (tier, bucket) executable is a
    ``shard_map`` of the forward — Megatron TP (``num_heads`` and
    ``intermediate_size`` must divide by the axis size), replicated-
    dispatch expert-parallel MoE (``moe_experts`` must divide), and the
    GPipe pipeline (the model must already be the STACKED
    ``pipeline_parallel == axis size`` variant; microbatches re-derive per
    tier since GPipe needs M | batch). Numerics match the single-chip
    engine to the tolerances pinned by tests/test_serve_mesh.py.
    """

    def __init__(
        self,
        model,
        params,
        mesh=None,
        *,
        buckets: tuple[int, ...] = (128, 256, 512),
        max_batch: int = 8,
        batch_tiers: tuple[int, ...] | None = None,
        return_logits: bool = False,
        weight_dtype: str | None = None,
        memory=None,
    ):
        super().__init__(mesh, max_batch, batch_tiers, memory=memory)
        tp = self.mesh.shape.get("model", 1)
        ep = self.mesh.shape.get("expert", 1)
        pp = self.mesh.shape.get("pipeline", 1)
        self._model_sharded = tp > 1 or ep > 1 or pp > 1
        serve_cfg = self._serve_config(model.cfg, tp, ep, pp)
        self.model = (
            type(model)(serve_cfg) if serve_cfg is not model.cfg else model
        )
        cfg = self.model.cfg
        self.weight_dtype = self._plan_quant(
            cfg, tp=tp, ep=ep, pp=pp, weight_dtype=weight_dtype
        )
        if is_quantized_tree(params):
            self.weight_dtype = "int8"
        elif self.weight_dtype == "int8":
            params = quantize_params(params)
        elif jnp.dtype(self.weight_dtype) != jnp.dtype(cfg.dtype):
            params = cast_params(params, jnp.dtype(self.weight_dtype))
        self.buckets = tuple(
            sorted({min(int(b), cfg.max_position) for b in buckets})
        )
        if not self.buckets:
            raise ValueError("need at least one sequence bucket")
        self.return_logits = return_logits
        if self._model_sharded:
            from distributed_tensorflow_tpu.models.bert import bert_param_specs

            # The same spec tree training shards by (test_bert_tp.py /
            # test_bert_pp.py pin it) — when restore_serving_state already
            # placed the checkpoint into this layout, the device_put in
            # _place is a per-array no-op (no staging round-trip).
            self._param_specs = bert_param_specs(
                params,
                model_axis="model" if tp > 1 else None,
                expert_axis="expert" if ep > 1 else None,
                pipeline_axis="pipeline" if pp > 1 else None,
            )
            self._param_sharding = jax.tree.map(
                lambda s: NamedSharding(self.mesh, s),
                self._param_specs,
                is_leaf=lambda x: isinstance(x, P),
            )
        else:
            self._param_specs = None
        self.params = self._place(params)
        self.memory.register_tree(
            "bert_params", self.params, dtype=self.weight_dtype,
            fp32_nbytes=fp32_equiv_nbytes(self.params),
        )
        # AOT-compile one executable per (batch tier, sequence bucket) NOW:
        # startup pays every trace/compile, the request path pays none (jit
        # cache lookups included — these are Compiled objects, not jit
        # wrappers). A partial flush dispatches at the smallest tier that
        # fits instead of padding to max_batch.
        self._compiled = {}
        self._plan_cells(len(self.batch_tiers) * len(self.buckets))
        for T in self.batch_tiers:
            fwd = self._tier_forward(T)
            for L in self.buckets:
                b = (T, L)
                self._compiled[T, L] = self._compile_cell(
                    f"bert/{self.layout}/t{T}/b{L}",
                    lambda fwd=fwd, b=b, T=T: (
                        jax.jit(fwd)
                        .lower(
                            self.params,
                            self._struct(b, jnp.int32, T),
                            self._struct(b, jnp.bool_, T),
                            self._struct(b, jnp.int32, T),
                            self._struct(b, jnp.int32, T),
                        )
                        .compile()
                    ),
                )
        logger.info(
            "BERT engine ready: layout=%s buckets=%s tiers=%s (%d executables)",
            self.layout, self.buckets, self.batch_tiers, len(self._compiled),
        )

    @staticmethod
    def _plan_quant(cfg, *, tp: int = 1, ep: int = 1, pp: int = 1,
                    weight_dtype: str | None = None) -> str:
        """Validate the weight-quantization knob for this config/layout and
        return the concrete dtype name (``None`` resolves to the model's
        compute dtype). Raises ``ValueError`` loudly at startup, the SC002
        clean-rejection contract. int8 x pipeline rejects: the stacked
        ``[pp, ...]`` pipeline kernels would fold the stage axis into the
        per-channel absmax reduction, silently sharing scales across
        stages. MoE expert stacks simply stay fp32 (quantize_params skips
        non-"kernel" leaf names), so ep needs no constraint."""
        del tp, ep
        w = normalize_quant_dtype(weight_dtype, "weight_dtype")
        if w == "int8" and pp > 1:
            raise ValueError(
                f"weight_dtype=int8 does not support the stacked "
                f"pipeline-parallel variant (pipeline axis of {pp}): "
                "per-channel scales would span pipeline stages"
            )
        return w or str(np.dtype(cfg.dtype).name)

    @staticmethod
    def _serve_config(cfg, tp: int, ep: int, pp: int):
        """Bind the model config to the mesh's model axes, validating the
        same divisibility contracts training enforces — loudly, at startup,
        never as a shape error mid-request."""
        if tp > 1:
            if cfg.num_heads % tp or cfg.intermediate_size % tp:
                raise ValueError(
                    f"model axis of {tp} must divide num_heads "
                    f"({cfg.num_heads}) and intermediate_size "
                    f"({cfg.intermediate_size})"
                )
            cfg = dataclasses.replace(
                cfg, model_axis="model", model_parallel=tp
            )
        if ep > 1:
            if not cfg.moe_experts or cfg.moe_experts % ep:
                raise ValueError(
                    f"expert axis of {ep} needs a MoE model with "
                    f"moe_experts divisible by it (got {cfg.moe_experts})"
                )
            # Replicated dispatch: every expert shard routes the full batch
            # and partial outputs psum — exact, and free of the capacity
            # a2a's batch-layout requirements (serving batches are tiny).
            cfg = dataclasses.replace(
                cfg,
                expert_axis="expert",
                expert_parallel=ep,
                moe_dispatch="replicated",
            )
        if pp > 1:
            if cfg.pipeline_parallel != pp:
                raise ValueError(
                    f"pipeline axis of {pp} needs the stacked "
                    f"pipeline_parallel={pp} model/checkpoint (got "
                    f"pipeline_parallel={cfg.pipeline_parallel}); pass the "
                    "training run's --pipeline-parallel to cli/serve"
                )
            cfg = dataclasses.replace(cfg, pipeline_axis="pipeline")
        return cfg

    def _tier_forward(self, tier: int):
        """Build the function to compile for one batch tier: the plain
        forward on a DP-only mesh, or its ``shard_map`` over the model axes
        (the TP/EP/PP module code runs psums that need bound axes)."""
        cfg = self.model.cfg
        model = self.model
        if self._model_sharded and cfg.pipeline_axis is not None:
            # GPipe needs n_microbatches | rows, and inside shard_map the
            # pipeline sees the PER-SHARD rows (tier/dp when the tier is
            # dp-sharded — must mirror _batch_sharding_or_replicated): per
            # tier, the largest M dividing both the local rows and the
            # configured M (gcd; a 1-row shard runs M=1 — bubble-heavy but
            # correct).
            dp = math.prod(self.mesh.shape[a] for a in data_axes(self.mesh))
            local = tier // dp if dp > 1 and tier % dp == 0 else tier
            m = math.gcd(
                local, cfg.pipeline_microbatches or 4 * cfg.pipeline_parallel
            )
            model = type(model)(
                dataclasses.replace(cfg, pipeline_microbatches=m)
            )
        fwd = _make_bert_forward(model, self.return_logits)
        if not self._model_sharded:
            return fwd
        # Batch spec matches the tier's placement rule: sharded over the DP
        # axes when the tier divides them, replicated otherwise. All inputs
        # and every output leaf are leading-dim-batch, so one spec serves
        # as prefix for both sides; params use the bert_param_specs tree.
        bspec = self._tier_sharding[tier].spec
        return jax.shard_map(
            fwd,
            mesh=self.mesh,
            in_specs=(self._param_specs, bspec, bspec, bspec, bspec),
            out_specs=bspec,
            check_vma=False,
        )

    def bucket_for(self, length: int) -> int:
        for b in self.buckets:
            if length <= b:
                return b
        raise RequestError(
            f"sequence length {length} exceeds the largest bucket "
            f"{self.buckets[-1]}"
        )

    def validate(self, payload: dict) -> None:
        """Reject un-servable payloads BEFORE they enqueue — a bad request
        must fail alone, never poison the batch it would have ridden in."""
        ids = np.asarray(payload.get("input_ids", ()))
        if ids.ndim != 1 or ids.size == 0:
            raise RequestError("input_ids must be a non-empty 1-D id list")
        self.bucket_for(ids.shape[0])
        for k in ("token_type_ids", "mlm_targets"):
            if k in payload and np.asarray(payload[k]).shape != ids.shape:
                raise RequestError(f"{k} shape must match input_ids")

    def request_bucket(self, payload: dict) -> int:
        """Queue key for bucket-aware batching: the sequence bucket this
        payload would pad to (batcher groups same-bucket requests)."""
        return self.bucket_for(np.asarray(payload["input_ids"]).shape[0])

    def dispatch(self, payloads: list[dict]) -> InFlightBatch:
        """Assemble one micro-batch and launch it; returns WITHOUT blocking
        on device compute (the returned refs materialize in ``fetch``).

        Pads every row to the batch's bucket — the smallest bucket holding
        the LONGEST member (mixed-length batches pay the longest member's
        bucket; per-bucket queues in the batcher avoid assembling such
        batches in the first place) — and pads missing rows to the
        smallest batch TIER that fits with inert rows (mask True only at
        position 0: fully-masked rows would softmax over zero keys; the
        padded rows' outputs are sliced off anyway, but NaNs should never
        exist in a served buffer).
        """
        if len(payloads) > self.max_batch:
            raise ValueError(
                f"batch of {len(payloads)} exceeds max_batch {self.max_batch}"
            )
        lens = [np.asarray(p["input_ids"]).shape[0] for p in payloads]
        L = self.bucket_for(max(lens))
        T = self.tier_for(len(payloads))
        key = (T, L)

        def _make():
            return (
                np.zeros((T, L), np.int32),
                np.zeros((T, L), bool),
                np.zeros((T, L), np.int32),
                np.full((T, L), -1, np.int32),
            )

        ids, mask, types, targets = buffers = self._take_buffers(key, _make)
        ids.fill(0)
        mask.fill(False)
        types.fill(0)
        targets.fill(-1)
        for r, (p, l) in enumerate(zip(payloads, lens)):
            ids[r, :l] = np.asarray(p["input_ids"], np.int32)
            mask[r, :l] = True
            if "token_type_ids" in p:
                types[r, :l] = np.asarray(p["token_type_ids"], np.int32)
            if "mlm_targets" in p:
                targets[r, :l] = np.asarray(p["mlm_targets"], np.int32)
        mask[len(payloads):, 0] = True
        t_assembled = time.monotonic()
        out = self._compiled[key](
            self.params,
            self._put(ids, T),
            self._put(mask, T),
            self._put(types, T),
            self._put(targets, T),
        )
        self._record_dispatch(T, L, len(payloads))
        return InFlightBatch(
            out=out, key=key, n=len(payloads), meta=lens, buffers=buffers,
            layout=self.layout, t_assembled=t_assembled,
        )

    def fetch(self, inflight: InFlightBatch) -> list[dict]:
        """Block on the in-flight batch and slice out per-row results."""
        out = jax.device_get(inflight.out)
        inflight.t_got = time.monotonic()
        self._give_buffers(inflight.key, inflight.buffers)
        L = inflight.key[1]
        results = []
        for r, l in enumerate(inflight.meta):
            count = float(out["count"][r])
            res = {
                "pred_ids": out["pred_ids"][r, :l],
                "score": (-float(out["nll"][r]) / count) if count else None,
                "embedding": out["embedding"][r],
                "nsp_probs": out["nsp_probs"][r],
                "bucket": L,
            }
            if self.return_logits:
                res["mlm_logits"] = out["mlm_logits"][r, :l]
            results.append(res)
        return results


def _kv_leaf(cache):
    """The payload leaf of a KV operand: the int8 ``"q"`` array of a
    quantized ``{"q", "s"}`` pytree, or the plain dense array. Geometry
    (layers/slots/cache_len/heads/head_dim) is always read off this leaf so
    shape logic is mode-agnostic."""
    return cache["q"] if isinstance(cache, dict) else cache


def _make_causal_prefill(model):
    """Prefill executable body for one (tier, bucket): run the full causal
    forward, scatter every layer's K/V into the slot cache pages, and
    sample each row's FIRST generated token on-device.

    Tier padding rows carry slot index == S (one past the pool) so the
    ``mode="drop"`` scatters write nowhere — padding can never dirty a
    live slot's pages.

    Quantized caches (``{"q", "s"}`` pytrees) quantize the fresh fp32 K/V
    at the scatter — same per-position absmax the incremental decode write
    uses, so a prefilled page is bit-identical to one the decode path
    would have written."""

    def prefill_fn(params, ck, cv, last, ids, mask, slots, lengths, temps,
                   seeds):
        params = dequantize_params(params, model.cfg.dtype)
        logits, k, v = model.apply(
            {"params": params}, ids, mask, method="prefill"
        )
        rows = jnp.arange(ids.shape[0])
        last_logits = logits[rows, jnp.maximum(lengths, 1) - 1]
        tok = sample_tokens(last_logits, temps, seeds, lengths)
        L = ids.shape[1]

        def scatter(cache, fresh):
            if isinstance(cache, dict):
                q, s = quantize_kv(fresh)  # [nl, T, L, h, d] -> s [nl, T, L]
                return {
                    "q": cache["q"].at[:, slots, :L].set(q, mode="drop"),
                    "s": cache["s"].at[:, slots, :L].set(s, mode="drop"),
                }
            return cache.at[:, slots, :L].set(
                fresh.astype(cache.dtype), mode="drop"
            )

        ck = scatter(ck, k)
        cv = scatter(cv, v)
        last = last.at[slots].set(tok, mode="drop")
        return ck, cv, last, tok

    return prefill_fn


def _make_causal_decode(model, cache_len: int):
    """Decode-step executable body (ONE shape: the full slot table): write
    each slot's pending token at its position, attend the cache prefix,
    sample the next token. ``last`` only advances where ``active``, and
    idle lanes carry the out-of-bounds position ``cache_len`` so their
    garbage K/V scatters DROP — a mid-chunk-prefill slot rides decode
    steps inactive, and a stray write would corrupt pages its earlier
    chunks already filled (chunked prefill never re-writes them)."""

    def decode_fn(params, ck, cv, last, lengths, active, temps, seeds):
        params = dequantize_params(params, model.cfg.dtype)
        pos = jnp.where(
            active, jnp.minimum(lengths, cache_len - 1), cache_len
        )
        logits, ck, cv = model.apply(
            {"params": params}, last, pos, ck, cv, method="decode_step"
        )
        tok = sample_tokens(logits, temps, seeds, lengths + 1)
        last = jnp.where(active, tok, last)
        return ck, cv, last, tok

    return decode_fn


def _make_causal_verify(model, cache_len: int, k: int):
    """Speculative-verify executable body (ONE shape: the full slot table,
    ``k+1`` columns): score each verifying slot's last token plus up to
    ``k`` host-drafted candidates in one forward, sample every column with
    the SAME (seed, absolute position) keys successive decode steps would
    use, and compute the accepted prefix on-device so ``last_token`` stays
    coherent without a host round-trip.

    Column ``j`` of a verifying lane sits at absolute position
    ``lengths + j``; lanes beyond a slot's ``n_input`` (and every lane of
    a non-verifying slot, ``n_input == 0``) carry the sentinel position
    ``cache_len`` so their K/V scatters drop — the decode path's idle-lane
    invariant, column-wise. Acceptance is exact match: draft ``j`` survives
    iff it equals the sampled token at column ``j-1`` AND every earlier
    draft survived (the cumprod), so with ``m`` accepted drafts the lane
    emits ``m+1`` tokens (``tok[:, :m+1]`` — the first mismatch column IS
    the verified model token; a full reject still advances one token).
    K/V written past ``lengths + m`` are dead stores the rolled-back slot
    position masks; the host rollback is just not advancing its length."""

    def verify_fn(params, ck, cv, last, drafts, lengths, n_input, temps,
                  seeds):
        params = dequantize_params(params, model.cfg.dtype)
        tokens = jnp.concatenate([last[:, None], drafts], axis=1)  # [S, k+1]
        cols = jnp.arange(k + 1)[None, :]
        pos = lengths[:, None] + cols
        wpos = jnp.where(cols < n_input[:, None], pos, cache_len)
        logits, ck, cv = model.apply(
            {"params": params}, tokens, wpos, ck, cv, method="verify_step"
        )
        # Column j's sampling key is position lengths + j + 1 — exactly the
        # key the (j+1)-th plain decode step after this point would fold
        # in, so seeded streams stay bit-identical however many columns
        # each step accepts.
        tok = jax.vmap(
            lambda lg, st: sample_tokens(lg, temps, seeds, st),
            in_axes=(1, 1), out_axes=1,
        )(logits, pos + 1)
        dcols = jnp.arange(k)[None, :]
        matches = (tok[:, :-1] == drafts) & (dcols < n_input[:, None] - 1)
        m = jnp.sum(jnp.cumprod(matches.astype(jnp.int32), axis=1), axis=1)
        new_last = tok[jnp.arange(tok.shape[0]), m]
        last = jnp.where(n_input > 0, new_last, last)
        return ck, cv, last, tok

    return verify_fn


def _make_causal_chunk_prefill(model, cache_len: int):
    """Chunk-prefill executable body for one (tier, chunk bucket): a fused
    page-gather prologue + one absolute-position prompt chunk + on-device
    first-token sampling where the chunk completes its row's prompt.

    The prologue materializes each row's matched prefix chain (pool block
    ids in ``chain``, first ``n_gather`` entries real) into the row's slot
    pages by gather-and-blend — fusing it here instead of a separate
    executable saves a dispatch/completion round per admission. Rows past
    their first chunk (and cache-miss rows) pass ``n_gather == 0`` and
    blend back their own pages unchanged. Pool pages are READ-ONLY in this
    executable: requests diverging after a shared head extend private
    copies, which is the pool's copy-on-read isolation contract.

    Per-lane validity comes from ``starts``/``lengths``: lane ``c`` of row
    ``t`` holds absolute position ``starts[t] + c`` when in range and the
    out-of-range sentinel ``cache_len`` otherwise, so padding lanes (and
    whole padding rows, which also carry slot index == S) write nowhere.
    ``is_last`` rows sample their first token at the prompt's final lane,
    keyed on absolute position exactly like the monolithic prefill — bit
    parity with the cold path follows."""

    def chunk_fn(params, ck, cv, last, pool_k, pool_v, ids, starts,
                 lengths, chain, n_gather, slots, temps, seeds):
        params = dequantize_params(params, model.cfg.dtype)
        nl = _kv_leaf(ck).shape[0]
        T, C = ids.shape
        # Quantized caches are {"q","s"} pytrees: every gather/blend/scatter
        # below maps over both leaves, so prefix pages move WITH their
        # scales bit-exactly (the cached-vs-cold parity contract).
        rows_k = jax.tree.map(lambda a: a[:, slots], ck)  # padding ix clamps
        rows_v = jax.tree.map(lambda a: a[:, slots], cv)
        bt = _kv_leaf(pool_k).shape[2]
        M = chain.shape[1]
        span = M * bt
        sel_rows = jnp.arange(span)[None, :] < (n_gather * bt)[:, None]

        def blend(rows, pool):
            def one(r, p):
                g = p[:, chain].reshape(nl, T, span, *p.shape[3:])
                sel = sel_rows.reshape((1, T, span) + (1,) * (p.ndim - 3))
                return r.at[:, :, :span].set(
                    jnp.where(sel, g, r[:, :, :span])
                )

            return jax.tree.map(one, rows, pool)

        rows_k = blend(rows_k, pool_k)
        rows_v = blend(rows_v, pool_v)
        pos = starts[:, None] + jnp.arange(C)[None, :]
        wpos = jnp.where(pos < lengths[:, None], pos, cache_len)
        logits, nk, nv = model.apply(
            {"params": params}, ids, wpos, rows_k, rows_v,
            method="prefill_chunk",
        )
        ck = jax.tree.map(
            lambda c, n: c.at[:, slots].set(n, mode="drop"), ck, nk
        )
        cv = jax.tree.map(
            lambda c, n: c.at[:, slots].set(n, mode="drop"), cv, nv
        )
        is_last = starts + C >= lengths
        li = jnp.clip(lengths - 1 - starts, 0, C - 1)
        tok = sample_tokens(
            logits[jnp.arange(T), li], temps, seeds, lengths
        )
        upd = jnp.where(is_last, tok, jnp.take(last, slots, mode="clip"))
        last = last.at[slots].set(upd, mode="drop")
        return ck, cv, last, tok

    return chunk_fn


def _make_prefix_insert(block_tokens: int):
    """Publish-to-pool executable body: copy a finished slot's prefix
    pages into newly allocated pool blocks (``block_ids``/``block_pos``
    padded with the out-of-pool sentinel, whose scatters drop).

    The slot caches are DONATED and returned untouched so the donation
    chain through the engine's device state stays linear — every
    executable (chunk -> insert -> decode) consumes the previous one's
    outputs, and XLA aliases buffers instead of copying to protect a
    still-referenced operand."""

    def insert_fn(pool_k, pool_v, ck, cv, slot, block_ids, block_pos):
        nl, _, lc = _kv_leaf(ck).shape[:3]
        nb = lc // block_tokens
        bp = jnp.minimum(block_pos, nb - 1)

        def publish(pool, cache):
            def one(p, c):
                # Works for both ranks: c.shape[3:] is (h, d) for pages and
                # () for the per-position scale plane.
                src = c[:, slot, : nb * block_tokens].reshape(
                    nl, nb, block_tokens, *c.shape[3:]
                )
                return p.at[:, block_ids].set(src[:, bp], mode="drop")

            return jax.tree.map(one, pool, cache)

        return publish(pool_k, ck), publish(pool_v, cv), ck, cv

    return insert_fn


def _make_pool_export():
    """Gather-for-transfer executable body (serve/disagg.py): read a
    pinned chain's pages out of the prefix pool into a fixed ``[nl,
    max_chain, block_tokens, heads, head_dim]`` stage (pad lanes repeat
    block 0; the importer's sentinel ids drop them). The pool operands
    are NOT donated — export copies, the pool stays live, and the
    caller's ``KVBlockPool.match`` pin keeps the gathered blocks
    immutable for the duration."""

    def export_fn(pool_k, pool_v, block_ids):
        take = lambda p: jax.tree.map(  # noqa: E731
            lambda a: jnp.take(a, block_ids, axis=1), p
        )
        return take(pool_k), take(pool_v)

    return export_fn


def _make_pool_import():
    """Adopt-transferred-pages executable body (serve/disagg.py): scatter
    a fixed ``[nl, max_chain, block_tokens, heads, head_dim]`` stage of
    received KV pages into the prefix pool at ``block_ids`` (padded with
    the out-of-pool sentinel, whose scatters drop — pad lanes carry
    garbage pages that never land). The pool operands are DONATED like
    every other executable in the chain; the import dispatches between
    decode steps on the loop thread, so the decode executable itself is
    untouched."""

    def import_fn(pool_k, pool_v, pages_k, pages_v, block_ids):
        put = lambda p, g: jax.tree.map(  # noqa: E731
            lambda a, b: a.at[:, block_ids].set(b, mode="drop"), p, g
        )
        return put(pool_k, pages_k), put(pool_v, pages_v)

    return import_fn


def _make_slot_export():
    """Live-stream checkpoint executable body (serve/disagg.py stream
    migration): gather ONE slot's lane out of the slot-table KV cache
    into a ``[nl, cache_len, heads, head_dim]`` stage. The cache operands
    are NOT donated — export copies between decode steps and the cache
    stays live (sibling of :func:`_make_pool_export`, at slot instead of
    pool-block granularity)."""

    def export_fn(ck, cv, slot):
        take = lambda c: jax.tree.map(  # noqa: E731
            lambda a: jnp.take(a, slot, axis=1), c
        )
        return take(ck), take(cv)

    return export_fn


def _make_slot_import():
    """Resume-a-migrated-stream executable body: scatter a received
    ``[nl, cache_len, heads, head_dim]`` stage into ONE slot's cache lane
    and seed ``last_token[slot]`` with the stream's newest token, so the
    very next decode step continues the generation mid-flight. Cache /
    last_token operands are DONATED like every executable in the decode
    chain; dispatches between decode steps on the loop thread."""

    def import_fn(ck, cv, last, stage_k, stage_v, slot, tok):
        put = lambda c, st: jax.tree.map(  # noqa: E731
            lambda a, b: a.at[:, slot].set(b), c, st
        )
        ck = put(ck, stage_k)
        cv = put(cv, stage_v)
        last = last.at[slot].set(tok)
        return ck, cv, last

    return import_fn


class CausalLMEngine(_AotEngine):
    """Autoregressive generation over a trained :class:`CausalLM` checkpoint
    with a paged, slot-addressed KV cache.

    The cache is a FIXED pool of per-slot pages — ``k/v: [num_layers,
    slots, cache_len, heads, head_dim]`` plus a ``last_token [slots]``
    vector — living on device for the engine's lifetime and threaded
    functionally through every executable with buffer donation, so each
    step updates the pool in place and slot assignment/reuse never changes
    a shape (= never recompiles, the decode analog of the tier grid's
    "startup pays every compile" rule). The AOT grid is:

    - ``prefill`` per (batch tier x prompt bucket): the full causal
      forward + a scatter of the prompt's K/V into the admitted rows'
      pages + on-device sampling of each row's first token (the
      time-to-first-token reply needs exactly that one small fetch).
    - ``decode`` — ONE executable at the full slot-table shape: every
      step embeds each slot's pending token, extends its pages, samples
      the next token. Idle slots ride along masked; the batcher admits /
      frees between steps without ever touching a compiled shape.
    - ``verify`` (``spec_tokens > 0`` only) — ONE executable at
      ``[slots, k+1]``: speculative decoding's batched verify of host-
      drafted candidates (:func:`_make_causal_verify`), same donation
      chain and idle-lane masking as decode, timed through
      ``_compile_cell`` like every other cell so ``/compilez`` and
      warm-fraction readiness gating see it.

    ``last_token`` stays device-resident, so step k+1 dispatches against
    step k's un-fetched output — the host fetch of sampled tokens (finish
    detection, streaming) overlaps the next step's device compute via the
    batcher's completion thread.

    Sampling is greedy at ``temperature == 0`` and seeded-categorical
    otherwise, keyed on (seed, absolute position) only — a request's token
    stream is a function of the request, not of its batchmates, so
    continuous batching is bit-identical to a solo run.

    Tensor parallelism (a mesh with a ``model`` axis) shards the head axis
    of the cache pages and the params per ``causal_param_specs``; batch
    inputs replicate (every model shard sees every slot — slot state must
    stay coherent, and decode batches are tiny). Expert/pipeline axes are
    rejected at startup. DP axes likewise replicate: a decode engine is
    one replica; fleet scale-out is N engines behind the router contract.

    **Chunked mode** (``prefix_cache_mb > 0`` or ``prefill_chunk > 0``)
    swaps the monolithic prefill grid for a CHUNK grid — one executable
    per (tier x chunk bucket), each a fused page-gather prologue + one
    absolute-position prompt chunk (see :func:`_make_causal_chunk_prefill`)
    — so prompt admission becomes a sequence of bounded chunk dispatches
    the batcher interleaves with decode steps. With a prefix-cache budget
    the engine also owns a device-resident pool of KV pages ``[nl,
    n_blocks, block_tokens, heads, head_dim]`` (sharded like the slot
    cache, so TP gathers pages with per-shard head dims) indexed by a host
    :class:`~..serve.kvpool.KVBlockPool` trie, plus one ``insert``
    executable that publishes a finished slot's prefix pages back to the
    pool. A chunk at ``start == 0`` with nothing to gather is exactly the
    monolithic prefill, so legacy mode (both knobs 0) keeps the original
    grid and byte-identical behavior.
    """

    def __init__(
        self,
        model,
        params,
        mesh=None,
        *,
        buckets: tuple[int, ...] = (64, 128, 256),
        slots: int = 8,
        max_batch: int = 4,
        batch_tiers: tuple[int, ...] | None = None,
        max_new_tokens: int = 32,
        prefix_cache_mb: float = 0.0,
        block_tokens: int = 16,
        prefill_chunk: int = 0,
        spec_tokens: int = 0,
        spec_min_match: int = 2,
        spec_backoff: float = 0.25,
        kv_transfer: bool = False,
        stream_migrate: bool = False,
        weight_dtype: str | None = None,
        kv_dtype: str | None = None,
        memory=None,
    ):
        if slots < 1:
            raise ValueError(f"need at least one cache slot, got {slots}")
        super().__init__(mesh, min(max_batch, slots), batch_tiers,
                         memory=memory)
        tp = self.mesh.shape.get("model", 1)
        ep = self.mesh.shape.get("expert", 1)
        pp = self.mesh.shape.get("pipeline", 1)
        self._model_sharded = tp > 1
        serve_cfg = self._serve_config(model.cfg, tp=tp, ep=ep, pp=pp)
        self.model = (
            type(model)(serve_cfg) if serve_cfg is not model.cfg else model
        )
        cfg = self.model.cfg
        # Quantized serving (ROADMAP item 4; docs/DEPLOY.md "Quantized
        # serving"): weight_dtype packs kernels to int8 at engine build
        # (idempotent — restore_serving_state may have packed them already),
        # kv_dtype stores cache/pool pages as int8 {"q","s"} pytrees.
        self.weight_dtype, self.kv_dtype = self._plan_quant(
            cfg, tp=tp, weight_dtype=weight_dtype, kv_dtype=kv_dtype
        )
        if is_quantized_tree(params):
            self.weight_dtype = "int8"
        elif self.weight_dtype == "int8":
            params = quantize_params(params)
        elif jnp.dtype(self.weight_dtype) != jnp.dtype(cfg.dtype):
            params = cast_params(params, jnp.dtype(self.weight_dtype))
        self._kv_quantized = self.kv_dtype == "int8"
        self._kv_store_dtype = (
            jnp.dtype(cfg.dtype) if self._kv_quantized
            else jnp.dtype(self.kv_dtype)
        )
        self.slots = slots
        self.buckets = tuple(
            sorted({min(int(b), cfg.max_position) for b in buckets})
        )
        if not self.buckets:
            raise ValueError("need at least one prompt bucket")
        # Every slot's pages hold prompt + generated tokens; validate()
        # rejects requests that could not fit before they ever enqueue.
        self.cache_len = min(self.buckets[-1] + max_new_tokens,
                             cfg.max_position)
        self.max_new_tokens = max_new_tokens
        # Speculative decoding (serve/spec.py; docs/DEPLOY.md "Speculative
        # decoding"): k > 0 compiles ONE extra verify executable at
        # [slots, k+1] and hands the batcher a SpecConfig to draft against.
        from distributed_tensorflow_tpu.serve.spec import SpecConfig

        self.spec_tokens = self._plan_spec(
            cfg, tp=tp, spec_tokens=spec_tokens, min_match=spec_min_match,
            max_new_tokens=max_new_tokens,
        )
        self.spec = (
            SpecConfig(
                spec_tokens=self.spec_tokens, min_match=spec_min_match,
                backoff_threshold=spec_backoff,
            )
            if self.spec_tokens > 0 else None
        )

        from distributed_tensorflow_tpu.models.causal_lm import (
            causal_param_specs,
        )

        cache_shape = (
            cfg.num_layers, slots, self.cache_len,
            cfg.num_heads, cfg.hidden_size // cfg.num_heads,
        )
        if self._model_sharded:
            self._param_specs = causal_param_specs(params, model_axis="model")
            self._param_sharding = jax.tree.map(
                lambda s: NamedSharding(self.mesh, s),
                self._param_specs,
                is_leaf=lambda x: isinstance(x, P),
            )
            self._cache_spec = P(None, None, None, "model", None)
        else:
            self._param_specs = None
            self._cache_spec = P()
        self._cache_sharding = self._kv_sharding(self._cache_spec)
        self._rep = replicated_sharding(self.mesh)
        self.params = self._place(params)
        self._cache_k = self._kv_zeros(cache_shape, self._cache_sharding)
        self._cache_v = self._kv_zeros(cache_shape, self._cache_sharding)
        self._last_token = jax.device_put(
            jnp.zeros((slots,), jnp.int32), self._rep
        )
        self.memory.register_tree(
            "lm_params", self.params, dtype=self.weight_dtype,
            fp32_nbytes=fp32_equiv_nbytes(self.params),
        )
        kv_bytes = tree_nbytes(self._cache_k) + tree_nbytes(self._cache_v)
        self.memory.register(
            "kv_slot_cache", kv_bytes, dtype=self.kv_dtype,
            fp32_nbytes=2 * int(np.prod(cache_shape)) * 4,
        )
        # Per-slot share of the slot-table KV cache: the batcher multiplies
        # this by slots_active so /statusz and /memz agree on active bytes.
        self.slot_page_bytes = kv_bytes // slots

        # Prefix-cache / chunked-prefill plumbing. Legacy mode (both knobs
        # 0) compiles the original monolithic prefill grid; chunked mode
        # compiles the chunk grid INSTEAD (a start-0 chunk subsumes it),
        # so startup never pays both.
        from distributed_tensorflow_tpu.serve.kvpool import KVBlockPool

        self.block_tokens = int(block_tokens)
        self._chunked_mode = prefix_cache_mb > 0 or prefill_chunk > 0
        self.prefix_cache = None
        if self._chunked_mode:
            chunk = int(prefill_chunk) if prefill_chunk > 0 \
                else self.buckets[-1]
            self.prefill_chunk_size = min(chunk, self.buckets[-1])
            self._chunk_buckets = tuple(sorted(
                {b for b in self.buckets if b <= self.prefill_chunk_size}
                | {self.prefill_chunk_size}
            ))
            self._max_chain = max(1, self.buckets[-1] // self.block_tokens)
            n_blocks, self._bytes_per_block = self._plan_prefix_cache(
                cfg, tp=tp, prefix_cache_mb=prefix_cache_mb,
                block_tokens=self.block_tokens, kv_dtype=self.kv_dtype,
            )
            if prefix_cache_mb > 0:
                self.prefix_cache = KVBlockPool(
                    n_blocks, self.block_tokens, self._bytes_per_block,
                    dtype=self.kv_dtype,
                )
            else:
                n_blocks = 1  # dummy pool keeps one chunk operand layout
            pool_shape = (
                cfg.num_layers, n_blocks, self.block_tokens,
                cfg.num_heads, cfg.hidden_size // cfg.num_heads,
            )
            self._pool_blocks = n_blocks
            self._pool_k = self._kv_zeros(pool_shape, self._cache_sharding)
            self._pool_v = self._kv_zeros(pool_shape, self._cache_sharding)
            self.memory.register(
                "kv_prefix_pool",
                tree_nbytes(self._pool_k) + tree_nbytes(self._pool_v),
                dtype=self.kv_dtype,
                fp32_nbytes=2 * int(np.prod(pool_shape)) * 4,
            )
        else:
            self.prefill_chunk_size = 0

        # The grid: prefill per (tier x bucket) — or chunk-prefill per
        # (tier x chunk bucket) — + ONE decode step. Cache / last_token
        # operands are donated — XLA updates the pool in place, and the
        # engine swaps its refs for the returned ones at dispatch.
        self._prefill_compiled = {}
        self._chunk_compiled = {}
        self._export_compiled = None
        self._import_compiled = None
        self._kv_transfer = False
        # Live-stream migration (serve/disagg.py): two extra AOT cells —
        # slot export (checkpoint a live generation's KV lane) and slot
        # import (resume it here) — valid in BOTH prefill modes.
        self.stream_migrate = bool(stream_migrate)
        self._slot_export_compiled = None
        self._slot_import_compiled = None
        n_spec_cells = 1 if self.spec_tokens else 0
        n_mig_cells = 2 if self.stream_migrate else 0
        if not self._chunked_mode:
            self._plan_cells(
                len(self.batch_tiers) * len(self.buckets) + 1 + n_spec_cells
                + n_mig_cells
            )
            for T in self.batch_tiers:
                fn = self._wrap(_make_causal_prefill(self.model), n_batch=6)
                for L in self.buckets:
                    self._prefill_compiled[T, L] = self._compile_cell(
                        f"lm/{self.layout}/prefill/t{T}/b{L}",
                        lambda fn=fn, T=T, L=L: (
                            jax.jit(fn, donate_argnums=(1, 2, 3))
                            .lower(
                                self.params,
                                self._kv_struct(cache_shape),
                                self._kv_struct(cache_shape),
                                self._rep_struct((slots,), jnp.int32),
                                self._rep_struct((T, L), jnp.int32),
                                self._rep_struct((T, L), jnp.bool_),
                                self._rep_struct((T,), jnp.int32),
                                self._rep_struct((T,), jnp.int32),
                                self._rep_struct((T,), jnp.float32),
                                self._rep_struct((T,), jnp.int32),
                            )
                            .compile()
                        ),
                    )
        else:
            self._kv_transfer = (
                bool(kv_transfer) and self.prefix_cache is not None
            )
            self._plan_cells(
                len(self.batch_tiers) * len(self._chunk_buckets) + 1
                + (1 if self.prefix_cache is not None else 0)
                + (2 if self._kv_transfer else 0) + n_spec_cells
                + n_mig_cells
            )
            chunk_fn = self._wrap_chunk(
                _make_causal_chunk_prefill(self.model, self.cache_len)
            )
            pool_struct = self._kv_struct(pool_shape)
            for T in self.batch_tiers:
                for C in self._chunk_buckets:
                    self._chunk_compiled[T, C] = self._compile_cell(
                        f"lm/{self.layout}/chunk/t{T}/c{C}",
                        lambda T=T, C=C: (
                            jax.jit(chunk_fn, donate_argnums=(1, 2, 3))
                            .lower(
                                self.params,
                                self._kv_struct(cache_shape),
                                self._kv_struct(cache_shape),
                                self._rep_struct((slots,), jnp.int32),
                                pool_struct,
                                pool_struct,
                                self._rep_struct((T, C), jnp.int32),
                                self._rep_struct((T,), jnp.int32),
                                self._rep_struct((T,), jnp.int32),
                                self._rep_struct((T, self._max_chain),
                                                 jnp.int32),
                                self._rep_struct((T,), jnp.int32),
                                self._rep_struct((T,), jnp.int32),
                                self._rep_struct((T,), jnp.float32),
                                self._rep_struct((T,), jnp.int32),
                            )
                            .compile()
                        ),
                    )
            if self.prefix_cache is not None:
                insert_fn = self._wrap_insert(
                    _make_prefix_insert(self.block_tokens)
                )
                self._insert_compiled = self._compile_cell(
                    f"lm/{self.layout}/insert",
                    lambda: (
                        jax.jit(insert_fn, donate_argnums=(0, 1, 2, 3))
                        .lower(
                            pool_struct,
                            pool_struct,
                            self._kv_struct(cache_shape),
                            self._kv_struct(cache_shape),
                            self._rep_struct((), jnp.int32),
                            self._rep_struct((self._max_chain,), jnp.int32),
                            self._rep_struct((self._max_chain,), jnp.int32),
                        )
                        .compile()
                    ),
                )
            if self._kv_transfer:
                pages_struct = self._kv_struct(
                    (cfg.num_layers, self._max_chain, self.block_tokens,
                     *pool_shape[3:]),
                )
                # Export gathers pinned pages OUT of the pool — the pool
                # operands are NOT donated (they must survive the gather;
                # eager ops over the donation-aliased pool are exactly
                # what this AOT cell exists to avoid).
                export_fn = self._wrap_export(_make_pool_export())
                self._export_compiled = self._compile_cell(
                    f"lm/{self.layout}/export",
                    lambda: (
                        jax.jit(export_fn)
                        .lower(
                            pool_struct,
                            pool_struct,
                            self._rep_struct((self._max_chain,), jnp.int32),
                        )
                        .compile()
                    ),
                )
                import_fn = self._wrap_import(_make_pool_import())
                self._import_compiled = self._compile_cell(
                    f"lm/{self.layout}/import",
                    lambda: (
                        jax.jit(import_fn, donate_argnums=(0, 1))
                        .lower(
                            pool_struct,
                            pool_struct,
                            pages_struct,
                            pages_struct,
                            self._rep_struct((self._max_chain,), jnp.int32),
                        )
                        .compile()
                    ),
                )
        decode_fn = self._wrap(
            _make_causal_decode(self.model, self.cache_len), n_batch=4
        )
        self._decode_compiled = self._compile_cell(
            f"lm/{self.layout}/decode",
            lambda: (
                jax.jit(decode_fn, donate_argnums=(1, 2, 3))
                .lower(
                    self.params,
                    self._kv_struct(cache_shape),
                    self._kv_struct(cache_shape),
                    self._rep_struct((slots,), jnp.int32),
                    self._rep_struct((slots,), jnp.int32),
                    self._rep_struct((slots,), jnp.bool_),
                    self._rep_struct((slots,), jnp.float32),
                    self._rep_struct((slots,), jnp.int32),
                )
                .compile()
            ),
        )
        self._verify_compiled = None
        if self.spec_tokens:
            verify_fn = self._wrap(
                _make_causal_verify(
                    self.model, self.cache_len, self.spec_tokens
                ),
                n_batch=5,
            )
            self._verify_compiled = self._compile_cell(
                f"lm/{self.layout}/verify",
                lambda: (
                    jax.jit(verify_fn, donate_argnums=(1, 2, 3))
                    .lower(
                        self.params,
                        self._kv_struct(cache_shape),
                        self._kv_struct(cache_shape),
                        self._rep_struct((slots,), jnp.int32),
                        self._rep_struct(
                            (slots, self.spec_tokens), jnp.int32
                        ),
                        self._rep_struct((slots,), jnp.int32),
                        self._rep_struct((slots,), jnp.int32),
                        self._rep_struct((slots,), jnp.float32),
                        self._rep_struct((slots,), jnp.int32),
                    )
                    .compile()
                ),
            )
        if self.stream_migrate:
            stage_spec = (
                P(None, None, "model", None) if self._model_sharded else P()
            )
            self._slot_stage_spec = stage_spec
            self._slot_stage_sharding = self._kv_sharding(stage_spec)
            slot_stage_struct = self._kv_struct(
                (cfg.num_layers, self.cache_len, cfg.num_heads,
                 cfg.hidden_size // cfg.num_heads),
                sharding=self._slot_stage_sharding,
            )
            # Slot export reads the live cache between decode steps — the
            # cache operands are NOT donated (the stream may stay resident
            # if the push fails and the batcher re-adopts it locally).
            sexp_fn = self._wrap_slot_export(_make_slot_export())
            self._slot_export_compiled = self._compile_cell(
                f"lm/{self.layout}/slot_export",
                lambda: (
                    jax.jit(sexp_fn)
                    .lower(
                        self._kv_struct(cache_shape),
                        self._kv_struct(cache_shape),
                        self._rep_struct((), jnp.int32),
                    )
                    .compile()
                ),
            )
            simp_fn = self._wrap_slot_import(_make_slot_import())
            self._slot_import_compiled = self._compile_cell(
                f"lm/{self.layout}/slot_import",
                lambda: (
                    jax.jit(simp_fn, donate_argnums=(0, 1, 2))
                    .lower(
                        self._kv_struct(cache_shape),
                        self._kv_struct(cache_shape),
                        self._rep_struct((slots,), jnp.int32),
                        slot_stage_struct,
                        slot_stage_struct,
                        self._rep_struct((), jnp.int32),
                        self._rep_struct((), jnp.int32),
                    )
                    .compile()
                ),
            )
        logger.info(
            "causal-LM engine ready: layout=%s slots=%d cache_len=%d "
            "buckets=%s tiers=%s chunk=%s pool_blocks=%s spec_k=%s "
            "(%d executables)",
            self.layout, slots, self.cache_len, self.buckets,
            self.batch_tiers, self.prefill_chunk_size or None,
            self.prefix_cache.n_blocks if self.prefix_cache else None,
            self.spec_tokens or None,
            len(self._prefill_compiled) + len(self._chunk_compiled) + 1
            + (1 if self.prefix_cache is not None else 0)
            + (2 if self._kv_transfer else 0) + n_spec_cells + n_mig_cells,
        )

    @staticmethod
    def _serve_config(cfg, tp: int = 1, ep: int = 1, pp: int = 1):
        """Bind the decode model to the mesh's model axes — TP only. The
        slot cache has no expert routing and a pipelined decode step would
        bubble ~(pp-1)/pp of every token; both reject loudly at startup so
        shardcheck's sweep (SC002) sees a clean plan/serve/reject story."""
        if ep > 1:
            raise ValueError(
                f"expert axis of {ep}: the decode engine does not support "
                "expert parallelism (no MoE decoder variant)"
            )
        if pp > 1:
            raise ValueError(
                f"pipeline axis of {pp}: the decode engine does not support "
                "pipeline parallelism (a one-token step cannot fill a "
                "GPipe schedule)"
            )
        if tp > 1:
            if cfg.num_heads % tp or cfg.intermediate_size % tp:
                raise ValueError(
                    f"model axis of {tp} must divide num_heads "
                    f"({cfg.num_heads}) and intermediate_size "
                    f"({cfg.intermediate_size})"
                )
            cfg = dataclasses.replace(
                cfg, model_axis="model", model_parallel=tp
            )
        return cfg

    @staticmethod
    def _plan_prefix_cache(cfg, *, tp: int = 1, prefix_cache_mb: float = 0.0,
                           block_tokens: int = 16,
                           kv_dtype: str | None = None) -> tuple[int, int]:
        """Size + validate the prefix-page pool for this config/layout:
        ``(n_blocks, bytes_per_block)``. Raises ``ValueError`` loudly at
        startup (shardcheck's SC002 sweep crosses layouts with these
        configs) — a budget smaller than one block or a TP degree that
        cannot split the pages' head axis must never become a shape error
        mid-request."""
        if block_tokens < 1:
            raise ValueError(
                f"block_tokens must be >= 1, got {block_tokens}"
            )
        if tp > 1 and cfg.num_heads % tp:
            raise ValueError(
                f"model axis of {tp} must divide num_heads "
                f"({cfg.num_heads}) to shard prefix-cache pages"
            )
        kv = normalize_quant_dtype(kv_dtype, "kv_dtype") \
            or str(np.dtype(cfg.dtype).name)
        if kv == "int8":
            # int8 page payload + two f32 per-position scales (k and v).
            bytes_per_block = (
                2 * cfg.num_layers * block_tokens * (cfg.hidden_size + 4)
            )
        else:
            bytes_per_block = (
                2 * cfg.num_layers * block_tokens * cfg.hidden_size
                * jnp.dtype(kv).itemsize
            )
        n_blocks = int(prefix_cache_mb * 2**20 // bytes_per_block)
        if prefix_cache_mb > 0 and n_blocks < 1:
            raise ValueError(
                f"--prefix-cache-mb {prefix_cache_mb:g} holds no "
                f"{bytes_per_block}-byte block (num_layers="
                f"{cfg.num_layers}, block_tokens={block_tokens}, "
                f"hidden={cfg.hidden_size})"
            )
        return n_blocks, bytes_per_block

    @staticmethod
    def _plan_spec(cfg, *, tp: int = 1, spec_tokens: int = 0,
                   min_match: int = 2, max_new_tokens: int = 32) -> int:
        """Validate the speculation knobs for this config/layout and return
        the verify width ``k`` (0 = disabled). Raises ``ValueError`` loudly
        at startup (shardcheck's SC002 sweep crosses layouts with these
        configs, like ``_plan_prefix_cache``) — a draft window the cache or
        generation budget can never use must not wait for a request to
        fail. ``tp`` imposes no extra constraint beyond ``_serve_config``'s
        head-divisibility (the verify executable shards exactly like
        decode), but stays in the signature so the sweep exercises every
        layout through one call shape."""
        del tp
        if spec_tokens < 0:
            raise ValueError(
                f"spec_tokens must be >= 0, got {spec_tokens}"
            )
        if spec_tokens == 0:
            return 0
        if min_match < 1:
            raise ValueError(
                f"spec min_match must be >= 1, got {min_match}"
            )
        if spec_tokens >= max_new_tokens:
            raise ValueError(
                f"spec_tokens {spec_tokens} >= max_new_tokens "
                f"{max_new_tokens}: a draft can never exceed the remaining "
                "generation budget"
            )
        if spec_tokens + 1 > cfg.max_position:
            raise ValueError(
                f"spec_tokens {spec_tokens} + 1 exceeds max_position "
                f"{cfg.max_position}"
            )
        return int(spec_tokens)

    @staticmethod
    def _plan_quant(cfg, *, tp: int = 1, weight_dtype: str | None = None,
                    kv_dtype: str | None = None) -> tuple[str, str]:
        """Validate the quantization knobs for this config/layout and
        return concrete ``(weight_dtype, kv_dtype)`` names (``None`` knobs
        resolve to the model's compute dtype). Raises ``ValueError`` loudly
        at startup — shardcheck's SC002 quant sweep crosses these with
        every serving layout, so an unsupported mode must reject cleanly
        here, never surface as an XLA error mid-request. ``tp`` imposes no
        extra constraint: packed ``_q8`` kernels shard exactly like the
        kernels they replace, weight scales are per-last-axis-channel (the
        axis TP splits, so each shard owns its scales), and KV scales drop
        the sharded head axes entirely."""
        del tp
        w = normalize_quant_dtype(weight_dtype, "weight_dtype")
        k = normalize_quant_dtype(kv_dtype, "kv_dtype")
        default = str(np.dtype(cfg.dtype).name)
        return (w or default, k or default)

    # -- quantized-KV plumbing: every cache/pool/stage operand flows
    # -- through these helpers, so int8 mode is ONE representation decision
    # -- (the {"q","s"} pytree) instead of per-cell branching.

    def _kv_wrap_spec(self, spec):
        """shard_map spec for a KV operand: the per-position scale plane
        drops the trailing (heads, head_dim) axes, so a TP "model" entry
        never lands in its spec."""
        if not self._kv_quantized:
            return spec
        return {"q": spec, "s": P(*tuple(spec)[:-2])}

    def _kv_sharding(self, spec):
        if not self._kv_quantized:
            return NamedSharding(self.mesh, spec)
        return {
            "q": NamedSharding(self.mesh, spec),
            "s": NamedSharding(self.mesh, P(*tuple(spec)[:-2])),
        }

    def _kv_struct(self, shape, sharding=None):
        sharding = self._cache_sharding if sharding is None else sharding
        if not self._kv_quantized:
            return jax.ShapeDtypeStruct(
                shape, self._kv_store_dtype, sharding=sharding
            )
        return {
            "q": jax.ShapeDtypeStruct(
                shape, jnp.int8, sharding=sharding["q"]
            ),
            "s": jax.ShapeDtypeStruct(
                shape[:-2], jnp.float32, sharding=sharding["s"]
            ),
        }

    def _kv_zeros(self, shape, sharding):
        if not self._kv_quantized:
            return jax.device_put(
                jnp.zeros(shape, self._kv_store_dtype), sharding
            )
        return {
            "q": jax.device_put(jnp.zeros(shape, jnp.int8), sharding["q"]),
            "s": jax.device_put(
                jnp.zeros(shape[:-2], jnp.float32), sharding["s"]
            ),
        }

    def kv_bytes_per_token(self) -> int:
        """Slot-cache bytes ONE cached token occupies (K + V across all
        layers, plus scales at int8) — the `serve_kv_bytes_per_token{dtype=}`
        gauge and DEPLOY.md's sizing math both read this."""
        cfg = self.model.cfg
        if self._kv_quantized:
            return 2 * cfg.num_layers * (cfg.hidden_size + 4)
        return (
            2 * cfg.num_layers * cfg.hidden_size
            * jnp.dtype(self._kv_store_dtype).itemsize
        )

    def _rep_struct(self, shape, dtype):
        return jax.ShapeDtypeStruct(shape, dtype, sharding=self._rep)

    def _wrap(self, fn, n_batch: int):
        """shard_map the step over the model axis when sharded; the cache's
        head axis splits, everything batch-like replicates (post-psum
        logits are identical across shards, so replicated outs are safe)."""
        if not self._model_sharded:
            return fn
        cache, rep = self._kv_wrap_spec(self._cache_spec), P()
        # (params, cache_k, cache_v, last) + the n_batch step operands.
        in_specs = (self._param_specs, cache, cache, rep) + (rep,) * n_batch
        return jax.shard_map(
            fn,
            mesh=self.mesh,
            in_specs=in_specs,
            out_specs=(cache, cache, rep, rep),
            check_vma=False,
        )

    def _wrap_chunk(self, fn):
        """Chunk-prefill twin of ``_wrap``: the pool pages shard their
        head axis exactly like the slot cache (per-shard gathers stay
        local — no cross-shard page traffic), everything else replicates."""
        if not self._model_sharded:
            return fn
        cache, rep = self._kv_wrap_spec(self._cache_spec), P()
        in_specs = (
            self._param_specs, cache, cache, rep, cache, cache,
        ) + (rep,) * 8
        return jax.shard_map(
            fn,
            mesh=self.mesh,
            in_specs=in_specs,
            out_specs=(cache, cache, rep, rep),
            check_vma=False,
        )

    def _wrap_insert(self, fn):
        if not self._model_sharded:
            return fn
        cache, rep = self._kv_wrap_spec(self._cache_spec), P()
        return jax.shard_map(
            fn,
            mesh=self.mesh,
            in_specs=(cache, cache, cache, cache, rep, rep, rep),
            out_specs=(cache, cache, cache, cache),
            check_vma=False,
        )

    def _wrap_import(self, fn):
        """Pool-import twin of ``_wrap_insert``: transferred pages shard
        their head axis exactly like the pool they scatter into."""
        if not self._model_sharded:
            return fn
        cache, rep = self._kv_wrap_spec(self._cache_spec), P()
        return jax.shard_map(
            fn,
            mesh=self.mesh,
            in_specs=(cache, cache, cache, cache, rep),
            out_specs=(cache, cache),
            check_vma=False,
        )

    def _wrap_export(self, fn):
        """Pool-export twin of ``_wrap_import``: per-shard gathers stay
        local (the page stage splits its head axis like the pool)."""
        if not self._model_sharded:
            return fn
        cache, rep = self._kv_wrap_spec(self._cache_spec), P()
        return jax.shard_map(
            fn,
            mesh=self.mesh,
            in_specs=(cache, cache, rep),
            out_specs=(cache, cache),
            check_vma=False,
        )

    def _wrap_slot_export(self, fn):
        """Slot-lane export for stream migration: the gathered stage drops
        the slot dim, so its head axis sits one position earlier than the
        cache spec's — per-shard gathers stay local either way."""
        if not self._model_sharded:
            return fn
        cache, rep = self._kv_wrap_spec(self._cache_spec), P()
        stage = self._kv_wrap_spec(P(None, None, "model", None))
        return jax.shard_map(
            fn,
            mesh=self.mesh,
            in_specs=(cache, cache, rep),
            out_specs=(stage, stage),
            check_vma=False,
        )

    def _wrap_slot_import(self, fn):
        """Slot-lane import (resume a migrated stream): the received stage
        shards its head axis like the cache it scatters into."""
        if not self._model_sharded:
            return fn
        cache, rep = self._kv_wrap_spec(self._cache_spec), P()
        stage = self._kv_wrap_spec(P(None, None, "model", None))
        return jax.shard_map(
            fn,
            mesh=self.mesh,
            in_specs=(cache, cache, rep, stage, stage, rep, rep),
            out_specs=(cache, cache, rep),
            check_vma=False,
        )

    # -- request surface ------------------------------------------------

    def bucket_for(self, length: int) -> int:
        for b in self.buckets:
            if length <= b:
                return b
        raise RequestError(
            f"prompt length {length} exceeds the largest bucket "
            f"{self.buckets[-1]}"
        )

    def _chunk_bucket_for(self, n: int) -> int:
        for c in self._chunk_buckets:
            if n <= c:
                return c
        raise ValueError(
            f"chunk of {n} exceeds prefill_chunk_size "
            f"{self.prefill_chunk_size}"
        )

    def validate(self, payload: dict) -> None:
        ids = np.asarray(payload.get("input_ids", ()))
        if ids.ndim != 1 or ids.size == 0:
            raise RequestError("input_ids must be a non-empty 1-D id list")
        max_new = int(payload.get("max_new_tokens", self.max_new_tokens))
        if max_new < 1:
            raise RequestError("max_new_tokens must be >= 1")
        # Migration replay: ``resume_tokens`` are already-delivered
        # generated tokens the re-prefill treats as prompt suffix — the
        # effective prompt must bucket, and the stream must still owe
        # tokens (a fully-satisfied stream has nothing to resume).
        res = np.asarray(payload.get("resume_tokens", ()))
        if res.size and res.ndim != 1:
            raise RequestError("resume_tokens must be a 1-D id list")
        if res.size >= max_new:
            raise RequestError(
                f"resume_tokens of {res.size} already satisfy "
                f"max_new_tokens {max_new}: nothing left to generate"
            )
        if not self._chunked_mode:
            # Monolithic prefill pads the whole effective prompt into one
            # bucket executable; chunked mode splits it, so there the only
            # real bound is the cache-page check below (a migrated stream's
            # prompt + resumed prefix routinely exceeds the largest bucket).
            self.bucket_for(ids.shape[0] + res.size)
        if ids.shape[0] + max_new > self.cache_len:
            raise RequestError(
                f"prompt of {ids.shape[0]} + max_new_tokens {max_new} "
                f"exceeds the {self.cache_len}-token cache pages"
            )
        if float(payload.get("temperature", 0.0)) < 0.0:
            raise RequestError("temperature must be >= 0")
        # Priority scheduling (serve/batcher.py): class 0 is the most
        # urgent; deadline_ms is a TTFT deadline relative to enqueue that
        # EDF admission orders on (and preemption rescues).
        pri = payload.get("priority")
        if pri is not None:
            try:
                pri = int(pri)
            except (TypeError, ValueError):
                raise RequestError("priority must be an integer") from None
            if pri < 0:
                raise RequestError(f"priority must be >= 0, got {pri}")
        ddl = payload.get("deadline_ms")
        if ddl is not None:
            try:
                ddl = float(ddl)
            except (TypeError, ValueError):
                raise RequestError(
                    "deadline_ms must be a number of milliseconds"
                ) from None
            if not (ddl > 0.0):
                raise RequestError(
                    f"deadline_ms must be > 0, got {ddl}"
                )

    def request_bucket(self, payload: dict) -> int:
        n = np.asarray(payload["input_ids"]).shape[0]
        n += np.asarray(payload.get("resume_tokens", ())).size
        if self._chunked_mode and n > self.buckets[-1]:
            return self.buckets[-1]  # queue key only: chunks split the rest
        return self.bucket_for(n)

    # -- the two dispatch points (decode-loop thread only: both swap the
    # -- engine's device-state refs, which is single-writer by contract) --

    def prefill(self, admissions: list[dict]) -> InFlightBatch:
        """Admit up to a tier of requests into their assigned slots.

        ``admissions`` rows: ``{"slot", "input_ids", "temperature",
        "seed"}``. Returns without blocking; ``fetch_step`` yields the
        [tier]-shaped first-token vector (real rows = admitted order)."""
        if self._chunked_mode:
            raise RuntimeError(
                "engine compiled in chunked-prefill mode (prefix cache / "
                "prefill_chunk); admissions go through prefill_chunks"
            )
        if len(admissions) > self.max_batch:
            raise ValueError(
                f"admitting {len(admissions)} exceeds max_batch "
                f"{self.max_batch}"
            )
        lens = [np.asarray(a["input_ids"]).shape[0] for a in admissions]
        L = self.bucket_for(max(lens))
        T = self.tier_for(len(admissions))
        key = ("prefill", T, L)

        def _make():
            return (
                np.zeros((T, L), np.int32),
                np.zeros((T, L), bool),
                np.full((T,), self.slots, np.int32),
                np.zeros((T,), np.int32),
                np.zeros((T,), np.float32),
                np.zeros((T,), np.int32),
            )

        ids, mask, slot_ix, lengths, temps, seeds = buffers = (
            self._take_buffers(key, _make)
        )
        ids.fill(0)
        mask.fill(False)
        slot_ix.fill(self.slots)  # out-of-pool: padding rows scatter-drop
        lengths.fill(0)
        temps.fill(0.0)
        seeds.fill(0)
        for r, (a, l) in enumerate(zip(admissions, lens)):
            ids[r, :l] = np.asarray(a["input_ids"], np.int32)
            mask[r, :l] = True
            slot_ix[r] = int(a["slot"])
            lengths[r] = l
            temps[r] = float(a.get("temperature", 0.0))
            seeds[r] = int(a.get("seed", 0))
        mask[len(admissions):, 0] = True
        t_assembled = time.monotonic()
        ck, cv, last, tok = self._prefill_compiled[T, L](
            self.params, self._cache_k, self._cache_v, self._last_token,
            jax.device_put(ids, self._rep), jax.device_put(mask, self._rep),
            jax.device_put(slot_ix, self._rep),
            jax.device_put(lengths, self._rep),
            jax.device_put(temps, self._rep),
            jax.device_put(seeds, self._rep),
        )
        self._cache_k, self._cache_v, self._last_token = ck, cv, last
        self._record_dispatch(T, L, len(admissions))
        return InFlightBatch(
            out={"tok": tok}, key=key, n=len(admissions),
            meta=[int(s) for s in slot_ix[: len(admissions)]],
            buffers=buffers, layout=self.layout, t_assembled=t_assembled,
        )

    def prefill_chunks(self, rows: list[dict]) -> InFlightBatch:
        """Dispatch ONE prefill chunk for up to a tier of admitted slots.

        ``rows``: ``{"slot", "input_ids" (the FULL prompt), "start",
        "n_tokens", "length", "chain" (pool block ids — non-empty only on
        a row's first chunk, when its matched prefix gathers),
        "temperature", "seed"}``. The executable slices nothing: the host
        stages ``input_ids[start : start + n_tokens]`` per row, pads to
        the smallest (tier, chunk-bucket) cell, and rows whose chunk
        completes the prompt sample their first token on-device (rows
        mid-prompt return garbage lanes the batcher ignores)."""
        if not self._chunked_mode:
            raise RuntimeError(
                "prefill_chunks needs chunked mode (prefix_cache_mb or "
                "prefill_chunk at construction)"
            )
        if len(rows) > self.max_batch:
            raise ValueError(
                f"admitting {len(rows)} exceeds max_batch {self.max_batch}"
            )
        T = self.tier_for(len(rows))
        C = self._chunk_bucket_for(max(int(r["n_tokens"]) for r in rows))
        M = self._max_chain
        key = ("chunk", T, C)

        def _make():
            return (
                np.zeros((T, C), np.int32),
                np.zeros((T,), np.int32),
                np.zeros((T,), np.int32),
                np.zeros((T, M), np.int32),
                np.zeros((T,), np.int32),
                np.full((T,), self.slots, np.int32),
                np.zeros((T,), np.float32),
                np.zeros((T,), np.int32),
            )

        ids, starts, lengths, chain, n_gather, slot_ix, temps, seeds = (
            buffers
        ) = self._take_buffers(key, _make)
        ids.fill(0)
        starts.fill(0)
        lengths.fill(0)
        chain.fill(0)
        n_gather.fill(0)
        slot_ix.fill(self.slots)  # out-of-pool: padding rows scatter-drop
        temps.fill(0.0)
        seeds.fill(0)
        for r, row in enumerate(rows):
            s0, n = int(row["start"]), int(row["n_tokens"])
            ids[r, :n] = np.asarray(
                row["input_ids"][s0:s0 + n], np.int32
            )
            starts[r] = s0
            lengths[r] = int(row["length"])
            blocks = row.get("chain") or ()
            if len(blocks) > M:
                raise ValueError(
                    f"prefix chain of {len(blocks)} exceeds max chain {M}"
                )
            chain[r, :len(blocks)] = blocks
            n_gather[r] = len(blocks)
            slot_ix[r] = int(row["slot"])
            temps[r] = float(row.get("temperature", 0.0))
            seeds[r] = int(row.get("seed", 0))
        t_assembled = time.monotonic()
        ck, cv, last, tok = self._chunk_compiled[T, C](
            self.params, self._cache_k, self._cache_v, self._last_token,
            self._pool_k, self._pool_v,
            jax.device_put(ids, self._rep),
            jax.device_put(starts, self._rep),
            jax.device_put(lengths, self._rep),
            jax.device_put(chain, self._rep),
            jax.device_put(n_gather, self._rep),
            jax.device_put(slot_ix, self._rep),
            jax.device_put(temps, self._rep),
            jax.device_put(seeds, self._rep),
        )
        self._cache_k, self._cache_v, self._last_token = ck, cv, last
        self._record_dispatch(T, C, len(rows))
        return InFlightBatch(
            out={"tok": tok}, key=key, n=len(rows),
            meta=[int(s) for s in slot_ix[: len(rows)]],
            buffers=buffers, layout=self.layout, t_assembled=t_assembled,
        )

    def insert_prefix(self, slot: int, blocks: list[tuple[int, int]]) -> None:
        """Publish a fully-prefilled slot's prefix pages into the pool:
        ``blocks`` are ``(block_id, block_index)`` pairs from
        ``KVBlockPool.insert``. Dispatch-only (nothing to fetch — the
        batcher never blocks on it); stream order guarantees the pages
        hold the prompt's K/V before any later chunk can gather them."""
        if self.prefix_cache is None:
            raise RuntimeError("engine has no prefix cache")
        M = self._max_chain
        if len(blocks) > M:
            raise ValueError(
                f"inserting {len(blocks)} blocks exceeds max chain {M}"
            )
        ids = np.full((M,), self._pool_blocks, np.int32)  # sentinel: drop
        pos = np.zeros((M,), np.int32)
        for j, (bid, bix) in enumerate(blocks):
            ids[j] = int(bid)
            pos[j] = int(bix)
        pk, pv, ck, cv = self._insert_compiled(
            self._pool_k, self._pool_v, self._cache_k, self._cache_v,
            jax.device_put(np.int32(slot), self._rep),
            jax.device_put(ids, self._rep),
            jax.device_put(pos, self._rep),
        )
        self._pool_k, self._pool_v = pk, pv
        self._cache_k, self._cache_v = ck, cv

    # -- disaggregated-serving page transfer (serve/disagg.py) ----------

    def export_prefix_pages(self, blocks: list[int]):
        """Gather published pool pages for a PINNED chain of block ids:
        returns device arrays ``[nl, max_chain, block_tokens, heads,
        head_dim]`` (k, v) — the chain's pages in order, pad lanes
        repeating block 0 (the importer's sentinel ids drop them).
        Requires ``kv_transfer=True`` at construction (the AOT export
        cell — same no-trace rule as every other dispatch).

        Safe OFF the decode-loop thread, unlike every dispatch method: it
        never swaps the engine's device-state refs, and the caller holds
        a ``KVBlockPool.match`` pin, so the gathered blocks hold the
        prompt's bytes for the duration. The one cross-thread hazard is
        the pool ref itself: a concurrent publish DONATES the buffer this
        thread just read, and a dispatch that loses that race raises
        jax's deleted-array error — re-read the swapped-in ref and
        retry (bounded; the pin means any ref's content is equally
        correct)."""
        if self._export_compiled is None:
            raise RuntimeError(
                "engine built without kv_transfer=True (no pool-export "
                "cell)"
            )
        M = self._max_chain
        if len(blocks) > M:
            raise ValueError(
                f"exporting {len(blocks)} blocks exceeds max chain {M}"
            )
        idx = np.zeros((M,), np.int32)
        idx[: len(blocks)] = blocks
        jdx = jax.device_put(idx, self._rep)
        for attempt in range(5):
            pk, pv = self._pool_k, self._pool_v
            try:
                return self._export_compiled(pk, pv, jdx)
            # jax surfaces the dead-buffer dispatch as RuntimeError from
            # the python call path and ValueError (INVALID_ARGUMENT) from
            # the C++ fast path — match the message, not the type.
            except (RuntimeError, ValueError) as e:
                dead = "deleted" in str(e) or "donated" in str(e)
                if not dead or attempt == 4:
                    raise
                # A publish is mid-swap on the loop thread: the donation
                # lands before the ref swap, so an immediate re-read can
                # still see the dead ref. Back off past the swap window.
                time.sleep(0.002 * (attempt + 1))
        raise AssertionError("unreachable")

    def import_prefix_pages(
        self, blocks: list[tuple[int, int]], pages_k, pages_v
    ) -> None:
        """Adopt transferred KV pages into this engine's prefix pool:
        ``blocks`` are ``(block_id, chain_index)`` pairs from
        ``KVBlockPool.insert`` on THIS engine's pool — chain_index picks
        the page lane out of the received stage (a chain partially cached
        here imports only its new blocks); ``pages_*`` are ``[nl,
        max_chain, block_tokens, heads, head_dim]`` stages (host numpy
        from the wire path, or device arrays from the D2D path).
        Decode-loop thread only — it swaps the pool refs, like
        ``insert_prefix``; dispatch-only, nothing to fetch. Requires
        ``kv_transfer=True`` at construction (the AOT import cell)."""
        if self._import_compiled is None:
            raise RuntimeError(
                "engine built without kv_transfer=True (no pool-import "
                "cell)"
            )
        M = self._max_chain
        if len(blocks) > M:
            raise ValueError(
                f"importing {len(blocks)} blocks exceeds max chain {M}"
            )
        ids = np.full((M,), self._pool_blocks, np.int32)  # sentinel: drop
        for bid, cix in blocks:
            if not 0 <= int(cix) < M:
                raise ValueError(
                    f"chain index {cix} outside the {M}-lane page stage"
                )
            ids[int(cix)] = int(bid)
        pk, pv = self._import_compiled(
            self._pool_k, self._pool_v,
            jax.device_put(pages_k, self._cache_sharding),
            jax.device_put(pages_v, self._cache_sharding),
            jax.device_put(ids, self._rep),
        )
        self._pool_k, self._pool_v = pk, pv

    def page_meta(self) -> dict:
        """Static page-geometry digest the wire format stamps into its
        header (serve/disagg.py) — two pools are transfer-compatible iff
        these match."""
        if self.prefix_cache is None:
            raise RuntimeError("engine has no prefix cache")
        nl, _, bt, heads, hd = _kv_leaf(self._pool_k).shape
        return {
            "num_layers": int(nl),
            "block_tokens": int(bt),
            "heads": int(heads),
            "head_dim": int(hd),
            # int8 pools report int8 (the q payload's dtype): fp32 and int8
            # peers must refuse each other's pages fail-closed.
            "dtype": str(np.dtype(_kv_leaf(self._pool_k).dtype).name),
            "max_chain": int(self._max_chain),
        }

    # -- live-stream migration (serve/disagg.py stream wire) ------------

    def export_slot_pages(self, slot: int):
        """Checkpoint ONE live slot's KV lane: returns device arrays
        ``[nl, cache_len, heads, head_dim]`` (k, v). Decode-loop thread
        only, between dispatches with nothing in flight — the batcher's
        ``export_streams`` guarantees the lane is settled, so unlike
        ``export_prefix_pages`` there is no donation race to retry.
        Requires ``stream_migrate=True`` at construction."""
        if self._slot_export_compiled is None:
            raise RuntimeError(
                "engine built without stream_migrate=True (no slot-export "
                "cell)"
            )
        return self._slot_export_compiled(
            self._cache_k, self._cache_v,
            jax.device_put(np.int32(slot), self._rep),
        )

    def import_slot_pages(self, slot: int, pages_k, pages_v,
                          last_token: int) -> None:
        """Adopt a migrated stream's KV lane into ``slot`` and seed
        ``last_token[slot]`` so the next decode step continues the
        generation. ``pages_*`` are full ``[nl, cache_len, heads,
        head_dim]`` stages (the wire path pads short payloads back up —
        trailing positions are dead weight the causal mask never reads).
        Decode-loop thread only: swaps the cache refs like every
        dispatch. Requires ``stream_migrate=True`` at construction."""
        if self._slot_import_compiled is None:
            raise RuntimeError(
                "engine built without stream_migrate=True (no slot-import "
                "cell)"
            )
        ck, cv, last = self._slot_import_compiled(
            self._cache_k, self._cache_v, self._last_token,
            jax.device_put(pages_k, self._slot_stage_sharding),
            jax.device_put(pages_v, self._slot_stage_sharding),
            jax.device_put(np.int32(slot), self._rep),
            jax.device_put(np.int32(last_token), self._rep),
        )
        self._cache_k, self._cache_v, self._last_token = ck, cv, last

    def stream_page_meta(self) -> dict:
        """Slot-lane geometry digest the stream wire format stamps into
        its header — two engines can ship live streams between each other
        iff these match (``cache_len`` may differ: the receiver re-pads,
        refusing only streams longer than its own lanes)."""
        nl, _, cache_len, heads, hd = _kv_leaf(self._cache_k).shape
        return {
            "num_layers": int(nl),
            "cache_len": int(cache_len),
            "heads": int(heads),
            "head_dim": int(hd),
            "dtype": str(np.dtype(_kv_leaf(self._cache_k).dtype).name),
        }

    def decode(self, lengths, active, temps, seeds) -> InFlightBatch:
        """Dispatch ONE decode step over the full slot table (host arrays
        are snapshots; the batcher advances its lengths at dispatch so
        steps pipeline). Returns without blocking."""
        key = ("decode",)

        def _make():
            s = self.slots
            return (
                np.zeros((s,), np.int32),
                np.zeros((s,), bool),
                np.zeros((s,), np.float32),
                np.zeros((s,), np.int32),
            )

        blen, bact, btmp, bseed = buffers = self._take_buffers(key, _make)
        np.copyto(blen, lengths)
        np.copyto(bact, active)
        np.copyto(btmp, temps)
        np.copyto(bseed, seeds)
        t_assembled = time.monotonic()
        ck, cv, last, tok = self._decode_compiled(
            self.params, self._cache_k, self._cache_v, self._last_token,
            jax.device_put(blen, self._rep), jax.device_put(bact, self._rep),
            jax.device_put(btmp, self._rep), jax.device_put(bseed, self._rep),
        )
        self._cache_k, self._cache_v, self._last_token = ck, cv, last
        return InFlightBatch(
            out={"tok": tok}, key=key, n=int(np.sum(bact)), meta=None,
            buffers=buffers, layout=self.layout, t_assembled=t_assembled,
        )

    def verify(self, drafts, lengths, n_input, temps, seeds) -> InFlightBatch:
        """Dispatch ONE speculative verify step over the full slot table.

        ``drafts [slots, k]``: host-proposed candidate tokens;
        ``n_input``: drafted+1 for verifying lanes, 0 for everyone else
        (idle slots AND slots riding the plain-decode path this step).
        Unlike ``decode``, the batcher advances a verifying slot's length
        at FETCH, not dispatch — the accepted count is data-dependent — so
        a verifying slot never re-dispatches until its verdict lands.
        Returns without blocking; ``fetch_step`` yields the [slots, k+1]
        sampled-token matrix (the host re-derives the accepted prefix from
        its own drafts)."""
        if self._verify_compiled is None:
            raise RuntimeError(
                "engine built without speculation (spec_tokens=0)"
            )
        key = ("verify",)

        def _make():
            s = self.slots
            return (
                np.zeros((s, self.spec_tokens), np.int32),
                np.zeros((s,), np.int32),
                np.zeros((s,), np.int32),
                np.zeros((s,), np.float32),
                np.zeros((s,), np.int32),
            )

        bdr, blen, bnin, btmp, bseed = buffers = self._take_buffers(
            key, _make
        )
        np.copyto(bdr, drafts)
        np.copyto(blen, lengths)
        np.copyto(bnin, n_input)
        np.copyto(btmp, temps)
        np.copyto(bseed, seeds)
        t_assembled = time.monotonic()
        ck, cv, last, tok = self._verify_compiled(
            self.params, self._cache_k, self._cache_v, self._last_token,
            jax.device_put(bdr, self._rep), jax.device_put(blen, self._rep),
            jax.device_put(bnin, self._rep), jax.device_put(btmp, self._rep),
            jax.device_put(bseed, self._rep),
        )
        self._cache_k, self._cache_v, self._last_token = ck, cv, last
        return InFlightBatch(
            out={"tok": tok}, key=key, n=int(np.sum(bnin > 0)), meta=None,
            buffers=buffers, layout=self.layout, t_assembled=t_assembled,
        )

    def fetch_step(self, inflight: InFlightBatch) -> np.ndarray:
        """Block on a step's sampled-token vector — or a verify step's
        [slots, k+1] token matrix — the ONLY device_get on the decode path
        (everything else stays resident; analysis/baseline.json designates
        this method for JL003, and the verify path reuses it rather than
        growing a second blocking point)."""
        tok = np.asarray(jax.device_get(inflight.out["tok"]))
        inflight.t_got = time.monotonic()
        self._give_buffers(inflight.key, inflight.buffers)
        return tok


class ImageClassifierEngine(_AotEngine):
    """Top-k classification over a trained image-classifier checkpoint
    (LeNet/ResNet/Inception — anything with ``apply(vars, image,
    train=False) -> logits``).

    Request payload: ``image`` ``[H, W, C]`` float32 at the engine's
    geometry (the model's training geometry — there is one image "bucket").
    Response: ``top_ids [k]``, ``top_probs [k]``.
    """

    def __init__(
        self,
        model,
        params,
        model_state=None,
        mesh=None,
        *,
        image_shape: tuple[int, int, int],
        max_batch: int = 8,
        batch_tiers: tuple[int, ...] | None = None,
        top_k: int = 5,
        memory=None,
    ):
        super().__init__(mesh, max_batch, batch_tiers, memory=memory)
        self.model = model
        self.image_shape = tuple(image_shape)
        self.top_k = top_k
        self.variables = self._place(
            {"params": params, **(model_state or {})}
        )
        self.memory.register_tree("image_params", self.variables)
        self._plan_cells(len(self.batch_tiers))
        self._compiled = {
            T: self._compile_cell(
                f"image/{self.layout}/t{T}",
                lambda T=T: (
                    jax.jit(self._forward)
                    .lower(
                        self.variables,
                        self._struct((T, *self.image_shape), jnp.float32, T),
                    )
                    .compile()
                ),
            )
            for T in self.batch_tiers
        }
        logger.info(
            "image engine ready: shape=%s tiers=%s top_k=%d",
            self.image_shape, self.batch_tiers, top_k,
        )

    def _forward(self, variables, image):
        logits = self.model.apply(variables, image, train=False)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        k = min(self.top_k, probs.shape[-1])
        top_probs, top_ids = jax.lax.top_k(probs, k)
        return {"top_ids": top_ids.astype(jnp.int32), "top_probs": top_probs}

    def validate(self, payload: dict) -> None:
        img = np.asarray(payload.get("image", ()))
        if img.shape != self.image_shape:
            raise RequestError(
                f"image shape {img.shape} != engine geometry {self.image_shape}"
            )

    def request_bucket(self, payload: dict) -> int:
        return 0  # one geometry: every request shares the single bucket

    def dispatch(self, payloads: list[dict]) -> InFlightBatch:
        if len(payloads) > self.max_batch:
            raise ValueError(
                f"batch of {len(payloads)} exceeds max_batch {self.max_batch}"
            )
        T = self.tier_for(len(payloads))

        def _make():
            return (np.zeros((T, *self.image_shape), np.float32),)

        (imgs,) = buffers = self._take_buffers((T,), _make)
        imgs.fill(0.0)
        for r, p in enumerate(payloads):
            imgs[r] = np.asarray(p["image"], np.float32)
        t_assembled = time.monotonic()
        out = self._compiled[T](self.variables, self._put(imgs, T))
        self._record_dispatch(T, None, len(payloads))
        return InFlightBatch(
            out=out, key=(T,), n=len(payloads), meta=[], buffers=buffers,
            layout=self.layout, t_assembled=t_assembled,
        )

    def fetch(self, inflight: InFlightBatch) -> list[dict]:
        out = jax.device_get(inflight.out)
        inflight.t_got = time.monotonic()
        self._give_buffers(inflight.key, inflight.buffers)
        return [
            {"top_ids": out["top_ids"][r], "top_probs": out["top_probs"][r]}
            for r in range(inflight.n)
        ]
