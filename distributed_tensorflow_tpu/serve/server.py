"""Serving front ends: in-process :class:`Client` and a stdlib HTTP server.

``Client`` is the canonical surface — validate-at-submit, enqueue into the
:class:`DynamicBatcher`, block on the Future. The HTTP server is a thin
JSON adapter over the same client (``ThreadingHTTPServer``: one thread per
connection blocks on its Future while the flusher thread batches across
them — exactly the concurrency the micro-batcher exists to exploit).

Routes::

    GET  /healthz    -> readiness probe: 200 {"status": "ready", ...} only
                        when the stack serves; 503 with the state string
                        (starting/degraded/draining/closed) otherwise —
                        the router contract in docs/DEPLOY.md
    GET  /metrics    -> ServeMetrics.snapshot() as JSON;
                        ``?format=prom`` -> Prometheus text exposition
                        (format 0.0.4) of every family + SLO + health
    GET  /sloz       -> declared SLOs: per-window attainment, error-budget
                        burn rates, ok/warn/page verdicts
    GET  /statusz    -> live status: queue depths, in-flight batches,
                        tier/bucket occupancy, rejections by cause,
                        recent-span summary
    GET  /tracez?spans=N -> drain the span ring buffer as Chrome
                        trace-event JSON (Perfetto / chrome://tracing)
    GET  /memz       -> device-memory accounting: per-component HBM
                        reservations, per-device memory_stats() where the
                        backend reports them, headroom + reconciliation
    GET  /compilez   -> AOT-grid compile digest: cells total/compiled/
                        failed, cumulative compile seconds, per-cell
                        records, the coldest cell
    POST /debugz/dump-> force a flight-recorder dump (bypasses the rate
                        limit); answers the dump path, or the full payload
                        when no --dump-dir is configured
    POST /profilez?ms=N -> capture a bounded jax.profiler window on the
                        RUNNING server (needs trace_dir)
    POST /drainz     -> flip to draining: /healthz goes 503 so the router
                        stops routing here, while in-flight + already-
                        queued requests still complete
    POST /v1/mlm     -> BERT: pred_ids / score / nsp_probs for one example
    POST /v1/embed   -> BERT: pooled [CLS] embedding for one example
    POST /v1/classify-> image: top-k ids/probs for one example
    POST /v1/generate-> causal LM: generated tokens for one prompt
                        (continuous batching: the request joins the
                        in-flight decode batch between steps; optional
                        "priority" class + "deadline_ms" TTFT deadline
                        drive EDF admission and slot preemption when the
                        batcher runs --sched edf / --preempt)

Every request gets a ``request_id`` (honoring an ``X-Request-Id`` header
when the client sends one) that rides through the batcher into the engine
spans and comes back in the response — success bodies also carry
``phases``, the per-request latency breakdown
(``queue_wait/batch_assemble/dispatch/device/fetch``, milliseconds).

Error mapping: RequestError -> 400; Backpressure -> 429 + ``Retry-After``;
:class:`Draining` (submit during drain) -> 503; anything the engine raises
mid-batch -> 500. All error bodies carry the ``request_id``, so shed or
failed load is attributable in client logs and server traces alike.
"""

from __future__ import annotations

import itertools
import json
import logging
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import numpy as np

from distributed_tensorflow_tpu.obs.export import (
    PROM_CONTENT_TYPE,
    prometheus_text,
)
from distributed_tensorflow_tpu.obs.flightrec import NULL_RECORDER
from distributed_tensorflow_tpu.obs.health import HealthTracker
from distributed_tensorflow_tpu.obs.memory import default_registry
from distributed_tensorflow_tpu.obs.metrics import ServeMetrics
from distributed_tensorflow_tpu.obs.slo import SloSpec, SloTracker
from distributed_tensorflow_tpu.obs.timeseries import bounds_with
from distributed_tensorflow_tpu.obs.trace import Tracer
from distributed_tensorflow_tpu.serve.batcher import (
    BatcherConfig,
    ContinuousBatcher,
    DynamicBatcher,
)
from distributed_tensorflow_tpu.serve.engine import RequestError

logger = logging.getLogger(__name__)


class Draining(Exception):
    """A submit arrived while the stack was draining (or closed): new work
    is shed AT THE DOOR with an attributable ``request_id`` — it must not
    enqueue behind work the drain is waiting out, and it must never hang.
    The HTTP layer maps this to 503 (the drain contract: same code the
    router already sees from ``/healthz``)."""

    def __init__(self, request_id: str, state: str = "draining"):
        super().__init__(f"shedding: server is {state}")
        self.request_id = request_id
        self.state = state


class Client:
    """In-process serving client: ``submit`` returns a Future, ``call``
    blocks for the result. Payloads validate BEFORE they enqueue so a
    malformed request fails alone instead of poisoning its batch.

    The resolved Future carries the request's observability sidecar:
    ``future.request_id`` and ``future.phases`` (the per-phase latency
    breakdown in seconds) — results themselves stay exactly what the
    engine returned.
    """

    def __init__(
        self,
        engine,
        config: BatcherConfig | None = None,
        metrics: ServeMetrics | None = None,
        tracer: Tracer | None = None,
        slo: SloSpec | None = None,
        admission: str = "continuous",
        recorder=None,
        memory=None,
        warmup_ready_fraction: float = 1.0,
        tag: str | None = None,
    ):
        self.engine = engine
        # Deployment identity (cli/serve.py sets "ckpt-<step>" from the
        # restored checkpoint): surfaced on /healthz so the router's
        # rolling hot-swap can VERIFY each replica came back on the new
        # checkpoint instead of trusting the restart.
        self.tag = tag
        self._shed_ids = itertools.count()
        self.recorder = recorder if recorder is not None else NULL_RECORDER
        # The memory registry /memz answers from: an injected one, the
        # engine's (real engines register their footprints with the
        # process-wide default), or the default for bare stubs.
        self.memory = (
            memory
            if memory is not None
            else getattr(engine, "memory", None) or default_registry()
        )
        if metrics is None:
            # Insert the SLO latency threshold as an explicit histogram
            # bound so windowed attainment at the threshold is EXACT.
            threshold_s = (slo.latency_threshold_ms / 1e3) if slo else 0.0
            metrics = ServeMetrics(latency_bounds=bounds_with(threshold_s))
        self.metrics = metrics
        self.tracer = tracer if tracer is not None else Tracer()
        if config is None:
            config = BatcherConfig(max_batch=engine.max_batch)
        elif config.max_batch > engine.max_batch:
            raise ValueError(
                f"batcher max_batch {config.max_batch} exceeds engine "
                f"max_batch {engine.max_batch}"
            )
        # Engines that expose the split hot path (dispatch/fetch) get the
        # overlapped batcher; engines that expose a bucket key get
        # bucket-aware queues when the config asks for them. Stub engines
        # with only run_batch keep the classic serial path. Decode engines
        # (prefill + per-step decode over a slot table) get the
        # continuous batcher — ``admission`` picks continuous vs the
        # flush-batching baseline, and bucket_queues is moot (admission
        # groups are tiny and pad per-group, not per-flush).
        if getattr(engine, "metrics", False) is None:
            engine.metrics = self.metrics  # per-tier/bucket instruments
        if hasattr(engine, "prefill") and hasattr(engine, "decode"):
            self.batcher = ContinuousBatcher(
                engine,
                config,
                metrics=self.metrics,
                admission=admission,
                tracer=self.tracer,
                recorder=self.recorder,
                layout=getattr(engine, "layout", ""),
            )
        else:
            bucket_for = (
                getattr(engine, "request_bucket", None)
                if config.bucket_queues
                else None
            )
            if config.bucket_queues and bucket_for is None:
                raise ValueError(
                    "bucket_queues=True needs an engine with request_bucket()"
                )
            self.batcher = DynamicBatcher(
                engine.run_batch,
                config,
                metrics=self.metrics,
                dispatch=getattr(engine, "dispatch", None),
                fetch=getattr(engine, "fetch", None),
                bucket_for=bucket_for,
                tracer=self.tracer,
                recorder=self.recorder,
                layout=getattr(engine, "layout", ""),
            )
        # SLO + readiness: the tracker reads the windowed families and the
        # batcher's live status at probe time — no thread, nothing to join.
        self.slo = SloTracker(
            self.metrics, slo or SloSpec(), recorder=self.recorder
        )
        gs = getattr(engine, "grid_status", None)
        self._grid_status = gs if callable(gs) else None
        self.health = HealthTracker(
            status_fn=self.batcher.status,
            metrics=self.metrics if self.metrics.windowed else None,
            slo=self.slo if self.slo.spec.enabled else None,
            warmup_fn=(
                (lambda: self._grid_status()["warm_fraction"])
                if self._grid_status is not None else None
            ),
            warmup_target=warmup_ready_fraction,
            recorder=self.recorder,
        )
        if self._grid_status is None:
            # No grid to warm (stub / legacy engine): serve immediately.
            # Grid engines instead stay ``starting`` until a probe sees the
            # warm fraction reach the target (docs/DEPLOY.md contract) —
            # synchronous-compiling engines are warm by the time we get
            # here, so their first probe promotes.
            self.health.mark_ready()
        self.recorder.attach(
            metrics_fn=self.metrics.snapshot,
            memz_fn=self.memory.snapshot,
            compilez_fn=self.grid_status,
            tracer_fn=self.tracer.summary,
        )

    def grid_status(self) -> dict:
        """The engine's AOT-grid compile digest (an always-warm placeholder
        for engines without one, so /compilez answers on every stack)."""
        if self._grid_status is not None:
            return self._grid_status()
        return {
            "cells_total": 0,
            "cells_compiled": 0,
            "cells_failed": 0,
            "compile_seconds_total": 0.0,
            "warm_fraction": 1.0,
            "coldest_cell": None,
            "cells": [],
        }

    def submit(self, payload: dict, request_id: str | None = None) -> Future:
        state = self.health.lifecycle
        if state in ("draining", "closed"):
            # Shed at the door, BEFORE validation or enqueue: a drain must
            # finish the work it already owns, not accept more. The check
            # races benignly with a concurrent drain flip — a request that
            # slips past still completes under the drain contract.
            rid = request_id or f"shed-{next(self._shed_ids):06d}"
            self.metrics.rejected_by_cause.inc(state)
            self.tracer.instant(
                "rejected", "serve", request_id=rid, cause=state,
            )
            self.recorder.record("request_reject", rid, cause=state)
            raise Draining(rid, state)
        try:
            self.engine.validate(payload)  # RequestError before enqueue
        except RequestError:
            self.metrics.rejected_by_cause.inc("validation")
            self.tracer.instant(
                "rejected", "serve", request_id=request_id,
                cause="validation",
            )
            raise
        return self.batcher.submit(payload, request_id=request_id)

    def call(self, payload: dict, timeout: float | None = 60.0) -> dict:
        return self.submit(payload).result(timeout=timeout)

    def start_draining(self) -> None:
        """Flip /healthz to 503 (state ``draining``) WITHOUT closing: the
        router stops sending traffic while queued work still completes.
        Idempotent from ready/starting; a no-op once already draining."""
        if self.health.lifecycle in ("starting", "ready"):
            try:
                self.health.mark_draining()
            except ValueError:
                pass  # concurrent drain/close won the transition race

    def close(self) -> None:
        self.health.mark_closed()
        self.batcher.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _jsonable(obj):
    """numpy -> plain python, recursively (json.dumps chokes on ndarrays)."""
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, np.generic):
        return obj.item()
    return obj


def build_http_server(
    client: Client,
    host: str = "127.0.0.1",
    port: int = 0,
    *,
    trace_dir: str | None = None,
    kv_receiver=None,
    transfer_budget=None,
    stream_receiver=None,
    migrator=None,
):
    """Build (not start) a ``ThreadingHTTPServer`` over ``client``.

    ``port=0`` binds an ephemeral port (tests read ``server.server_address``).
    Call ``serve_forever()`` to run; ``shutdown()`` to stop. ``trace_dir``
    is where ``POST /profilez`` drops its ``jax.profiler`` captures (the
    endpoint answers 503 without one).

    Disaggregated decode roles pass ``kv_receiver`` (a ``bytes -> dict``
    callable from :func:`~distributed_tensorflow_tpu.serve.disagg.make_kv_receiver`)
    to mount ``POST /v1/kv_transfer`` — octet-stream wire buffers, 400 on
    a ``WireError`` refusal, 429 on a budget shed — and ``transfer_budget``
    (a :class:`~distributed_tensorflow_tpu.serve.disagg.TransferBudget`)
    to surface the bytes-in-flight digest under ``/statusz``.

    Live stream migration (ISSUE 18) adds two more optional mounts:
    ``stream_receiver`` (a
    :class:`~distributed_tensorflow_tpu.serve.disagg.StreamReceiver`)
    mounts ``POST /v1/stream_migrate`` — same octet-stream/400/429
    contract as kv_transfer — plus ``POST /v1/stream_wait`` (JSON
    ``{"request_id": ..}``) which blocks for an adopted stream's finished
    generation and 404s for unknown ids (the caller's cue to replay with
    ``resume_tokens``). ``migrator`` (a ``targets -> dict`` callable
    wrapping :func:`~distributed_tensorflow_tpu.serve.disagg.migrate_streams`)
    mounts ``POST /migratez`` — export every live stream here and push
    them to the given ``[[host, port], ..]`` survivors.
    """

    class Handler(BaseHTTPRequestHandler):
        # Route table maps a POST path to "which keys of the engine result
        # this endpoint exposes" — both BERT routes run the SAME executable,
        # /v1/embed just answers with less.
        _routes = {
            "/v1/mlm": ("pred_ids", "score", "nsp_probs", "bucket"),
            "/v1/embed": ("embedding", "bucket"),
            "/v1/classify": ("top_ids", "top_probs"),
            # status/target surface ONLY when a drain-with-deadline
            # migrated the stream away mid-generation: the router sees
            # status == "migrated" and collects the finished stream from
            # the target via /v1/stream_wait (ordinary results carry
            # neither key, so clients see no change).
            "/v1/generate": ("tokens", "n_tokens", "prompt_len", "bucket",
                             "status", "target"),
        }

        def log_message(self, fmt, *args):  # route access logs into logging
            logger.debug("http: " + fmt, *args)

        def _reply(self, code: int, body: dict, headers: dict | None = None):
            data = json.dumps(_jsonable(body)).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

        def _reply_text(self, code: int, text: str, content_type: str):
            data = text.encode()
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _statusz(self) -> dict:
            snap = client.metrics.snapshot()
            tracer = client.tracer
            mesh_info = getattr(client.engine, "mesh_info", None)
            return {
                "engine": type(client.engine).__name__,
                "tag": client.tag,
                # Mesh topology digest: layout label, axis sizes, devices
                # one batch spans (None for stub engines without a mesh).
                "mesh": mesh_info() if callable(mesh_info) else None,
                # Batching mode (flush vs continuous) + slot occupancy for
                # decode engines — the router contract's generative fields.
                "batcher": client.batcher.status(),
                "queue_depth": snap["queue_depth"],
                "in_flight": snap["in_flight"],
                "requests": snap["requests"],
                "rejected_by_cause": snap["rejected_by_cause"],
                "errors": snap["errors"],
                "tier_occupancy": snap["tier_occupancy"],
                "bucket_hits": snap["bucket_hits"],
                "layout_tier_hits": snap["layout_tier_hits"],
                "phase_ms": snap["phase_ms"],
                "tracer": tracer.status(),
                "recent_spans": tracer.summary(),
                # Warmup digest (per-cell records live on /compilez) + the
                # flight recorder's ring/dump counters.
                "grid": {
                    k: v
                    for k, v in client.grid_status().items()
                    if k != "cells"
                },
                "flight_recorder": client.recorder.status(),
                **(
                    {"kv_transfer": transfer_budget.digest()}
                    if transfer_budget is not None else {}
                ),
                **(
                    {"stream_migrate": stream_receiver.digest()}
                    if stream_receiver is not None else {}
                ),
            }

        def do_GET(self):
            url = urlparse(self.path)
            if url.path == "/healthz":
                code, body = client.health.probe()
                body["engine"] = type(client.engine).__name__
                body["tag"] = client.tag
                self._reply(code, body)
            elif url.path == "/metrics":
                q = parse_qs(url.query)
                if q.get("format", [""])[0] == "prom":
                    self._reply_text(
                        200,
                        prometheus_text(
                            client.metrics,
                            slo=(
                                client.slo
                                if client.slo.spec.enabled
                                else None
                            ),
                            health=client.health,
                            memory=client.memory,
                            grid=client.grid_status(),
                        ),
                        PROM_CONTENT_TYPE,
                    )
                else:
                    self._reply(200, client.metrics.snapshot())
            elif url.path == "/sloz":
                state, _ = client.health.state()
                self._reply(
                    200, {"health": state, **client.slo.report()}
                )
            elif url.path == "/statusz":
                self._reply(200, self._statusz())
            elif url.path == "/memz":
                self._reply(200, client.memory.snapshot())
            elif url.path == "/compilez":
                self._reply(200, client.grid_status())
            elif url.path == "/tracez":
                q = parse_qs(url.query)
                try:
                    n = int(q["spans"][0]) if "spans" in q else None
                except ValueError:
                    self._reply(400, {"error": "spans must be an integer"})
                    return
                spans = client.tracer.drain(n)
                self._reply(200, client.tracer.chrome_json(spans))
            else:
                self._reply(404, {"error": f"no route {url.path}"})

        def _profilez(self, url) -> None:
            if trace_dir is None:
                self._reply(
                    503,
                    {"error": "profiling disabled: server built without "
                              "trace_dir (pass --trace-dir)"},
                )
                return
            q = parse_qs(url.query)
            try:
                ms = float(q["ms"][0]) if "ms" in q else 500.0
            except ValueError:
                self._reply(400, {"error": "ms must be a number"})
                return
            from distributed_tensorflow_tpu.obs.profile import profile_window

            # Blocks THIS handler thread for the window; the serving hot
            # path keeps running underneath — that is the point: the
            # capture sees live traffic.
            self._reply(200, profile_window(trace_dir, ms))

        def do_POST(self):
            url = urlparse(self.path)
            if url.path == "/profilez":
                self._profilez(url)
                return
            if url.path == "/v1/kv_transfer":
                if kv_receiver is None:
                    self._reply(
                        503,
                        {"error": "kv transfer disabled: server built "
                                  "without a receiver (decode role only)"},
                    )
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    out = kv_receiver(self.rfile.read(n))
                except ValueError as e:  # WireError: refuse, don't adopt
                    self._reply(400, {"error": str(e)})
                except Exception as e:  # noqa: BLE001 — budget shed or adoption failure
                    retry = getattr(e, "retry_after_s", None)
                    if retry is not None:
                        self._reply(
                            429,
                            {"error": str(e), "retry_after_s": retry},
                            headers={"Retry-After": f"{retry:.3f}"},
                        )
                    else:
                        logger.exception("kv transfer failed")
                        client.recorder.record(
                            "server_error", "", error=type(e).__name__,
                        )
                        self._reply(500, {"error": str(e)})
                else:
                    self._reply(200, out)
                return
            if url.path == "/v1/stream_migrate":
                if stream_receiver is None:
                    self._reply(
                        503,
                        {"error": "stream migration disabled: server built "
                                  "without a stream receiver"},
                    )
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    out = stream_receiver(self.rfile.read(n))
                except ValueError as e:  # WireError: refuse, don't adopt
                    self._reply(400, {"error": str(e)})
                except Exception as e:  # noqa: BLE001 — budget shed or adoption failure
                    retry = getattr(e, "retry_after_s", None)
                    if retry is not None:
                        self._reply(
                            429,
                            {"error": str(e), "retry_after_s": retry},
                            headers={"Retry-After": f"{retry:.3f}"},
                        )
                    else:
                        logger.exception("stream migrate failed")
                        client.recorder.record(
                            "server_error", "", error=type(e).__name__,
                        )
                        self._reply(500, {"error": str(e)})
                else:
                    self._reply(200, out)
                return
            if url.path == "/v1/stream_wait":
                if stream_receiver is None:
                    self._reply(
                        503,
                        {"error": "stream migration disabled: server built "
                                  "without a stream receiver"},
                    )
                    return
                rid = None
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(n) or b"{}")
                    rid = payload.get("request_id")
                    if not rid:
                        self._reply(
                            400, {"error": "stream_wait needs a request_id"}
                        )
                        return
                    result = stream_receiver.wait(
                        rid, float(payload.get("timeout_s", 60.0))
                    )
                except json.JSONDecodeError as e:
                    self._reply(400, {"error": f"bad JSON: {e}"})
                except KeyError:
                    # Unknown id: this replica never adopted the stream
                    # (or already handed its result out) — the caller's
                    # cue to replay with resume_tokens.
                    self._reply(
                        404,
                        {"error": f"no adopted stream {rid!r} here",
                         "request_id": rid},
                    )
                except (FutureTimeout, TimeoutError):
                    self._reply(
                        504,
                        {"error": "stream still generating",
                         "request_id": rid},
                    )
                except Exception as e:  # noqa: BLE001 — the resumed stream failed
                    logger.exception("stream_wait %s failed", rid)
                    self._reply(500, {"error": str(e), "request_id": rid})
                else:
                    fields = self._routes["/v1/generate"]
                    body = {k: result[k] for k in fields if k in result}
                    body["request_id"] = rid
                    self._reply(200, body)
                return
            if url.path == "/migratez":
                if migrator is None:
                    self._reply(
                        503,
                        {"error": "stream migration disabled: server built "
                                  "without a migrator"},
                    )
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(n) or b"{}")
                    targets = [
                        (str(t[0]), int(t[1]))
                        for t in payload.get("targets", ())
                    ]
                    out = migrator(targets)
                except (ValueError, TypeError, IndexError,
                        json.JSONDecodeError) as e:
                    self._reply(400, {"error": str(e)})
                except Exception as e:  # noqa: BLE001
                    logger.exception("stream migration failed")
                    client.recorder.record(
                        "server_error", "", error=type(e).__name__,
                    )
                    self._reply(500, {"error": str(e)})
                else:
                    self._reply(200, out)
                return
            if url.path == "/drainz":
                client.start_draining()
                code, body = client.health.probe()
                # Drain progress (ISSUE 18 satellite): why is this drain
                # slow, and how much work remains — the router reads the
                # same numbers to decide migrate-vs-wait.
                st = client.batcher.status()
                self._reply(200, {
                    "draining": True,
                    "progress": {
                        "slots_active": st.get("slots_active", 0),
                        "queued": st.get("queue_depth", 0),
                        "in_flight": st.get("in_flight", 0),
                        "tokens_remaining": st.get("tokens_remaining", 0),
                    },
                    **body,
                })
                return
            if url.path == "/debugz/dump":
                if not client.recorder.enabled:
                    self._reply(
                        503,
                        {"error": "flight recorder disabled "
                                  "(pass --flight-buffer > 0)"},
                    )
                    return
                out = client.recorder.dump("manual", force=True)
                if isinstance(out, dict):
                    # No dump_dir configured: answer the payload inline so
                    # an operator (or the round-trip test) still gets it.
                    self._reply(200, out)
                else:
                    self._reply(200, {"reason": "manual", "path": str(out)})
                return
            fields = self._routes.get(url.path)
            if fields is None:
                self._reply(404, {"error": f"no route {url.path}"})
                return
            rid = self.headers.get("X-Request-Id") or None
            fut = None
            try:
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n) or b"{}")
                if not isinstance(payload, dict):
                    raise RequestError("request body must be a JSON object")
                fut = client.submit(payload, request_id=rid)
                rid = getattr(fut, "request_id", rid)
                result = fut.result(timeout=60.0)
            except RequestError as e:
                self._reply(400, {"error": str(e), "request_id": rid})
            except Draining as e:
                # Mid-drain submit: shed, never hang — the 503 carries the
                # request_id and the state so the router can retry it on a
                # survivor (drain-hardening satellite).
                self._reply(
                    503,
                    {
                        "error": str(e),
                        "request_id": e.request_id,
                        "status": e.state,
                    },
                )
            except json.JSONDecodeError as e:
                self._reply(
                    400, {"error": f"bad JSON: {e}", "request_id": rid}
                )
            except Exception as e:  # Backpressure or engine failure
                rid = getattr(e, "request_id", None) or rid
                retry = getattr(e, "retry_after_s", None)
                if retry is not None:
                    self._reply(
                        429,
                        {
                            "error": str(e),
                            "retry_after_s": retry,
                            "request_id": rid,
                        },
                        headers={"Retry-After": f"{retry:.3f}"},
                    )
                else:
                    logger.exception("request %s failed", rid)
                    client.recorder.record(
                        "server_error", rid, error=type(e).__name__,
                    )
                    client.recorder.trigger("server_error")
                    self._reply(500, {"error": str(e), "request_id": rid})
            else:
                body = {k: result[k] for k in fields if k in result}
                body["request_id"] = rid
                # Which batching served this (flush vs continuous) + slot
                # occupancy on decode replicas — one consistent status read.
                st = client.batcher.status()
                body["batching"] = {
                    "mode": st["mode"],
                    **(
                        {
                            "slots": st["slots"],
                            "slots_active": st["slots_active"],
                        }
                        if "slots" in st
                        else {}
                    ),
                }
                phases = getattr(fut, "phases", None)
                if phases is not None:
                    body["phases"] = {
                        k: v * 1e3 for k, v in phases.items()  # ms
                    }
                self._reply(200, body)

    server = ThreadingHTTPServer((host, port), Handler)
    logger.info("serving on http://%s:%d", *server.server_address)
    return server
