"""Serving front ends: in-process :class:`Client` and a stdlib HTTP server.

``Client`` is the canonical surface — validate-at-submit, enqueue into the
:class:`DynamicBatcher`, block on the Future. The HTTP server is a thin
JSON adapter over the same client (``ThreadingHTTPServer``: one thread per
connection blocks on its Future while the flusher thread batches across
them — exactly the concurrency the micro-batcher exists to exploit).

Routes::

    GET  /healthz    -> {"status": "ok", "engine": ...}
    GET  /metrics    -> ServeMetrics.snapshot() as JSON
    POST /v1/mlm     -> BERT: pred_ids / score / nsp_probs for one example
    POST /v1/embed   -> BERT: pooled [CLS] embedding for one example
    POST /v1/classify-> image: top-k ids/probs for one example

Error mapping: RequestError -> 400; Backpressure -> 429 + ``Retry-After``;
anything the engine raises mid-batch -> 500.
"""

from __future__ import annotations

import json
import logging
from concurrent.futures import Future
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from distributed_tensorflow_tpu.obs.metrics import ServeMetrics
from distributed_tensorflow_tpu.serve.batcher import (
    BatcherConfig,
    DynamicBatcher,
)
from distributed_tensorflow_tpu.serve.engine import RequestError

logger = logging.getLogger(__name__)


class Client:
    """In-process serving client: ``submit`` returns a Future, ``call``
    blocks for the result. Payloads validate BEFORE they enqueue so a
    malformed request fails alone instead of poisoning its batch."""

    def __init__(
        self,
        engine,
        config: BatcherConfig | None = None,
        metrics: ServeMetrics | None = None,
    ):
        self.engine = engine
        self.metrics = metrics or ServeMetrics()
        if config is None:
            config = BatcherConfig(max_batch=engine.max_batch)
        elif config.max_batch > engine.max_batch:
            raise ValueError(
                f"batcher max_batch {config.max_batch} exceeds engine "
                f"max_batch {engine.max_batch}"
            )
        # Engines that expose the split hot path (dispatch/fetch) get the
        # overlapped batcher; engines that expose a bucket key get
        # bucket-aware queues when the config asks for them. Stub engines
        # with only run_batch keep the classic serial path.
        if getattr(engine, "metrics", False) is None:
            engine.metrics = self.metrics  # per-tier/bucket instruments
        bucket_for = (
            getattr(engine, "request_bucket", None)
            if config.bucket_queues
            else None
        )
        if config.bucket_queues and bucket_for is None:
            raise ValueError(
                "bucket_queues=True needs an engine with request_bucket()"
            )
        self.batcher = DynamicBatcher(
            engine.run_batch,
            config,
            metrics=self.metrics,
            dispatch=getattr(engine, "dispatch", None),
            fetch=getattr(engine, "fetch", None),
            bucket_for=bucket_for,
        )

    def submit(self, payload: dict) -> Future:
        self.engine.validate(payload)  # RequestError before enqueue
        return self.batcher.submit(payload)

    def call(self, payload: dict, timeout: float | None = 60.0) -> dict:
        return self.submit(payload).result(timeout=timeout)

    def close(self) -> None:
        self.batcher.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _jsonable(obj):
    """numpy -> plain python, recursively (json.dumps chokes on ndarrays)."""
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, np.generic):
        return obj.item()
    return obj


def build_http_server(client: Client, host: str = "127.0.0.1", port: int = 0):
    """Build (not start) a ``ThreadingHTTPServer`` over ``client``.

    ``port=0`` binds an ephemeral port (tests read ``server.server_address``).
    Call ``serve_forever()`` to run; ``shutdown()`` to stop.
    """

    class Handler(BaseHTTPRequestHandler):
        # Route table maps a POST path to "which keys of the engine result
        # this endpoint exposes" — both BERT routes run the SAME executable,
        # /v1/embed just answers with less.
        _routes = {
            "/v1/mlm": ("pred_ids", "score", "nsp_probs", "bucket"),
            "/v1/embed": ("embedding", "bucket"),
            "/v1/classify": ("top_ids", "top_probs"),
        }

        def log_message(self, fmt, *args):  # route access logs into logging
            logger.debug("http: " + fmt, *args)

        def _reply(self, code: int, body: dict, headers: dict | None = None):
            data = json.dumps(_jsonable(body)).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            if self.path == "/healthz":
                self._reply(
                    200,
                    {"status": "ok", "engine": type(client.engine).__name__},
                )
            elif self.path == "/metrics":
                self._reply(200, client.metrics.snapshot())
            else:
                self._reply(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            fields = self._routes.get(self.path)
            if fields is None:
                self._reply(404, {"error": f"no route {self.path}"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n) or b"{}")
                if not isinstance(payload, dict):
                    raise RequestError("request body must be a JSON object")
                result = client.call(payload)
            except RequestError as e:
                self._reply(400, {"error": str(e)})
            except json.JSONDecodeError as e:
                self._reply(400, {"error": f"bad JSON: {e}"})
            except Exception as e:  # Backpressure or engine failure
                retry = getattr(e, "retry_after_s", None)
                if retry is not None:
                    self._reply(
                        429,
                        {"error": str(e), "retry_after_s": retry},
                        headers={"Retry-After": f"{retry:.3f}"},
                    )
                else:
                    logger.exception("request failed")
                    self._reply(500, {"error": str(e)})
            else:
                self._reply(
                    200, {k: result[k] for k in fields if k in result}
                )

    server = ThreadingHTTPServer((host, port), Handler)
    logger.info("serving on http://%s:%d", *server.server_address)
    return server
