"""Fleet front door: replica supervision, affinity routing, failover,
and zero-downtime checkpoint hot-swap.

The paper's §L2 ``ClusterSpec`` premise — one coordinator handing work to
N workers and surviving their loss — applied to serving: every replica is
a full ``cli/serve.py`` stack (its own engine, batcher, health tracker,
flight recorder), and this module is the process in front of them that
finally CONSUMES the router-facing surfaces the stack already exposes
(readiness-gated ``/healthz``, ``POST /drainz``, ``batcher.status()``
queue/slot occupancy):

- **Supervision** — a single poll thread probes every replica's
  ``/healthz`` at ``poll_interval_s``; a replica is *lost* on health-poll
  timeout, connection refusal, or process exit.  Verdicts come from
  :class:`~..obs.fleet.ReplicaSupervisor` (the serving twin of PR 15's
  ``FleetSupervisor``): transient blips are ignored below
  ``fail_threshold``; sustained loss restarts the replica under a
  progress-aware budget with ``train.resilience``-style exponential
  backoff; an exhausted budget QUARANTINES it (the fleet routes around a
  replica that dies instantly rather than feeding it traffic to drop).
- **Routing** — power-of-two-choices over ``queue_depth + in_flight +
  slots_active`` (one ``/healthz`` body carries all three), sharpened by
  the router's own per-replica in-flight count so the balancer reacts
  faster than the poll cadence.  **Prefix affinity**: the head of
  ``input_ids`` hashes (blake2b — stable across processes, unlike
  ``hash()``) to a rendezvous pick, so requests sharing a system prompt
  land on the replica whose ``kvpool`` trie is already warm — the PR 12
  prefix-cache TTFT win survives fleet spraying.  Affinity yields to
  p2c when the preferred replica is ``affinity_max_imbalance`` loads
  hotter than the coolest (a hot prefix must not melt one replica).
- **Admission + failover** — the door sheds before work reaches a
  replica: no routable replica -> 503 with a minted ``request_id``;
  fleet-wide in-flight cap -> 429 + ``Retry-After``.  A request that
  dies with a replica (transport error, 5xx, mid-drain 503 shed, 429)
  retries on a survivor up to ``max_retries`` times — safe because
  inference is pure: replaying a prompt on another replica returns the
  same tokens.
- **Hot swap** — :meth:`Router.hot_swap` rolls a new checkpoint through
  the fleet one replica at a time: ``POST /drainz`` (the balancer stops
  picking it), wait for in-flight + queued work to finish, stop the old
  process, relaunch on the new checkpoint, wait for warmup-gated ready,
  VERIFY the replica's ``tag`` actually changed, then move on — zero
  dropped requests by construction, because at every instant N-1
  replicas are routable.

Observability: ``router_spawn`` / ``replica_lost`` / ``replica_restart``
/ ``hot_swap`` flight-recorder events (docs/OBS.md taxonomy), per-replica
labelled Prometheus families (:meth:`Router.families`), and a ``/fleetz``
digest on the router's own HTTP server (:func:`build_router_server`).

Threading contract (obs/sanitizer.py discipline): ONE poll thread
(daemon, timeout-joined in ``close()`` exactly like the batcher
flushers); all mutable routing state is guarded by ``Router._lock`` and
declared in ``_RACETRACE_ATTRS``; no HTTP I/O ever happens under the
lock — polls snapshot state, probe outside, then write back.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import json
import logging
import random
import subprocess
import threading
import time
import urllib.error
import urllib.request
from collections.abc import Sequence
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from urllib.parse import urlparse

from distributed_tensorflow_tpu.obs.export import (
    PROM_CONTENT_TYPE,
    Family,
    render,
)
from distributed_tensorflow_tpu.obs.fleet import ReplicaSupervisor
from distributed_tensorflow_tpu.obs.flightrec import NULL_RECORDER

logger = logging.getLogger(__name__)

__all__ = [
    "Replica",
    "Router",
    "RouterConfig",
    "build_router_server",
    "pick_power_of_two",
    "prefix_affinity_key",
    "rendezvous_pick",
    "replica_load",
]


# --------------------------------------------------------------- policy
# Pure functions: the balancing math is testable without a process,
# a socket, or a thread (tests/test_router.py unit-tests exactly these).


def replica_load(status: dict) -> float:
    """Routing load from one ``/healthz`` body: queued + admitted +
    active decode slots.  Missing keys count zero so a flush-mode replica
    (no slot table) and a bare stub replica rank on the same scale."""
    return float(
        status.get("queue_depth", 0)
        + status.get("in_flight", 0)
        + status.get("slots_active", 0)
    )


def pick_power_of_two(loads: Sequence[float], rng: random.Random) -> int:
    """Power-of-two-choices: sample two distinct replicas, take the less
    loaded (ties -> the first sampled, so the choice stays a pure
    function of ``rng``).  O(1) and within a constant of full scans for
    balance — the classic result this policy is named for."""
    n = len(loads)
    if n <= 0:
        raise ValueError("pick_power_of_two needs at least one load")
    if n == 1:
        return 0
    i, j = rng.sample(range(n), 2)
    return i if loads[i] <= loads[j] else j


def prefix_affinity_key(token_ids, n_tokens: int) -> str | None:
    """Stable hash of the first ``n_tokens`` prompt tokens (the shared
    system-prompt head), or ``None`` for an empty head.  blake2b over the
    decimal token ids: identical across processes and runs — Python's
    ``hash()`` is salted per process and would scatter a restarted
    router's affinity map."""
    head = [int(t) for t in list(token_ids)[: int(n_tokens)]]
    if not head:
        return None
    raw = ",".join(str(t) for t in head).encode()
    return hashlib.blake2b(raw, digest_size=8).hexdigest()

def rendezvous_pick(key: str, names: Sequence[str]) -> str:
    """Highest-random-weight pick of ``names`` for ``key``: every router
    (and every restart) maps the same key to the same replica, and losing
    a replica only remaps the keys that lived on it — the property that
    keeps the other replicas' prefix caches warm through a failure."""
    if not names:
        raise ValueError("rendezvous_pick needs at least one name")
    return max(
        names,
        key=lambda nm: hashlib.blake2b(
            f"{key}:{nm}".encode(), digest_size=8
        ).digest(),
    )


# ------------------------------------------------------------- plumbing


def _get_json(url: str, timeout: float) -> tuple[int, dict]:
    """GET ``url`` -> (code, parsed body).  HTTPError is a RESPONSE here
    (the health contract answers 503 with a JSON body); transport errors
    (refused, timeout, reset) propagate to the caller."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read() or b"{}")
        except (json.JSONDecodeError, OSError):
            return e.code, {"error": str(e)}


def _post_json(
    url: str, payload: dict, request_id: str, timeout: float
) -> tuple[int, dict]:
    """POST JSON -> (code, parsed body); same error split as
    :func:`_get_json`.  The ``X-Request-Id`` header makes the replica
    reuse OUR id, so a retried request keeps one identity across the
    fleet's traces and flight recorders."""
    data = json.dumps(payload).encode()
    req = urllib.request.Request(
        url,
        data=data,
        headers={
            "Content-Type": "application/json",
            "X-Request-Id": request_id,
        },
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read() or b"{}")
        except (json.JSONDecodeError, OSError):
            return e.code, {"error": str(e)}


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Router knobs (one frozen bag, like ``BatcherConfig``).

    The restart-budget trio (``max_restarts`` / ``backoff_*``) mirrors
    ``train.resilience.ResilienceConfig`` on purpose — same semantics,
    same defaults — but lives here because that module imports jax at
    module scope and the router stays import-light.
    """

    poll_interval_s: float = 0.5     # health-poll cadence
    poll_timeout_s: float = 2.0      # one probe's socket timeout
    start_grace_s: float = 120.0     # failed polls don't count while a
                                     # just-launched replica is starting
    fail_threshold: int = 3          # consecutive failed polls -> lost
    max_restarts: int = 3            # consecutive restarts before quarantine
    backoff_base_s: float = 0.5
    backoff_factor: float = 2.0
    backoff_max_s: float = 30.0
    max_retries: int = 2             # failover hops after the first attempt
    request_timeout_s: float = 60.0
    affinity_tokens: int = 16        # prompt-head tokens hashed for affinity
    affinity_max_imbalance: float = 8.0  # yield affinity when this much hotter
    max_in_flight_per_replica: int = 64  # door cap: this x ready replicas
    ready_timeout_s: float = 180.0   # hot-swap: replica must re-ready by then
    drain_timeout_s: float = 60.0    # hot-swap: in-flight must finish by then
    seed: int = 0                    # p2c rng seed (deterministic tests)


class Replica:
    """One replica's identity + mutable supervision state.

    ``cmd`` is the argv the router (re)launches the replica server with;
    ``cmd=None`` ADOPTS an externally managed replica — it is polled,
    routed to, and failed over from, but never restarted (a lost adopted
    replica just goes ``down`` until its own manager brings it back).
    """

    # Mutated by the poll thread and read by the routing threads; every
    # access is ordered by the owning Router's _lock.
    _RACETRACE_ATTRS = (
        "state", "status", "tag", "in_flight", "requests", "restart_at",
        "swapping",
    )

    def __init__(
        self,
        name: str,
        base_url: str,
        cmd: Sequence[str] | None = None,
        *,
        supervisor: ReplicaSupervisor,
    ):
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.cmd = list(cmd) if cmd else None
        self.supervisor = supervisor
        self.proc: subprocess.Popen | None = None
        self._log_fh = None
        # starting | ready | draining | down | quarantined (plus whatever
        # state string the replica's own /healthz reports while alive).
        self.state = "starting"
        self.status: dict = {}       # last successful probe body
        self.tag: str | None = None  # deployment tag from /healthz
        self.in_flight = 0           # router-side requests on this replica
        self.requests = 0            # lifetime requests routed here
        self.restart_at: float | None = None  # backoff deadline when down
        self.started_at: float | None = None  # launch time (grace window)
        self.swapping = False        # hot_swap owns this replica right now

    def routable(self) -> bool:
        # Degraded stays routable: it IS serving (just burning SLO
        # budget) — dropping every degraded replica under fleet-wide
        # load would shed all traffic exactly when shedding hurts most.
        return self.state in ("ready", "degraded") and not self.swapping


class Router:
    """The fleet front door.  See the module docstring for the design;
    the lifecycle is ``start()`` (spawn + poll thread) ... ``close()``.

    ``specs`` is a list of ``(name, base_url, cmd_or_None)`` triples —
    :func:`replica_specs` builds the common same-host case.
    """

    # Door-level counters, guarded by _lock (watched by sanitize_races in
    # tests/test_router.py's pipelining soak).
    _RACETRACE_ATTRS = ("_closed", "_shed", "_retries", "_door_429",
                        "_n_probes", "_migrations")

    def __init__(
        self,
        specs: Sequence[tuple[str, str, Sequence[str] | None]],
        config: RouterConfig | None = None,
        *,
        recorder=None,
        log_dir: str | Path | None = None,
        clock=time.monotonic,
    ):
        if not specs:
            raise ValueError("router needs at least one replica spec")
        self.config = config or RouterConfig()
        self.recorder = recorder if recorder is not None else NULL_RECORDER
        self._clock = clock
        self._lock = threading.Lock()
        self._rng = random.Random(self.config.seed)
        self._req_ids = itertools.count()
        self._log_dir = Path(log_dir) if log_dir else None
        c = self.config
        self.replicas = [
            Replica(
                name,
                url,
                cmd,
                supervisor=ReplicaSupervisor(
                    fail_threshold=c.fail_threshold,
                    max_restarts=c.max_restarts,
                    backoff_base_s=c.backoff_base_s,
                    backoff_factor=c.backoff_factor,
                    backoff_max_s=c.backoff_max_s,
                ),
            )
            for name, url, cmd in specs
        ]
        names = [r.name for r in self.replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names: {names}")
        self._by_name = {r.name: r for r in self.replicas}
        self._closed = False
        self._shed = 0        # door sheds (no routable replica)
        self._door_429 = 0    # door backpressure (fleet in-flight cap)
        self._retries = 0     # failover hops taken
        self._n_probes = 0    # lifetime health probes (fault-hook clock)
        self._migrations = 0  # drain-deadline stream migrations triggered
        # Serving-side chaos (serve/faultinject.py): when set, probe_
        # timeout events swallow health probes on the probe ordinal clock.
        self.fault_injector = None
        self._stop = threading.Event()
        self._poll_thread: threading.Thread | None = None

    # ------------------------------------------------------ spawn / adopt

    def _launch(self, r: Replica) -> None:
        """(Re)launch one replica process; caller holds NO lock (Popen
        can take a while).  Replica stdout/err tees into ``log_dir`` when
        configured so a crashed replica leaves a readable post-mortem."""
        if r.cmd is None:
            raise ValueError(f"replica {r.name} is adopted (no cmd)")
        if self._log_dir is not None:
            self._log_dir.mkdir(parents=True, exist_ok=True)
            if r._log_fh is None or r._log_fh.closed:
                r._log_fh = (self._log_dir / f"{r.name}.log").open("ab")
            out = r._log_fh
        else:
            out = subprocess.DEVNULL
        r.proc = subprocess.Popen(r.cmd, stdout=out, stderr=out)
        r.started_at = self._clock()
        self.recorder.record(
            "router_spawn", replica=r.name, pid=r.proc.pid,
            url=r.base_url,
        )
        logger.info("spawned replica %s pid=%d (%s)",
                    r.name, r.proc.pid, r.base_url)

    def start(self) -> "Router":
        """Spawn every owned replica and start the poll thread."""
        for r in self.replicas:
            if r.cmd is not None and r.proc is None:
                self._launch(r)
            elif r.cmd is None:
                r.started_at = self._clock()
                self.recorder.record(
                    "router_spawn", replica=r.name, adopted=True,
                    url=r.base_url,
                )
        self._poll_thread = threading.Thread(
            target=self._poll_loop, name="router-poll", daemon=True
        )
        self._poll_thread.start()
        return self

    def wait_ready(
        self, n: int | None = None, timeout: float = 60.0
    ) -> bool:
        """Block until >= ``n`` replicas are routable (default: all
        non-quarantined).  Returns False on timeout — callers decide
        whether a partial fleet is fatal."""
        deadline = self._clock() + timeout
        while self._clock() < deadline:
            with self._lock:
                ready = sum(1 for r in self.replicas if r.routable())
                want = n if n is not None else sum(
                    1 for r in self.replicas if r.state != "quarantined"
                )
            if ready >= max(want, 1):
                return True
            time.sleep(0.05)
        return False

    # -------------------------------------------------------- supervision

    def _probe(self, r: Replica) -> tuple[bool, dict | None]:
        """One /healthz probe OUTSIDE the lock: (alive, body).  Alive
        means "answered with parseable JSON" — a 503 draining/starting
        body is an alive replica that must NOT be restarted."""
        inj = self.fault_injector
        if inj is not None:
            with self._lock:
                self._n_probes += 1
                n = self._n_probes
            if inj.check_probe(n):
                return False, None  # drill: the probe timed out
        try:
            _, body = _get_json(
                r.base_url + "/healthz", self.config.poll_timeout_s
            )
            return True, body
        except (urllib.error.URLError, TimeoutError, OSError,
                json.JSONDecodeError):
            return False, None

    def _poll_once(self) -> None:
        now = self._clock()
        with self._lock:
            todo = [
                r for r in self.replicas
                if r.state != "quarantined" and not r.swapping
            ]
        for r in todo:
            exited = r.proc is not None and r.proc.poll() is not None
            alive, body = (False, None) if exited else self._probe(r)
            with self._lock:
                if r.swapping:
                    continue  # hot_swap claimed it mid-poll: hands off
                if alive:
                    r.supervisor.record_poll(True)
                    r.status = body
                    r.tag = body.get("tag", r.tag)
                    new_state = body.get("status", "ready")
                    if new_state == "ready" and r.state != "ready":
                        r.supervisor.record_ready()
                        logger.info("replica %s ready (tag=%s)",
                                    r.name, r.tag)
                    r.state = new_state
                    r.restart_at = None
                    continue
                if exited:
                    # A dead process is not a flaky probe: saturate the
                    # fail count so the verdict fires this poll.
                    for _ in range(self.config.fail_threshold):
                        r.supervisor.record_poll(False)
                else:
                    if r.state == "starting" and r.started_at is not None \
                            and (now - r.started_at) < \
                            self.config.start_grace_s:
                        # Slow start (jax import, AOT grid warmup) is not
                        # a failure: the grace window keeps the restart
                        # budget for replicas that actually died.
                        continue
                    r.supervisor.record_poll(False)
                verdict = r.supervisor.verdict()
                if verdict == "none":
                    # Below threshold: keep routing (failover covers the
                    # window) unless the process is plainly gone.
                    pass
                elif r.state != "down":
                    reason = "exit" if exited else "probe"
                    rc = r.proc.returncode if exited and r.proc else None
                    self.recorder.record(
                        "replica_lost", replica=r.name, reason=reason,
                        returncode=rc, verdict=verdict,
                    )
                    logger.warning(
                        "replica %s lost (%s, rc=%s): verdict=%s",
                        r.name, reason, rc, verdict,
                    )
                    if verdict == "quarantine" or r.cmd is None:
                        r.state = (
                            "quarantined" if verdict == "quarantine"
                            else "down"
                        )
                        r.restart_at = None
                    else:
                        backoff = r.supervisor.record_restart()
                        r.state = "down"
                        r.restart_at = now + backoff
                # Relaunch when the backoff deadline passes (restarts run
                # on the poll thread — no extra supervision thread).
                if (
                    r.state == "down"
                    and r.cmd is not None
                    and r.restart_at is not None
                    and now >= r.restart_at
                ):
                    r.restart_at = None
                    r.state = "starting"
                    relaunch = True
                else:
                    relaunch = False
            if relaunch:
                self._launch(r)
                self.recorder.record(
                    "replica_restart", replica=r.name,
                    restarts=r.supervisor.summary()["total_restarts"],
                )

    def _poll_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._poll_once()
            except Exception:  # noqa: BLE001 — the poll thread must not die
                logger.exception("poll pass failed")
            self._stop.wait(self.config.poll_interval_s)

    # ------------------------------------------------------------ routing

    def pick(self, token_ids=None, exclude: set | None = None) -> str | None:
        """Pick a routable replica name: prefix affinity when the prompt
        head hashes and the preferred replica isn't overloaded, else
        power-of-two-choices on live load.  ``None`` when nothing is
        routable (the caller sheds)."""
        exclude = exclude or set()
        cfg = self.config
        with self._lock:
            ready = [
                (r.name, replica_load(r.status) + r.in_flight)
                for r in self.replicas
                if r.routable() and r.name not in exclude
            ]
        if not ready:
            return None
        loads = dict(ready)
        names = sorted(loads)  # stable order: affinity is order-independent
        if token_ids is not None and cfg.affinity_tokens > 0:
            key = prefix_affinity_key(token_ids, cfg.affinity_tokens)
            if key is not None:
                pref = rendezvous_pick(key, names)
                if loads[pref] <= (
                    min(loads.values()) + cfg.affinity_max_imbalance
                ):
                    return pref
        return names[pick_power_of_two([loads[n] for n in names], self._rng)]

    def route(
        self,
        path: str,
        payload: dict,
        *,
        request_id: str | None = None,
        timeout: float | None = None,
    ) -> tuple[int, dict]:
        """Forward one POST through admission + balancing + failover.

        Returns ``(code, body)``; the body always carries ``request_id``
        and (on success) ``replica``.  Retryable outcomes — transport
        error, 429, 5xx (including a mid-drain 503 shed) — move to a
        survivor up to ``config.max_retries`` times; 2xx and 400/404 are
        final (a malformed request is malformed everywhere)."""
        cfg = self.config
        rid = request_id or f"rt-{next(self._req_ids):08d}"
        token_ids = (
            payload.get("input_ids") if isinstance(payload, dict) else None
        )
        # Door admission: bound fleet-wide in-flight BEFORE picking, so a
        # loaded fleet answers 429-with-Retry-After instead of queueing
        # unboundedly inside the door.
        with self._lock:
            n_ready = sum(1 for r in self.replicas if r.routable())
            total_in_flight = sum(r.in_flight for r in self.replicas)
            cap = cfg.max_in_flight_per_replica * max(n_ready, 1)
            if n_ready and total_in_flight >= cap:
                self._door_429 += 1
                self.recorder.record(
                    "request_reject", rid, cause="router_backpressure",
                    in_flight=total_in_flight, cap=cap,
                )
                return 429, {
                    "error": "router at capacity",
                    "retry_after_s": cfg.poll_interval_s,
                    "request_id": rid,
                }
        tried: set[str] = set()
        attempts = 0
        code, body = None, {}
        while attempts <= cfg.max_retries:
            name = self.pick(token_ids, exclude=tried)
            if name is None:
                break  # nothing routable (left): shed below
            r = self._by_name[name]
            with self._lock:
                r.in_flight += 1
                r.requests += 1
            try:
                code, body = _post_json(
                    r.base_url + path, payload, rid,
                    timeout if timeout is not None
                    else cfg.request_timeout_s,
                )
            except (urllib.error.URLError, TimeoutError, OSError) as e:
                code, body = None, {
                    "error": f"{type(e).__name__}: {e}",
                    "request_id": rid,
                }
            finally:
                with self._lock:
                    r.in_flight -= 1
            if code is not None and (code < 500 and code != 429):
                if code == 200:
                    if body.get("status") == "migrated":
                        # A drain-deadline migration moved this stream
                        # mid-generation: collect the finished result
                        # from the adopting replica (or replay with the
                        # generated prefix) before answering the client.
                        code, body = self._collect_migrated(
                            rid, path, payload, body, timeout
                        )
                    body.setdefault("request_id", rid)
                    body.setdefault("replica", name)
                return code, body
            tried.add(name)
            attempts += 1
            if attempts <= cfg.max_retries:
                with self._lock:
                    self._retries += 1
                logger.info(
                    "request %s failed on %s (code=%s): failing over",
                    rid, name, code,
                )
        if code is not None:
            return code, body  # exhausted retries: last real answer
        with self._lock:
            self._shed += 1
        self.recorder.record("request_reject", rid, cause="router_shed")
        return 503, {
            "error": "no routable replica",
            "request_id": rid,
            "shed": True,
        }

    def _collect_migrated(
        self,
        rid: str,
        path: str,
        payload: dict,
        body: dict,
        timeout: float | None,
    ) -> tuple[int, dict]:
        """Follow a ``status: "migrated"`` answer to the stream's new
        home: ``POST /v1/stream_wait`` on the target blocks for the
        finished generation (the target may itself migrate onward — each
        hop is followed, bounded like failover). When the target cannot
        answer — died, never adopted, already handed the result out — the
        request REPLAYS through normal routing with the client-visible
        generated prefix as ``resume_tokens``, so retry-after-kill never
        re-emits or skips a token: the resumed replica re-prefills the
        prefix at its absolute positions and the accumulated token list
        comes back bit-identical to an uninterrupted run."""
        cfg = self.config
        total = timeout if timeout is not None else cfg.request_timeout_s
        hops = 0
        while body.get("status") == "migrated" and hops <= cfg.max_retries:
            hops += 1
            target = str(body.get("target", ""))
            tokens = [int(t) for t in body.get("tokens", ())]
            deadline = self._clock() + total
            code, out = None, {}
            while self._clock() < deadline:
                try:
                    code, out = _post_json(
                        f"http://{target}/v1/stream_wait",
                        {"request_id": rid, "timeout_s": total},
                        rid,
                        total + 5.0,
                    )
                except (urllib.error.URLError, TimeoutError, OSError):
                    code, out = None, {}
                    break
                if code != 504:
                    break  # 504 = still generating: keep waiting
            if code == 200:
                body = out  # may be "migrated" again: follow the chain
                continue
            # The target can't answer: replay with everything the client
            # (transitively, this router) has already been shown.
            replay = dict(payload)
            if tokens:
                replay["resume_tokens"] = tokens
            with self._lock:
                self._retries += 1
            logger.info(
                "request %s: migrated stream unreachable on %s "
                "(code=%s); replaying with %d resume tokens",
                rid, target, code, len(tokens),
            )
            return self.route(
                path, replay, request_id=rid, timeout=timeout
            )
        return 200, body

    # ----------------------------------------------------------- hot swap

    def _migrate_streams(self, victim: Replica) -> dict:
        """Drain-deadline path: move every live stream off ``victim`` to
        the surviving routable replicas via its ``POST /migratez``.
        Raises RuntimeError when no survivor exists or the victim refuses
        — hot_swap then fails exactly as the old wait-forever path did."""
        with self._lock:
            survivors = [
                r for r in self.replicas
                if r is not victim and r.routable()
            ]
        pairs = []
        for s in survivors:
            u = urlparse(s.base_url)
            pairs.append([u.hostname or "127.0.0.1", int(u.port or 80)])
        if not pairs:
            raise RuntimeError(
                f"hot_swap: {victim.name} did not drain and no survivor "
                "can adopt its streams"
            )
        try:
            code, body = _post_json(
                victim.base_url + "/migratez", {"targets": pairs},
                f"migrate-{victim.name}", self.config.request_timeout_s,
            )
        except (urllib.error.URLError, TimeoutError, OSError) as e:
            raise RuntimeError(
                f"hot_swap: stream migration off {victim.name} failed: {e}"
            ) from e
        if code != 200:
            raise RuntimeError(
                f"hot_swap: stream migration off {victim.name} refused: "
                f"HTTP {code} {body}"
            )
        with self._lock:
            self._migrations += 1
        logger.info(
            "migrated %d live streams off %s (%d to survivors, "
            "%d re-adopted)", body.get("exported", 0), victim.name,
            body.get("migrated", 0), body.get("readopted", 0),
        )
        return body

    def _wait_drained(self, r: Replica, deadline: float) -> bool:
        """Poll the draining replica until queued + in-flight work hits
        zero (its 503 health body still carries the batcher status). One
        zero probe suffices: every flush path — including the serial one,
        which runs its batch ON the flusher thread — counts a running
        batch in ``in_flight`` until its futures resolve, so a zero read
        means nothing is queued, dispatched, or owed to a caller."""
        while self._clock() < deadline:
            alive, body = self._probe(r)
            if alive and (
                body.get("queue_depth", 0) + body.get("in_flight", 0)
                + body.get("slots_active", 0)
            ) == 0:
                return True
            time.sleep(0.05)
        return False

    def _wait_replica_ready(self, r: Replica, deadline: float) -> bool:
        """Probe until /healthz answers ready (warmup-gated on real
        engines) and mirror the result into the routing state."""
        while self._clock() < deadline:
            alive, body = self._probe(r)
            if alive and body.get("status") == "ready":
                with self._lock:
                    r.status = body
                    r.tag = body.get("tag", r.tag)
                    r.state = "ready"
                    r.supervisor.record_ready()
                return True
            time.sleep(0.05)
        return False

    def _stop_proc(self, r: Replica, timeout: float = 10.0) -> None:
        if r.proc is None or r.proc.poll() is not None:
            return
        r.proc.terminate()
        try:
            r.proc.wait(timeout)
        except subprocess.TimeoutExpired:
            r.proc.kill()
            r.proc.wait(timeout)

    def hot_swap(
        self,
        make_cmd,
        *,
        expected_tag: str | None = None,
    ) -> dict:
        """Rolling checkpoint swap: drain -> restart -> verify, one
        replica at a time, so N-1 replicas stay routable throughout.

        ``make_cmd(replica) -> argv`` builds the NEW server command (same
        port, new ``--ckpt-dir``/``--tag``); ``expected_tag`` asserts
        every replica actually came back on the new deployment — a swap
        that silently restarted the old checkpoint is a failure, not a
        success.  Raises RuntimeError on drain timeout, ready timeout, or
        tag mismatch; returns a per-replica summary on success.
        """
        cfg = self.config
        swapped = []
        for r in list(self.replicas):
            with self._lock:
                if r.state == "quarantined" or r.cmd is None:
                    continue
                r.swapping = True  # the poll thread hands this replica off
            try:
                self.recorder.record(
                    "hot_swap", replica=r.name, stage="drain",
                    old_tag=r.tag,
                )
                try:
                    _post_json(
                        r.base_url + "/drainz", {}, f"swap-{r.name}",
                        cfg.poll_timeout_s,
                    )
                except (urllib.error.URLError, TimeoutError, OSError) as e:
                    raise RuntimeError(
                        f"hot_swap: drain of {r.name} failed: {e}"
                    ) from e
                with self._lock:
                    r.state = "draining"
                if not self._wait_drained(
                    r, self._clock() + cfg.drain_timeout_s
                ):
                    # Drain deadline (ISSUE 18): instead of waiting out
                    # the longest generation (unbounded with a large
                    # max_new_tokens), move the remaining live streams to
                    # the survivors and proceed with the swap. The
                    # victim-held responses come back "migrated" and the
                    # router's route() collects them from their new homes.
                    mig = self._migrate_streams(r)
                    self.recorder.record(
                        "hot_swap", replica=r.name, stage="migrate",
                        exported=mig.get("exported", 0),
                        migrated=mig.get("migrated", 0),
                        readopted=mig.get("readopted", 0),
                    )
                    if not self._wait_drained(
                        r, self._clock() + cfg.drain_timeout_s
                    ):
                        raise RuntimeError(
                            f"hot_swap: {r.name} did not drain within "
                            f"{cfg.drain_timeout_s}s even after migrating "
                            f"{mig.get('migrated', 0)} streams"
                        )
                self._stop_proc(r)
                r.cmd = list(make_cmd(r))
                self._launch(r)
                self.recorder.record(
                    "hot_swap", replica=r.name, stage="restart",
                )
                if not self._wait_replica_ready(
                    r, self._clock() + cfg.ready_timeout_s
                ):
                    raise RuntimeError(
                        f"hot_swap: {r.name} not ready within "
                        f"{cfg.ready_timeout_s}s of restart"
                    )
                if expected_tag is not None and r.tag != expected_tag:
                    raise RuntimeError(
                        f"hot_swap: {r.name} came back with tag "
                        f"{r.tag!r}, expected {expected_tag!r}"
                    )
                self.recorder.record(
                    "hot_swap", replica=r.name, stage="ready",
                    new_tag=r.tag,
                )
                swapped.append({"replica": r.name, "tag": r.tag})
            finally:
                with self._lock:
                    r.swapping = False
        self.recorder.record(
            "hot_swap", stage="done", swapped=len(swapped),
            expected_tag=expected_tag,
        )
        return {"swapped": swapped, "expected_tag": expected_tag}

    # ------------------------------------------------------ observability

    def fleetz(self) -> dict:
        """The /fleetz digest: one consistent read of the routing view."""
        with self._lock:
            reps = [
                {
                    "name": r.name,
                    "url": r.base_url,
                    "state": r.state,
                    "tag": r.tag,
                    "pid": r.proc.pid if r.proc else None,
                    "owned": r.cmd is not None,
                    "in_flight": r.in_flight,
                    "requests": r.requests,
                    "load": replica_load(r.status) + r.in_flight,
                    "served": r.status.get("served"),
                    "supervisor": r.supervisor.summary(),
                }
                for r in self.replicas
            ]
            out = {
                "replicas": reps,
                "n_ready": sum(
                    1 for r in self.replicas if r.routable()
                ),
                "requests": sum(r.requests for r in self.replicas),
                "retries": self._retries,
                "shed": self._shed,
                "door_429": self._door_429,
                "stream_migrations": self._migrations,
                "closed": self._closed,
            }
        return out

    def families(self) -> list[Family]:
        """Per-replica labelled Prometheus families for /metrics."""
        z = self.fleetz()
        up = Family("router_replica_up", "gauge",
                    "1 when the replica is routable")
        inflight = Family("router_replica_in_flight", "gauge",
                          "router-side in-flight requests per replica")
        reqs = Family("router_requests_total", "counter",
                      "requests routed per replica")
        restarts = Family("router_replica_restarts_total", "counter",
                          "replica restarts performed by the router")
        for rep in z["replicas"]:
            lbl = {"replica": rep["name"]}
            up.add(1.0 if rep["state"] == "ready" else 0.0, lbl)
            inflight.add(rep["in_flight"], lbl)
            reqs.add(rep["requests"], lbl)
            restarts.add(rep["supervisor"]["total_restarts"], lbl)
        retries = Family("router_retries_total", "counter",
                         "failover hops taken").add(z["retries"])
        shed = Family("router_shed_total", "counter",
                      "requests shed at the door").add(z["shed"])
        door = Family("router_backpressure_total", "counter",
                      "requests 429ed at the door").add(z["door_429"])
        readyf = Family("router_ready_replicas", "gauge",
                        "routable replicas").add(z["n_ready"])
        return [up, inflight, reqs, restarts, retries, shed, door, readyf]

    # ------------------------------------------------------------ closing

    def close(self, *, stop_replicas: bool = True) -> None:
        """Stop the poll thread (timeout-joined: a stuck join RAISES, the
        batcher idiom) and, by default, the owned replica processes."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._stop.set()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=30.0)
            if self._poll_thread.is_alive():
                raise RuntimeError("router poll thread failed to stop")
        if stop_replicas:
            for r in self.replicas:
                if r.cmd is not None:
                    self._stop_proc(r)
                if r._log_fh is not None and not r._log_fh.closed:
                    r._log_fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def replica_specs(
    n: int,
    base_port: int,
    make_cmd=None,
    *,
    host: str = "127.0.0.1",
) -> list[tuple[str, str, list[str] | None]]:
    """The common same-host fleet: ``replica-i`` on ``base_port + i``.
    ``make_cmd(name, port) -> argv`` builds each server command; omit it
    to adopt already-running servers on those ports."""
    out = []
    for i in range(n):
        name, port = f"replica-{i}", base_port + i
        cmd = list(make_cmd(name, port)) if make_cmd is not None else None
        out.append((name, f"http://{host}:{port}", cmd))
    return out


# ---------------------------------------------------------------- server


def build_router_server(
    router: Router, host: str = "127.0.0.1", port: int = 0
):
    """The router's own HTTP face (build, don't start — same contract as
    ``serve.server.build_http_server``).

    Routes: ``GET /healthz`` (200 while >=1 replica is routable),
    ``GET /fleetz`` (the digest), ``GET /metrics`` (JSON; ``?format=prom``
    for the exposition), and ``POST /v1/*`` forwarded through
    :meth:`Router.route` (the response body carries ``replica``).
    ``POST /drainz`` drains the whole fleet (operator shutdown path).
    """

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            logger.debug("router http: " + fmt, *args)

        def _reply(self, code: int, body: dict,
                   headers: dict | None = None):
            data = json.dumps(body).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            url = urlparse(self.path)
            if url.path == "/healthz":
                z = router.fleetz()
                code = 200 if z["n_ready"] > 0 else 503
                self._reply(code, {
                    "status": "ready" if code == 200 else "degraded",
                    "n_ready": z["n_ready"],
                    "n_replicas": len(z["replicas"]),
                })
            elif url.path == "/fleetz":
                self._reply(200, router.fleetz())
            elif url.path == "/metrics":
                if "format=prom" in (url.query or ""):
                    text = render(router.families())
                    data = text.encode()
                    self.send_response(200)
                    self.send_header("Content-Type", PROM_CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                else:
                    self._reply(200, router.fleetz())
            else:
                self._reply(404, {"error": f"no route {url.path}"})

        def do_POST(self):
            url = urlparse(self.path)
            if url.path == "/drainz":
                progress = {}
                for r in list(router.replicas):
                    try:
                        _, b = _post_json(
                            r.base_url + "/drainz", {}, "router-drain",
                            router.config.poll_timeout_s,
                        )
                        # Per-replica drain progress (slots_active,
                        # queued, tokens_remaining): the operator sees
                        # why the fleet drain is slow, per replica.
                        progress[r.name] = b.get("progress")
                    except (urllib.error.URLError, TimeoutError, OSError):
                        progress[r.name] = None  # dead = already drained
                self._reply(200, {"draining": True, "progress": progress})
                return
            if not url.path.startswith("/v1/"):
                self._reply(404, {"error": f"no route {url.path}"})
                return
            rid = self.headers.get("X-Request-Id") or None
            try:
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n) or b"{}")
            except json.JSONDecodeError as e:
                self._reply(400, {"error": f"bad JSON: {e}"})
                return
            code, body = router.route(url.path, payload, request_id=rid)
            headers = None
            retry = body.get("retry_after_s")
            if code == 429 and retry is not None:
                headers = {"Retry-After": f"{float(retry):.3f}"}
            self._reply(code, body, headers=headers)

    server = ThreadingHTTPServer((host, port), Handler)
    logger.info("router on http://%s:%d", *server.server_address)
    return server
