"""Serving subsystem: dynamic-batching inference over trained checkpoints.

The ROADMAP north star is a system that "serves heavy traffic from millions
of users" — this package is the layer users actually touch, built on the
same sharded-model, checkpoint, and observability infrastructure as
training rather than a separate stack:

- ``engine.py``  — checkpoint-loading, mesh-sharded, AOT-compiled forward
  engines with a batch-tier x sequence-bucket executable grid (all built
  at startup, so no request ever pays a trace) and a non-blocking
  ``dispatch``/``fetch`` split over reusable staging buffers.
- ``batcher.py`` — dynamic micro-batcher: flush on max-batch-size or
  max-delay, bounded queue with explicit backpressure, optional
  per-bucket queues, and up to ``max_in_flight`` overlapped batches.
- ``kvpool.py``  — prefix-cache bookkeeping for the decode path: a radix
  trie over prompt-token blocks mapping shared heads to refcounted,
  LRU-evicted chains of device KV pages (the engine owns the pages, this
  owns what they mean).
- ``spec.py``    — speculative decoding for the decode path: host-side
  n-gram drafting over each slot's own history, exact-match acceptance
  against one batched verify forward, and per-slot adaptive backoff
  (output stays bit-identical to plain decode).
- ``server.py``  — in-process :class:`Client` plus a stdlib-HTTP front end
  with latency/queue/occupancy metrics (obs/metrics.py ServeMetrics).
- ``disagg.py``  — disaggregated prefill/decode serving: engine roles on
  device subsets, KV-page chain transfer (in-process device-to-device or
  the versioned wire format over HTTP), and the bytes-in-flight transfer
  budget in the admission path.

Entry point: ``python -m distributed_tensorflow_tpu.cli.serve``.
"""

from distributed_tensorflow_tpu.serve.batcher import (  # noqa: F401
    Backpressure,
    BatcherConfig,
    ContinuousBatcher,
    DynamicBatcher,
)
from distributed_tensorflow_tpu.serve.disagg import (  # noqa: F401
    DisaggServingPair,
    TransferBudget,
    WireError,
    deserialize_chain,
    make_kv_receiver,
    post_kv_transfer,
    serialize_chain,
)
from distributed_tensorflow_tpu.serve.engine import (  # noqa: F401
    BertInferenceEngine,
    CausalLMEngine,
    ImageClassifierEngine,
    InFlightBatch,
    RequestError,
    plan_serve_mesh,
)
from distributed_tensorflow_tpu.serve.kvpool import (  # noqa: F401
    KVBlockPool,
    PrefixMatch,
)
from distributed_tensorflow_tpu.serve.spec import (  # noqa: F401
    Drafter,
    NGramDrafter,
    SpecConfig,
)
from distributed_tensorflow_tpu.serve.server import (  # noqa: F401
    Client,
    Draining,
    build_http_server,
)
