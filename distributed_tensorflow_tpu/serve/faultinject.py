"""Deterministic serving-side fault injection: seeded chaos for the fleet.

The training half of the failure surface got first-class, reproducible
faults in ``train/faultinject.py``; this module is its serving sibling.
A :class:`FaultPlan` is the same seeded schedule shape (pure function of
its spec string), carried by a :class:`FaultInjector` into the hook
points that cover the *serving* failure surface:

- ``serve/batcher.py::_loop`` — ``slow_decode_step`` (a seeded sleep
  before dispatching a decode step, exactly the straggler shape the SLO
  tracker must absorb) and ``dispatch_error`` (an exception raised on
  the decode-loop thread so the in-flight slot-failure path is
  exercised, not hypothesized); ``replica_kill`` (SIGKILL of this very
  replica — the preemption the router's failover exists for);
- ``serve/disagg.py`` senders — ``wire_corrupt`` (one byte of a
  serialized KV/stream payload flipped post-CRC, so the receiver's
  fail-closed refusal is the thing under test);
- ``serve/router.py`` probes — ``probe_timeout`` (a health probe
  swallowed, driving the ban/failover machinery from the real signal
  path).

Every fired event is recorded to the flight recorder (kind
``fault_injected``) and surfaces in :meth:`FaultInjector.summary`, so
chaos drills and their reactions share one timeline. Events are
one-shot; duplicates (same kind, same step) fire once each. The step
domain differs per kind: decode-step index for ``slow_decode_step`` /
``dispatch_error`` / ``replica_kill``, the per-process wire-send ordinal
for ``wire_corrupt``, and the per-replica probe ordinal for
``probe_timeout``.

Reproduction workflow (docs/DEPLOY.md): a failure seen with
``--fault-plan seed=7,...`` re-runs bit-identically with the same spec.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import random
import signal
import threading
import time
from collections.abc import Mapping
from pathlib import Path

logger = logging.getLogger(__name__)

__all__ = [
    "FAULT_KINDS",
    "FaultEvent",
    "FaultPlan",
    "FaultInjector",
    "InjectedFault",
]

#: the serving failure surface this module can schedule.
FAULT_KINDS = (
    "dispatch_error",    # exception raised on the decode-loop thread
    "slow_decode_step",  # seeded sleep before dispatching a decode step
    "wire_corrupt",      # flip one byte of a serialized wire payload
    "probe_timeout",     # swallow a router health probe
    "replica_kill",      # SIGKILL this replica (unannounced preemption)
)


class InjectedFault(OSError):
    """A scheduled fault firing as an exception.

    Subclasses :class:`OSError` deliberately: an injected dispatch error
    must travel the same slot-failure classification path a real device
    or runtime error would.
    """

    def __init__(self, kind: str, step: int):
        super().__init__(f"injected fault {kind!r} at step {step}")
        self.kind = kind
        self.step = step


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    """One scheduled fault. ``step`` is the decode-step index for
    step-scoped kinds, the wire-send ordinal for ``wire_corrupt``, and
    the probe ordinal for ``probe_timeout``."""

    kind: str
    step: int
    duration_s: float = 0.0  # slow_decode_step only: how long the sleep is

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; expected one of {FAULT_KINDS}"
            )
        if self.step < 0:
            raise ValueError(f"fault step must be >= 0, got {self.step}")


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """An ordered, seeded schedule of :class:`FaultEvent`.

    Build one three ways: explicitly (tests pinning exact steps),
    :meth:`generate` (seeded random placement — the chaos-suite form), or
    :meth:`parse` (the ``--fault-plan`` CLI surface: either a
    ``key=value,...`` spec or a path to a JSON file)."""

    events: tuple[FaultEvent, ...]
    seed: int | None = None

    @classmethod
    def generate(
        cls,
        seed: int,
        num_steps: int,
        counts: Mapping[str, int],
        *,
        slow_step_s: float = 0.05,
        min_step: int = 1,
    ) -> "FaultPlan":
        """Seeded schedule: ``counts[kind]`` events per kind, placed on
        distinct steps drawn uniformly from ``[min_step, num_steps)``.
        Pure function of the arguments — same seed, same schedule."""
        if num_steps <= min_step:
            raise ValueError(f"num_steps {num_steps} must exceed min_step {min_step}")
        rng = random.Random(seed)
        events = []
        for kind in sorted(counts):
            n = counts[kind]
            if kind not in FAULT_KINDS:
                raise ValueError(f"unknown fault kind {kind!r}")
            if n <= 0:
                continue
            span = range(min_step, num_steps)
            steps = rng.sample(span, min(n, len(span)))
            for s in sorted(steps):
                events.append(
                    FaultEvent(
                        kind,
                        s,
                        duration_s=slow_step_s if kind == "slow_decode_step" else 0.0,
                    )
                )
        events.sort(key=lambda e: (e.step, e.kind))
        return cls(tuple(events), seed=seed)

    @classmethod
    def parse(cls, spec: str, *, num_steps: int = 0) -> "FaultPlan":
        """The ``--fault-plan`` surface.

        A path to a ``.json`` file loads an explicit plan
        (``{"seed": .., "events": [{"kind": .., "step": ..}, ..]}``).
        Otherwise a comma spec drives :meth:`generate`::

            seed=7,replica_kill=1,slow_decode_step=2,slow_step_s=0.1

        ``num_steps`` bounds the random placement (required for specs,
        supplied by the harness from the workload size).
        """
        spec = spec.strip()
        if spec.endswith(".json") or os.path.sep in spec:
            return cls.from_file(spec)
        seed, counts, slow_s, min_step = 0, {}, 0.05, 1
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"bad --fault-plan entry {part!r}: expected key=value")
            key, _, val = part.partition("=")
            key = key.strip()
            if key == "seed":
                seed = int(val)
            elif key == "slow_step_s":
                slow_s = float(val)
            elif key == "min_step":
                min_step = int(val)
            elif key in FAULT_KINDS:
                counts[key] = int(val)
            else:
                raise ValueError(
                    f"unknown --fault-plan key {key!r}; expected seed/"
                    f"slow_step_s/min_step or one of {FAULT_KINDS}"
                )
        if not num_steps:
            raise ValueError("a --fault-plan spec needs num_steps to place events")
        return cls.generate(
            seed, num_steps, counts, slow_step_s=slow_s, min_step=min_step
        )

    @classmethod
    def from_file(cls, path: str | Path) -> "FaultPlan":
        doc = json.loads(Path(path).read_text())
        events = tuple(
            FaultEvent(
                e["kind"], int(e["step"]), duration_s=float(e.get("duration_s", 0.0))
            )
            for e in doc.get("events", ())
        )
        return cls(events, seed=doc.get("seed"))

    def to_json(self) -> str:
        return json.dumps(
            {
                "seed": self.seed,
                "events": [dataclasses.asdict(e) for e in self.events],
            }
        )


class FaultInjector:
    """Runtime carrier of a :class:`FaultPlan` across the serving hooks.

    One injector serves one replica process; the decode hook runs on the
    batcher's loop thread while wire/probe hooks may run on HTTP or
    router threads, so the fired-event ledger is lock-protected.
    ``recorder`` is any
    :class:`~distributed_tensorflow_tpu.obs.flightrec.FlightRecorder`
    (the NULL recorder when absent).
    """

    def __init__(self, plan: FaultPlan, *, recorder=None, sleep=time.sleep):
        from distributed_tensorflow_tpu.obs.flightrec import NULL_RECORDER

        self.plan = plan
        self.recorder = recorder if recorder is not None else NULL_RECORDER
        self._sleep = sleep
        self._lock = threading.Lock()
        # Multiset of pending events per kind: {kind: {step: [events]}} —
        # one-shot semantics with support for stacked duplicates.
        self._pending: dict[str, dict[int, list[FaultEvent]]] = {
            k: {} for k in FAULT_KINDS
        }
        for ev in plan.events:
            self._pending[ev.kind].setdefault(ev.step, []).append(ev)
        self.fired: list[dict] = []

    def _take(self, kind: str, step: int) -> FaultEvent | None:
        """Pop one pending event of ``kind`` at ``step`` and ledger it."""
        with self._lock:
            stack = self._pending[kind].get(step)
            if not stack:
                return None
            ev = stack.pop()
            if not stack:
                del self._pending[kind][step]
            self.fired.append({"kind": kind, "step": step})
        # detail key is "fault", not "kind" — record()'s own first
        # parameter is named kind.
        self.recorder.record("fault_injected", fault=kind, step=step)
        logger.warning("fault injection: %s at step %d", kind, step)
        return ev

    # ---- hook points -----------------------------------------------------

    def on_decode_step(self, step: int) -> None:
        """Called by the batcher loop before dispatching decode ``step``.

        ``slow_decode_step`` sleeps in place (the straggler shape);
        ``replica_kill`` flushes the flight recorder and SIGKILLs the
        process (there is no atexit after SIGKILL — the dump is the only
        trace that survives); ``dispatch_error`` raises
        :class:`InjectedFault` so the caller's slot-failure path runs.
        """
        ev = self._take("slow_decode_step", step)
        if ev is not None:
            self._sleep(ev.duration_s)
        if self._take("replica_kill", step) is not None:
            self.recorder.dump("replica_kill", force=True)
            os.kill(os.getpid(), signal.SIGKILL)
        if self._take("dispatch_error", step) is not None:
            raise InjectedFault("dispatch_error", step)

    def check_wire(self, index: int) -> bool:
        """Called by wire senders before shipping payload ``index``.
        True means: flip one byte of this payload (corrupt in flight)."""
        return self._take("wire_corrupt", index) is not None

    def check_probe(self, index: int) -> bool:
        """Called by the router before health probe ``index``. True
        means: swallow this probe (simulate a timeout)."""
        return self._take("probe_timeout", index) is not None

    # ---- observability ---------------------------------------------------

    def summary(self) -> dict:
        """Beacon/statusz payload: fired counts + the recent ledger tail."""
        with self._lock:
            counts: dict[str, int] = {}
            for f in self.fired:
                counts[f["kind"]] = counts.get(f["kind"], 0) + 1
            return {
                "injected_faults": counts,
                "recent_injected": list(self.fired)[-8:],
            }
