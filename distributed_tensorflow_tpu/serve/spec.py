"""Host-side speculative-decoding support: n-gram drafting + per-slot
accept/reject bookkeeping (serve/batcher.py drives it; docs/DEPLOY.md
"Speculative decoding").

Self-speculation, no second model: the drafter proposes up to ``k``
candidate tokens per slot by suffix-matching the slot's own prompt +
generated history (prompt-lookup decoding — the model-free variant of
Leviathan et al. 2023), and the engine verifies all of them in ONE
fixed-shape ``[slots, k+1]`` forward (``CausalLMEngine.verify``). The
accepted prefix is emitted as multiple tokens per step; the first
mismatch position already carries the VERIFIED model token, so a full
reject still emits exactly what a plain decode step would have — a
speculative step is never wasted, only its extra verify width is.

Acceptance here is EXACT MATCH against the model's (greedy or seeded-
categorical) choice at each position. That is stronger than
distribution-level acceptance: the emitted stream is bit-identical to
the non-speculative stream for ANY temperature, because sampling is
already deterministic per (seed, absolute position)
(models/causal_lm.sample_tokens — the determinism contract
tests/test_serve_decode.py pins).

Adaptive backoff protects adversarial streams: each slot tracks an
acceptance EMA; when it falls below the threshold the slot drops to
k=0 — plain pipelined decode, paying nothing — and re-probes with one
speculative step every ``reprobe_period`` steps so a stream that turns
repetitive later is re-detected. Engage/disengage transitions surface
as flight-recorder ``spec_backoff`` events.
"""

from __future__ import annotations

import dataclasses
from typing import Protocol, Sequence


class Drafter(Protocol):
    """Pluggable draft source. ``history`` is the slot's prompt followed by
    every token generated so far; return AT MOST ``k`` candidate
    continuations (fewer, or none, when there is nothing worth proposing).
    Implementations must be pure functions of ``history`` — the batcher
    calls them under its scheduling lock. A draft-model backend slots in
    here later; :class:`NGramDrafter` is the model-free default."""

    def draft(self, history: Sequence[int], k: int) -> list[int]:
        ...


class NGramDrafter:
    """Prompt-lookup drafting: match the longest recent suffix of
    ``history`` (between ``min_match`` and ``max_match`` tokens) against an
    earlier occurrence in the same history, and propose the tokens that
    followed that occurrence.

    Longest-suffix-first keeps precision up — a 4-gram match is far more
    predictive than a 2-gram one — and the most RECENT earlier occurrence
    wins ties, since local repetition (code, quoted spans, structured
    output) is what this drafter exists to exploit.
    """

    def __init__(self, min_match: int = 2, max_match: int = 4):
        if min_match < 1:
            raise ValueError(f"min_match must be >= 1, got {min_match}")
        if max_match < min_match:
            raise ValueError(
                f"max_match {max_match} < min_match {min_match}"
            )
        self.min_match = min_match
        self.max_match = max_match

    def draft(self, history: Sequence[int], k: int) -> list[int]:
        h = list(history)
        n = len(h)
        if k <= 0 or n < self.min_match + 1:
            return []
        for width in range(min(self.max_match, n - 1), self.min_match - 1, -1):
            suffix = h[n - width:]
            # Scan right-to-left over candidate match ends (the position
            # just past the earlier occurrence), most recent first; the
            # occurrence must end before the suffix itself starts.
            for end in range(n - 1, width - 1, -1):
                if h[end - width:end] == suffix:
                    return h[end:end + k]
            # No occurrence at this width -> retry shorter.
        return []


@dataclasses.dataclass(frozen=True)
class SpecConfig:
    """Speculation knobs (cli/serve.py ``--spec-*``; engine-validated by
    ``CausalLMEngine._plan_spec``).

    ``spec_tokens`` is the verify width k (0 disables speculation
    entirely); ``min_match`` the shortest n-gram the drafter may match.
    Backoff: a slot whose acceptance EMA (per drafted token, smoothed
    with ``ema_alpha``) drops below ``backoff_threshold`` after
    ``warmup_verifies`` speculative steps falls back to plain decode,
    re-probing one speculative step every ``reprobe_period`` plain steps;
    a probe that lifts the EMA back over the threshold re-engages."""

    spec_tokens: int = 0
    min_match: int = 2
    max_match: int = 4
    backoff_threshold: float = 0.25
    ema_alpha: float = 0.3
    warmup_verifies: int = 3
    reprobe_period: int = 16

    def make_drafter(self) -> Drafter:
        return NGramDrafter(self.min_match, self.max_match)


class SlotSpec:
    """Per-slot speculation state: the drafter, the acceptance EMA, and
    the backoff mode machine. One instance per slot OCCUPANCY (built at
    admission, dropped at free) — a new request always starts optimistic.

    Thread-safety: mutated only under the batcher's ``_cv`` (the same
    discipline as the slot fields themselves); the sanitizer soak in
    tests/test_serve_spec.py runs concurrent submitters over it.
    """

    __slots__ = (
        "cfg", "drafter", "ema", "verifies", "backed_off", "plain_steps",
        "drafted", "accepted", "rejects",
    )

    def __init__(self, cfg: SpecConfig, drafter: Drafter | None = None):
        self.cfg = cfg
        self.drafter = drafter if drafter is not None else cfg.make_drafter()
        self.ema = 1.0          # optimistic start: speculate until proven bad
        self.verifies = 0
        self.backed_off = False
        self.plain_steps = 0    # plain decode steps since the last probe
        self.drafted = 0
        self.accepted = 0
        self.rejects = 0

    @property
    def speculating(self) -> bool:
        """True when the slot should take the verify path this step —
        either in full speculation mode, or backed off with a probe due."""
        if not self.backed_off:
            return True
        return self.plain_steps >= self.cfg.reprobe_period

    def note_plain_step(self) -> None:
        self.plain_steps += 1

    def propose(self, history: Sequence[int], max_k: int) -> list[int]:
        """Draft for the next verify step; ``max_k`` is the caller's cap
        (generation budget / cache headroom), further clamped to k."""
        k = min(self.cfg.spec_tokens, max_k)
        if k <= 0:
            return []
        return list(self.drafter.draft(history, k))[:k]

    def record(self, drafted: int, accepted: int) -> str | None:
        """Fold one speculation outcome into the EMA; returns "engage" /
        "disengage" when the backoff mode flips (the batcher turns these
        into flight-recorder ``spec_backoff`` events), else None.

        ``drafted == 0`` means the drafter found NO usable n-gram — the
        batcher ran a plain step instead of a verify. That counts as 0.0
        acceptance: a stream the drafter can't predict should back off to
        the fully-pipelined plain path just like one whose drafts get
        rejected (the verify cadence itself costs pipelining)."""
        self.verifies += 1
        self.drafted += drafted
        self.accepted += accepted
        if 0 < drafted and accepted < drafted:
            self.rejects += 1
        a = self.cfg.ema_alpha
        rate = (accepted / drafted) if drafted > 0 else 0.0
        self.ema = (1.0 - a) * self.ema + a * rate
        if self.backed_off:
            self.plain_steps = 0  # this WAS the probe; restart the clock
            if self.ema >= self.cfg.backoff_threshold:
                self.backed_off = False
                return "disengage"
            return None
        if (
            self.verifies >= self.cfg.warmup_verifies
            and self.ema < self.cfg.backoff_threshold
        ):
            self.backed_off = True
            self.plain_steps = 0
            return "engage"
        return None

    def digest(self) -> dict:
        return {
            "k": 0 if self.backed_off else self.cfg.spec_tokens,
            "backed_off": self.backed_off,
            "acceptance_ema": round(self.ema, 4),
            "drafted": self.drafted,
            "accepted": self.accepted,
            "rejects": self.rejects,
        }
