"""Block-granular KV prefix cache: radix trie over prompt-token blocks.

The host half of prefix-cache KV reuse (the vLLM/SGLang recipe adapted to
this repo's slot-table cache): the ENGINE owns a device-resident pool of
fixed-size KV pages ``[num_layers, n_blocks, block_tokens, heads,
head_dim]`` sharded like the slot cache; this module owns every piece of
bookkeeping about what those pages MEAN — a token-trie (radix) index
mapping prompt prefixes to chains of block ids, refcount pins, and LRU
eviction under the byte budget. No JAX in here: the pool never touches a
device array, so trie ops cost microseconds on the decode loop.

Design contracts (tests/test_kvpool.py pins them):

- **Block granularity.** One trie node per FULL block of ``block_tokens``
  prompt ids (the node key is that token tuple); partial trailing blocks
  are never indexed, so two prompts can only share whole pages.
- **Copy-on-read, not copy-on-write.** Published pages are IMMUTABLE: a
  matching request gathers COPIES of the chain into its own slot pages
  and extends those, so requests diverging after a shared head can never
  corrupt each other — the COW isolation property without ever needing a
  write-fault path. A block id is (re)written exactly once, at
  :meth:`insert` time, before any later dispatch can match it.
- **Match leaves a suffix.** :meth:`match` caps the walk at
  ``(prompt_len - 1) // block_tokens`` blocks so at least one prompt
  token always remains for suffix prefill — the engine needs a real
  forward to produce first-token logits.
- **Pin across the gather window.** ``match`` increfs every node on the
  returned chain; the caller releases after the gather is DISPATCHED
  (device stream order then keeps the pages alive for the gather even if
  they are evicted and rewritten by a later insert).
- **LRU leaf eviction.** Allocation under a full pool evicts the
  least-recently-used refcount-0 LEAF — leaf-first keeps the trie
  prefix-closed (an interior page never outlives its children), and
  repeated allocation walks a cold chain back-to-front.

Thread safety: one internal lock orders every method; the continuous
batcher calls ``match``/``release`` while holding its own ``_cv`` (lock
order ``_cv -> pool``, never reversed) and ``insert``/``stats`` from the
decode-loop / HTTP threads. ``_RACETRACE_ATTRS`` lets the
``sanitize_races`` soak check that ordering at runtime.
"""

from __future__ import annotations

import threading

from distributed_tensorflow_tpu.obs.flightrec import NULL_RECORDER

__all__ = ["KVBlockPool", "PrefixMatch"]


class _TrieNode:
    """One cached block: ``key`` is the block's token tuple, ``block`` the
    pool page holding its K/V. ``refs`` pins (gathers in flight), ``tick``
    is the LRU clock stamp."""

    __slots__ = ("key", "block", "parent", "children", "refs", "tick")

    def __init__(self, key, block, parent):
        self.key = key
        self.block = block
        self.parent = parent
        self.children: dict = {}
        self.refs = 0
        self.tick = 0


class PrefixMatch:
    """A pinned chain from :meth:`KVBlockPool.match`: ``blocks`` are the
    pool page ids covering the prompt's first ``cached_len`` tokens.
    Release is idempotent — the pool guards the unpin with ``_released``
    so every exit path (post-dispatch, slot failure, slot free) can call
    it unconditionally."""

    __slots__ = ("blocks", "cached_len", "_nodes", "_released")

    def __init__(self, blocks, cached_len, nodes):
        self.blocks = blocks
        self.cached_len = cached_len
        self._nodes = nodes
        self._released = False


class KVBlockPool:
    """Refcounted, LRU-evicted index over a fixed pool of KV pages."""

    # Watched by obs.sanitizer.sanitize_races (tests/test_serve_decode.py
    # soak); every access must be ordered by self._lock.
    _RACETRACE_ATTRS = ("_free", "_by_block", "_ticks", "_evictions")

    def __init__(self, n_blocks: int, block_tokens: int,
                 bytes_per_block: int = 0, dtype: str = "float32"):
        if n_blocks < 1:
            raise ValueError(f"need at least one block, got {n_blocks}")
        if block_tokens < 1:
            raise ValueError(
                f"block_tokens must be >= 1, got {block_tokens}"
            )
        self.n_blocks = int(n_blocks)
        self.block_tokens = int(block_tokens)
        self.bytes_per_block = int(bytes_per_block)
        # Storage dtype of the pages this pool indexes (informational:
        # bytes_per_block already reflects it — int8 blocks carry their
        # per-position scale payload in the count, see engine
        # _plan_prefix_cache).
        self.dtype = str(dtype)
        self._lock = threading.Lock()
        self._root = _TrieNode(None, -1, None)
        self._free = list(range(self.n_blocks))
        self._by_block: dict[int, _TrieNode] = {}
        self._ticks = 0
        self._evictions = 0
        # Flight-recorder sink for prefix_evict events; the continuous
        # batcher swaps in its recorder when one is enabled. Recording is
        # a leaf-lock append (pool _lock -> recorder lock, never out).
        self.recorder = NULL_RECORDER

    # ------------------------------------------------------------- lookup

    def match(self, token_ids) -> PrefixMatch:
        """Longest cached prefix of ``token_ids`` in whole blocks, capped
        so at least one prompt token is left un-cached. Pins the chain;
        the caller MUST :meth:`release` once the page gather is
        dispatched (or the request dies first)."""
        ids = [int(t) for t in token_ids]
        bt = self.block_tokens
        limit = max(len(ids) - 1, 0) // bt
        with self._lock:
            self._ticks += 1
            tick = self._ticks
            node, nodes = self._root, []
            for b in range(limit):
                child = node.children.get(tuple(ids[b * bt:(b + 1) * bt]))
                if child is None:
                    break
                child.refs += 1
                child.tick = tick
                nodes.append(child)
                node = child
            return PrefixMatch(
                [n.block for n in nodes], len(nodes) * bt, nodes
            )

    def cached_len(self, token_ids) -> int:
        """No-pin peek: tokens of ``token_ids`` covered by cached blocks,
        under the same one-token-suffix cap as :meth:`match`. Advisory
        only (the answer can change the moment the lock drops) — the
        disagg transfer planner uses it to size the uncached remainder a
        wire push must carry; admission still does a real pinning
        :meth:`match`."""
        ids = [int(t) for t in token_ids]
        bt = self.block_tokens
        limit = max(len(ids) - 1, 0) // bt
        with self._lock:
            node, n = self._root, 0
            for b in range(limit):
                child = node.children.get(tuple(ids[b * bt:(b + 1) * bt]))
                if child is None:
                    break
                n += 1
                node = child
            return n * bt

    def release(self, match: PrefixMatch) -> None:
        """Unpin a matched chain (idempotent)."""
        with self._lock:
            if match._released:
                return
            match._released = True
            for n in match._nodes:
                n.refs -= 1

    # ------------------------------------------------------------- insert

    def insert(self, token_ids) -> list[tuple[int, int]]:
        """Index every full block of ``token_ids``, allocating pages for
        the ones not already cached. Returns ``(block_id, block_index)``
        pairs for the NEW pages — the caller must copy the slot's pages
        into them (``CausalLMEngine.insert_prefix``) before dispatching
        anything that could match them; single-dispatcher ordering plus
        the device stream makes that automatic. Allocation stops early
        (prefix closure) when nothing is evictable."""
        ids = [int(t) for t in token_ids]
        bt = self.block_tokens
        out: list[tuple[int, int]] = []
        with self._lock:
            self._ticks += 1
            tick = self._ticks
            node = self._root
            for b in range(len(ids) // bt):
                key = tuple(ids[b * bt:(b + 1) * bt])
                child = node.children.get(key)
                if child is None:
                    block = self._alloc_locked()
                    if block is None:
                        break
                    child = _TrieNode(key, block, node)
                    node.children[key] = child
                    self._by_block[block] = child
                    out.append((block, b))
                child.tick = tick
                node = child
        return out

    def index(self, token_ids) -> tuple[list[tuple[int, int]], int]:
        """:meth:`insert` plus a coverage report: ``(new_pairs,
        covered_blocks)`` where ``covered_blocks`` counts the full blocks
        of ``token_ids`` present in the trie AFTER the insert. The
        preemption park path needs the distinction insert alone cannot
        give — allocation stops early under a full pool, and a parked
        chain that only partially covers its sequence is useless (the
        resume would still re-prefill the tail from the break point, but
        the scheduler promised the victim a near-free resume and must
        abort the preemption instead when the pool cannot hold it)."""
        ids = [int(t) for t in token_ids]
        bt = self.block_tokens
        out: list[tuple[int, int]] = []
        covered = 0
        with self._lock:
            self._ticks += 1
            tick = self._ticks
            node = self._root
            for b in range(len(ids) // bt):
                key = tuple(ids[b * bt:(b + 1) * bt])
                child = node.children.get(key)
                if child is None:
                    block = self._alloc_locked()
                    if block is None:
                        break
                    child = _TrieNode(key, block, node)
                    node.children[key] = child
                    self._by_block[block] = child
                    out.append((block, b))
                child.tick = tick
                covered = b + 1
                node = child
        return out, covered

    def forget(self, token_ids) -> int:
        """Drop the trailing unpinned leaf run of ``token_ids``'s cached
        chain (deepest-first, stopping at the first pinned or interior
        node — prefix closure holds). The undo path for a park-publish
        whose device copy failed AFTER :meth:`index` grew the trie: those
        blocks advertise token content their pages never received, and
        serving them would break bit-parity. Returns blocks freed."""
        ids = [int(t) for t in token_ids]
        bt = self.block_tokens
        with self._lock:
            node, chain = self._root, []
            for b in range(len(ids) // bt):
                child = node.children.get(tuple(ids[b * bt:(b + 1) * bt]))
                if child is None:
                    break
                chain.append(child)
                node = child
            freed = 0
            for n in reversed(chain):
                if n.children or n.refs:
                    break
                del n.parent.children[n.key]
                del self._by_block[n.block]
                self._free.append(n.block)
                freed += 1
            return freed

    def _alloc_locked(self) -> int | None:
        if self._free:
            return self._free.pop()
        victim = None
        for node in self._by_block.values():
            if node.children or node.refs:
                continue
            if victim is None or node.tick < victim.tick:
                victim = node
        if victim is None:
            return None  # everything pinned or interior: cannot evict
        del victim.parent.children[victim.key]
        del self._by_block[victim.block]
        self._evictions += 1
        self.recorder.record("prefix_evict", block=victim.block,
                             tick=victim.tick)
        return victim.block

    # -------------------------------------------------------------- stats

    def stats(self) -> dict:
        """Occupancy digest for ``status()`` / the ``serve_kv_pool_bytes``
        gauge."""
        with self._lock:
            used = len(self._by_block)
            return {
                "block_tokens": self.block_tokens,
                "blocks": self.n_blocks,
                "blocks_used": used,
                "dtype": self.dtype,
                "bytes_per_block": self.bytes_per_block,
                "bytes_used": used * self.bytes_per_block,
                "capacity_bytes": self.n_blocks * self.bytes_per_block,
                "evictions": self._evictions,
            }
