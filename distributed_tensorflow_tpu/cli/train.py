"""``python -m distributed_tensorflow_tpu.cli.train --config=<workload>``.

Workload presets mirror the reference's five configurations
(BASELINE.json "configs" / SURVEY.md §2 workload rows) one-to-one:

=========================  ====================================================
preset                     reference configuration it rebuilds
=========================  ====================================================
``mnist_lenet``            MNIST LeNet-5, single-process sync SGD sanity run
``cifar_resnet20``         CIFAR-10 ResNet-20, SyncReplicasOptimizer PS (sync DP)
``imagenet_resnet50``      ImageNet ResNet-50, 8-worker NCCL allreduce (sync DP)
``imagenet_inception_async`` ImageNet Inception-v3, async PS → stale-K emulation
``bert_base``              BERT-base pretraining (MLM+NSP), large-embedding DP
=========================  ====================================================

Every preset runs on any mesh size (DP width comes from the devices present,
not from the config — there is no worker count to configure away). Datasets
are seeded synthetic stand-ins with learnable structure (zero-egress
environment); point ``--data-dir`` at real data when present (data/readers).
"""

from __future__ import annotations

import argparse
import dataclasses
import logging
from collections.abc import Callable
from typing import Any

import jax
import jax.numpy as jnp
import optax


@dataclasses.dataclass(frozen=True)
class WorkloadConfig:
    """One training workload: model + data + optimization, mesh-agnostic."""

    name: str
    build: Callable[["WorkloadConfig"], dict[str, Any]]  # returns the pieces
    global_batch: int
    num_steps: int
    learning_rate: float
    momentum: float = 0.9
    optimizer: str = "sgd"  # "sgd" | "adam"
    mode: str = "sync"  # "sync" | "stale"
    staleness: int = 0
    seq_parallel: int = 0  # >0: seq axis size for ring attention (BERT)
    image_size: int = 0  # overridable per run
    dataset: str = ""  # real-dataset name for data/readers.load_dataset
    data_dir: str = ""  # where to look for it; synthetic fallback otherwise
    log_every: int = 50
    ckpt_every: int = 0


def _make_tx(cfg: WorkloadConfig) -> optax.GradientTransformation:
    if cfg.optimizer == "adam":
        return optax.adam(cfg.learning_rate)
    if cfg.momentum:
        return optax.sgd(cfg.learning_rate, momentum=cfg.momentum)
    return optax.sgd(cfg.learning_rate)


def _build_image_workload(model, image_shape, num_classes, n_examples=4096):
    def build(cfg: WorkloadConfig):
        from distributed_tensorflow_tpu.data import device_batches
        from distributed_tensorflow_tpu.data.readers import load_dataset
        from distributed_tensorflow_tpu.train.objectives import (
            init_model,
            make_classification_loss,
        )

        shape = image_shape
        if cfg.image_size:
            shape = (cfg.image_size, cfg.image_size, image_shape[-1])

        def make(mesh):
            params, model_state = init_model(
                model, jax.random.key(0), jnp.zeros((1, *shape), jnp.float32)
            )
            ds = load_dataset(
                cfg.dataset or "synthetic",
                cfg.data_dir or None,
                fallback_examples=max(n_examples, cfg.global_batch),
                image_shape=shape,
                num_classes=num_classes,
                seed=0,
            )
            if tuple(ds.images.shape[1:]) != tuple(shape):
                raise ValueError(
                    f"dataset images are {ds.images.shape[1:]} but the model "
                    f"was configured for {shape} (--image-size conflicts with "
                    "the real dataset's geometry)"
                )
            batches = device_batches(ds, mesh, cfg.global_batch, seed=1)
            return {
                "params": params,
                "model_state": model_state,
                "loss_fn": make_classification_loss(model),
                "batches": batches,
                "batch_spec": None,
            }

        return make

    return build


def _build_bert_workload(cfg_kwargs: dict):
    def build(cfg: WorkloadConfig):
        from distributed_tensorflow_tpu.data.text import (
            SyntheticMLM,
            SyntheticMLMConfig,
            bert_batch_specs,
            mlm_device_batches,
        )
        from distributed_tensorflow_tpu.models.bert import (
            BertConfig,
            BertForPreTraining,
            make_bert_pretraining_loss,
        )

        def make(mesh):
            seq_parallel = cfg.seq_parallel and "seq" in mesh.axis_names
            init_cfg = BertConfig(**cfg_kwargs)
            model_cfg = (
                dataclasses.replace(init_cfg, seq_axis="seq")
                if seq_parallel
                else init_cfg
            )
            # Init outside shard_map must not bind the seq axis; the param
            # tree is identical either way (tests/test_bert.py).
            init_model_ = BertForPreTraining(init_cfg)
            model = BertForPreTraining(model_cfg)
            L = init_cfg.max_position
            variables = init_model_.init(
                jax.random.key(0),
                jnp.zeros((1, L), jnp.int32),
                jnp.ones((1, L), bool),
                jnp.zeros((1, L), jnp.int32),
                train=False,
            )
            data = SyntheticMLM(
                SyntheticMLMConfig(
                    vocab_size=init_cfg.vocab_size, seq_len=L, seed=0
                )
            )
            batches = mlm_device_batches(
                data, mesh, cfg.global_batch, seq_sharded=bool(seq_parallel), seed=1
            )
            return {
                "params": variables["params"],
                "model_state": {},
                "loss_fn": make_bert_pretraining_loss(model),
                "batches": batches,
                "batch_spec": bert_batch_specs(
                    mesh, seq_sharded=bool(seq_parallel)
                ),
            }

        return make

    return build


def _presets() -> dict[str, WorkloadConfig]:
    from distributed_tensorflow_tpu.models import (
        InceptionV3,
        LeNet5,
        ResNet20,
        ResNet50,
    )

    return {
        "mnist_lenet": WorkloadConfig(
            name="mnist_lenet",
            build=_build_image_workload(LeNet5(), (28, 28, 1), 10),
            global_batch=128,
            num_steps=1000,
            learning_rate=0.05,
            dataset="mnist",
        ),
        "cifar_resnet20": WorkloadConfig(
            name="cifar_resnet20",
            build=_build_image_workload(ResNet20(), (32, 32, 3), 10),
            global_batch=256,
            num_steps=2000,
            learning_rate=0.1,
            dataset="cifar10",
        ),
        "imagenet_resnet50": WorkloadConfig(
            name="imagenet_resnet50",
            build=_build_image_workload(
                ResNet50(dtype=jnp.bfloat16), (224, 224, 3), 1000, n_examples=8192
            ),
            global_batch=256,
            num_steps=5000,
            learning_rate=0.4,  # linear-scaling rule for large global batch
        ),
        "imagenet_inception_async": WorkloadConfig(
            name="imagenet_inception_async",
            build=_build_image_workload(
                InceptionV3(dtype=jnp.bfloat16, aux_logits=False),
                (299, 299, 3),
                1000,
                n_examples=8192,
            ),
            global_batch=256,
            num_steps=5000,
            learning_rate=0.05,
            momentum=0.0,
            mode="stale",
            staleness=4,
        ),
        "bert_base": WorkloadConfig(
            name="bert_base",
            build=_build_bert_workload(
                dict(max_position=128, dropout_rate=0.1, dtype=jnp.bfloat16)
            ),
            global_batch=256,
            num_steps=10000,
            learning_rate=1e-4,
            optimizer="adam",
        ),
    }


PRESETS = _presets()


def run(cfg: WorkloadConfig, args: argparse.Namespace):
    from distributed_tensorflow_tpu.ckpt import Checkpointer
    from distributed_tensorflow_tpu.obs import make_metric_hook
    from distributed_tensorflow_tpu.parallel.mesh import (
        build_mesh,
        initialize_runtime,
    )
    from distributed_tensorflow_tpu.train import (
        create_train_state,
        fit,
        make_train_step,
    )
    from distributed_tensorflow_tpu.train.step import place_state

    initialize_runtime()
    mesh_spec = (
        {"data": -1, "seq": cfg.seq_parallel} if cfg.seq_parallel else {"data": -1}
    )
    mesh = build_mesh(mesh_spec)
    if jax.process_index() == 0:
        logging.info("workload=%s mesh=%s", cfg.name, dict(mesh.shape))

    pieces = cfg.build(cfg)(mesh)
    tx = _make_tx(cfg)
    state = place_state(
        create_train_state(
            pieces["params"],
            tx,
            pieces["model_state"],
            staleness=cfg.staleness if cfg.mode == "stale" else 0,
        ),
        mesh,
    )
    step = make_train_step(
        pieces["loss_fn"],
        tx,
        mesh,
        mode=cfg.mode,
        staleness=cfg.staleness if cfg.mode == "stale" else 0,
        batch_spec=pieces["batch_spec"],
    )

    ckpt = Checkpointer(args.ckpt_dir) if args.ckpt_dir else None
    start = 0
    if ckpt is not None:
        state, start = ckpt.restore_latest(state)
    hook = make_metric_hook(
        logdir=args.tb_dir, jsonl=args.metrics_jsonl or None
    )
    try:
        state, last = fit(
            state,
            step,
            pieces["batches"],
            num_steps=cfg.num_steps,
            rng=jax.random.key(args.seed),
            log_every=cfg.log_every,
            hooks=(hook,),
            checkpointer=ckpt,
            ckpt_every=cfg.ckpt_every or args.ckpt_every,
        )
        if ckpt is not None and ckpt.latest_step() != int(state.step):
            ckpt.save(int(state.step), state, force=True)
    finally:
        if ckpt is not None:
            ckpt.close()
        for w in getattr(hook, "writers", ()):
            w.close()
    return state, last


def main(argv: list[str] | None = None):
    parser = argparse.ArgumentParser(
        description="TPU-native distributed training (single SPMD entrypoint)"
    )
    parser.add_argument("--config", required=True, choices=sorted(PRESETS))
    parser.add_argument("--steps", type=int, default=0, help="override num_steps")
    parser.add_argument("--global-batch", type=int, default=0)
    parser.add_argument("--image-size", type=int, default=0)
    parser.add_argument("--seq-parallel", type=int, default=-1,
                        help="seq axis size for ring attention (BERT)")
    parser.add_argument("--staleness", type=int, default=-1)
    parser.add_argument("--log-every", type=int, default=0)
    parser.add_argument("--data-dir", default="",
                        help="directory with real dataset files (synthetic fallback)")
    parser.add_argument("--ckpt-dir", default="")
    parser.add_argument("--ckpt-every", type=int, default=0)
    parser.add_argument("--tb-dir", default="")
    parser.add_argument("--metrics-jsonl", default="")
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s: %(message)s"
    )
    cfg = PRESETS[args.config]
    overrides = {}
    if args.steps:
        overrides["num_steps"] = args.steps
    if args.global_batch:
        overrides["global_batch"] = args.global_batch
    if args.image_size:
        overrides["image_size"] = args.image_size
    if args.seq_parallel >= 0:
        overrides["seq_parallel"] = args.seq_parallel
    if args.staleness >= 0:
        overrides["staleness"] = args.staleness
        if args.staleness:
            overrides["mode"] = "stale"
    if args.log_every:
        overrides["log_every"] = args.log_every
    if args.data_dir:
        overrides["data_dir"] = args.data_dir
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    state, last = run(cfg, args)
    if jax.process_index() == 0 and last is not None:
        logging.info("final: %s", last)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
