"""``python -m distributed_tensorflow_tpu.cli.train --config=<workload>``.

Workload presets mirror the reference's five configurations
(BASELINE.json "configs" / SURVEY.md §2 workload rows) one-to-one:

=========================  ====================================================
preset                     reference configuration it rebuilds
=========================  ====================================================
``mnist_lenet``            MNIST LeNet-5, single-process sync SGD sanity run
``cifar_resnet20``         CIFAR-10 ResNet-20, SyncReplicasOptimizer PS (sync DP)
``imagenet_resnet50``      ImageNet ResNet-50, 8-worker NCCL allreduce (sync DP)
``imagenet_inception_async`` ImageNet Inception-v3, async PS → stale-K emulation
``bert_base``              BERT-base pretraining (MLM+NSP), large-embedding DP
=========================  ====================================================

Every preset runs on any mesh size (DP width comes from the devices present,
not from the config — there is no worker count to configure away). Datasets
are seeded synthetic stand-ins with learnable structure (zero-egress
environment); point ``--data-dir`` at real data when present (data/readers:
MNIST idx, CIFAR pickles, ImageNet imagefolder/TFRecord caches).

Round-2 capabilities beyond the preset table: warmup+decay LR schedules per
workload, periodic held-out evaluation (``--eval-every``), the native C++
input pipeline feeding the image presets (random-resized-crop/flip on the
worker pool, prefetch off the Python thread), resume-correct data streams
(a restored run consumes batches N.. not 0..), and ``--profile-dir`` xprof
trace capture.
"""

from __future__ import annotations

import argparse
import dataclasses
import logging
from collections.abc import Callable, Iterator
from pathlib import Path
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class WorkloadConfig:
    """One training workload: model + data + optimization, mesh-agnostic."""

    name: str
    build: Callable[["WorkloadConfig"], Any]  # cfg -> make(mesh) -> pieces
    global_batch: int
    num_steps: int
    learning_rate: float
    momentum: float = 0.9
    optimizer: str = "sgd"  # "sgd" | "adam" | "adamw"
    weight_decay: float = 0.0  # adamw decoupled weight decay
    clip_norm: float = 0.0  # >0: global-norm gradient clipping
    grad_accum: int = 1  # >1: micro-slice gradient accumulation in-step
    lr_schedule: str = "constant"  # "constant" | "warmup_cosine" | "piecewise"
    warmup_steps: int = 0
    mode: str = "sync"  # "sync" | "stale"
    staleness: int = 0
    seq_parallel: int = 0  # >0: seq axis size for ring attention (BERT)
    sp_impl: str = "ring"  # "ring" | "ulysses" (all-to-all head re-partition)
    tensor_parallel: int = 0  # >0: model axis size for Megatron-TP (BERT)
    moe_experts: int = 0  # >0: switch-MoE FFN with this many experts (BERT)
    expert_parallel: int = 0  # >0: expert axis size for MoE sharding (BERT)
    # "replicated" | "alltoall" (GShard a2a over replicated tokens) |
    # "sharded" (production GShard: batch sharded over the expert axis)
    moe_dispatch: str = "replicated"
    moe_topk: int = 1  # routing fan-out: 1 = Switch, 2 = GShard top-2
    pipeline_parallel: int = 0  # >0: pipeline axis size, stage-sharded encoder (BERT)
    pipeline_microbatches: int = 0  # GPipe M; 0 -> 4 * pipeline_parallel
    remat: bool = False  # activation remat over encoder layers (BERT)
    bert_layers: int = 0  # >0: override encoder depth (smoke runs)
    bert_hidden: int = 0  # >0: override hidden size (intermediate = 4x)
    bert_vocab: int = 0  # >0: override vocab size (smoke runs)
    image_size: int = 0  # overridable per run
    dataset: str = ""  # real-dataset name for data/readers.load_dataset
    data_dir: str = ""  # where to look for it; synthetic fallback otherwise
    augment: str = ""  # "" | "cifar" (pad-crop+flip) | "imagenet" (RRC+flip)
    native_input: bool = True  # use the C++ pipeline when buildable
    # > 0: pre-place this many batches in HBM and cycle them — the training
    # loop then runs at device rate with ZERO host->device transfers in the
    # hot path. For throughput/trajectory runs on tunneled or feed-bound
    # hosts (the r3 ImageNet runs were host-bound at ~0.2 steps/s); the
    # model revisits the pool every N steps, so it is NOT for convergence
    # claims beyond pool-sized epochs.
    device_pool: int = 0
    # Feed-stage lookahead (data/prefetch.py): a feeder thread runs batch
    # assembly + host->device transfer this many batches ahead of the step
    # stream, so the loop's next(it) is a queue pop in steady state. 0 =
    # synchronous feed (assembly on the critical path). Streams are
    # bit-identical either way — the wrapper never skips or reorders.
    prefetch: int = 2
    log_every: int = 50
    ckpt_every: int = 0


def make_lr_schedule(cfg: WorkloadConfig) -> optax.Schedule:
    """The per-workload LR schedule (reference-era ImageNet/BERT recipes).

    ``warmup_cosine``: linear warmup to the peak LR then cosine decay to ~0
    over ``num_steps`` (the standard large-batch ImageNet/BERT recipe — the
    linear-scaling rule's required companion). ``piecewise``: x0.1 at 50% and
    75% of the run (classic step-decay ResNet recipe). ``constant``: the
    reference harness's fixed LR.
    """
    if cfg.lr_schedule == "constant":
        return optax.constant_schedule(cfg.learning_rate)
    if cfg.lr_schedule == "warmup_cosine":
        warmup = cfg.warmup_steps or max(1, cfg.num_steps // 20)
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=cfg.learning_rate,
            warmup_steps=warmup,
            decay_steps=max(cfg.num_steps, warmup + 1),
            end_value=cfg.learning_rate * 1e-3,
        )
    if cfg.lr_schedule == "piecewise":
        return optax.piecewise_constant_schedule(
            cfg.learning_rate,
            {cfg.num_steps // 2: 0.1, (3 * cfg.num_steps) // 4: 0.1},
        )
    raise ValueError(f"unknown lr_schedule {cfg.lr_schedule!r}")


def _decay_mask(params):
    """AdamW decoupled-weight-decay mask: the canonical BERT recipe
    (google-research/bert AdamWeightDecayOptimizer exclude_from_weight_decay)
    applies decay to weight matrices/embeddings only — LayerNorm/BatchNorm
    scales and every bias are excluded. Name- and rank-based: 1-D leaves
    (biases, norm scales) never decay; nor does anything named like a bias
    (MoE expert bias stacks are 2-D) or living under a norm module."""

    def decays(path, leaf) -> bool:
        names = tuple(
            str(p.key) for p in path if isinstance(p, jax.tree_util.DictKey)
        )
        last = names[-1] if names else ""
        if leaf.ndim < 2 or "bias" in last or last in ("experts_b1", "experts_b2"):
            return False
        norm_mod = any(
            n == "ln" or n.endswith("_ln") or n.endswith("_bn")
            or "LayerNorm" in n or "BatchNorm" in n
            for n in names
        )
        return not norm_mod

    return jax.tree_util.tree_map_with_path(decays, params)


def _make_tx(cfg: WorkloadConfig) -> tuple[optax.GradientTransformation, optax.Schedule]:
    # Global-norm clipping (cfg.clip_norm) is deliberately NOT chained here:
    # optax.clip_by_global_norm inside the shard_mapped step sees per-shard
    # slices of sharded params and would clip with a different scale on each
    # shard (desynchronizing replicated leaves). The engine applies the
    # spec-aware clip instead — see make_train_step(clip_norm=...).
    schedule = make_lr_schedule(cfg)
    if cfg.optimizer == "adamw":
        tx = optax.adamw(
            schedule, weight_decay=cfg.weight_decay, mask=_decay_mask
        )
    elif cfg.optimizer == "adam":
        tx = optax.adam(schedule)
    elif cfg.momentum:
        tx = optax.sgd(schedule, momentum=cfg.momentum)
    else:
        tx = optax.sgd(schedule)
    return tx, schedule


def _image_batches(cfg, ds, mesh, model_hw, *, train, seed, start_step=0):
    """Train/eval batch stream over an image dataset: native C++ pipeline
    with augmentation when available, numpy fallback otherwise."""
    from distributed_tensorflow_tpu.data import device_batches, native_device_batches
    from distributed_tensorflow_tpu.data.native import native_available
    from distributed_tensorflow_tpu.data.readers import IMAGENET_MEAN, IMAGENET_STD

    store_hw = tuple(ds.images.shape[1:3])
    is_u8 = ds.images.dtype == np.uint8
    # Per-channel normalization belongs to the real-pixel path; synthetic
    # float templates are already ~N(0,1).
    mean = IMAGENET_MEAN if (is_u8 and cfg.augment == "imagenet") else None
    std = IMAGENET_STD if mean is not None else None
    out_size = model_hw if store_hw != model_hw else None
    if train and cfg.native_input and native_available():
        return native_device_batches(
            ds,
            mesh,
            cfg.global_batch,
            out_size=out_size,
            pad=4 if cfg.augment == "cifar" else 0,
            flip=cfg.augment in ("cifar", "imagenet"),
            rrc=cfg.augment == "imagenet",
            mean=mean,
            stddev=std,
            seed=seed,
            start_step=start_step,
        )
    return device_batches(
        ds,
        mesh,
        cfg.global_batch,
        seed=seed,
        start_step=start_step,
        out_size=out_size,
        mean=mean,
        stddev=std,
    )


def _build_image_workload(
    model, image_shape, num_classes, n_examples=4096, model_factory=None
):
    """``model_factory(cfg, shape)`` (optional) builds the model per-config —
    for models whose architecture depends on the run geometry (Inception's
    aux head needs the full 299x299 train-time feature map)."""

    def build(cfg: WorkloadConfig):
        from distributed_tensorflow_tpu.data.readers import load_dataset
        from distributed_tensorflow_tpu.train.objectives import (
            init_model,
            make_classification_loss,
            make_classification_metrics,
        )

        shape = image_shape
        if cfg.image_size:
            shape = (cfg.image_size, cfg.image_size, image_shape[-1])
        net = model_factory(cfg, shape) if model_factory is not None else model

        def make(mesh):
            params, model_state = init_model(
                net, jax.random.key(0), jnp.zeros((1, *shape), jnp.float32)
            )

            def load(split):
                return load_dataset(
                    cfg.dataset or "synthetic",
                    cfg.data_dir or None,
                    split=split,
                    fallback_examples=max(n_examples, cfg.global_batch),
                    image_shape=shape,
                    num_classes=num_classes,
                    seed=0 if split == "train" else 1,
                )

            ds = load("train")
            store = tuple(ds.images.shape[1:3])
            if store != shape[:2] and (
                ds.images.dtype != np.uint8 or store[0] < shape[0] or store[1] < shape[1]
            ):
                raise ValueError(
                    f"dataset images are {ds.images.shape[1:]} but the model "
                    f"was configured for {shape}; a u8 store may only be "
                    "LARGER than the model geometry (train-time crop)"
                )
            # Val split loads lazily on the first eval pass — preparing a
            # real val cache (full PIL decode) must not tax runs that never
            # evaluate (--eval-every=0).
            eval_ds_box: list = []

            def eval_batches(n_batches: int) -> Iterator[dict]:
                if not eval_ds_box:
                    eval_ds_box.append(load("val"))
                it = _image_batches(
                    cfg, eval_ds_box[0], mesh, shape[:2], train=False, seed=101
                )
                for _ in range(n_batches):
                    yield next(it)

            return {
                "params": params,
                "model_state": model_state,
                # Serving hooks (cli/serve.py): the bare module + the input
                # geometry its executables must be compiled for.
                "model": net,
                "image_shape": shape,
                "loss_fn": make_classification_loss(net),
                "batches": lambda start_step=0: _image_batches(
                    cfg, ds, mesh, shape[:2], train=True, seed=1, start_step=start_step
                ),
                "batch_spec": None,
                "metric_fn": make_classification_metrics(net),
                "eval_batches": eval_batches,
            }

        return make

    return build


def _build_bert_workload(cfg_kwargs: dict):
    def build(cfg: WorkloadConfig):
        from distributed_tensorflow_tpu.data.text import (
            SyntheticMLM,
            SyntheticMLMConfig,
            TextCorpusConfig,
            TextCorpusMLM,
            bert_batch_specs,
            mlm_device_batches,
        )
        from distributed_tensorflow_tpu.models.bert import (
            BertConfig,
            BertForPreTraining,
            make_bert_pretraining_loss,
        )

        def make(mesh):
            from distributed_tensorflow_tpu.models.bert import bert_param_specs

            seq_parallel = cfg.seq_parallel and "seq" in mesh.axis_names
            tp = mesh.shape.get("model", 1)
            ep = mesh.shape.get("expert", 1)
            pp = mesh.shape.get("pipeline", 1)
            # GShard token-sharded layout: the expert axis carries batch rows
            # (expert group ≡ data group), so non-MoE compute shards over it
            # too and the MoE a2a routes straight from the local slice.
            expert_sharded = cfg.moe_dispatch == "sharded" and ep > 1
            if cfg.moe_dispatch == "sharded" and ep <= 1:
                raise ValueError(
                    "--moe-dispatch=sharded requires --expert-parallel > 1"
                )
            kwargs = dict(cfg_kwargs)
            if cfg.bert_layers:
                kwargs["num_layers"] = cfg.bert_layers
            if cfg.bert_hidden:
                kwargs["hidden_size"] = cfg.bert_hidden
                kwargs["intermediate_size"] = 4 * cfg.bert_hidden
            if cfg.bert_vocab:
                kwargs["vocab_size"] = cfg.bert_vocab
            init_cfg = BertConfig(**kwargs)
            if cfg.moe_experts:
                if cfg.moe_experts % max(ep, 1):
                    raise ValueError(
                        f"--moe-experts={cfg.moe_experts} not divisible by "
                        f"--expert-parallel={ep}"
                    )
                if not 1 <= cfg.moe_topk <= cfg.moe_experts:
                    raise ValueError(
                        f"--moe-topk={cfg.moe_topk} must be in "
                        f"[1, --moe-experts={cfg.moe_experts}]"
                    )
                # Init with the GLOBAL expert count (expert_parallel=1) and
                # the replicated dispatch — "sharded" needs a bound expert
                # axis and an expert-sharded batch, neither of which exists
                # at init time; the param tree is dispatch-independent.
                init_cfg = dataclasses.replace(
                    init_cfg,
                    moe_experts=cfg.moe_experts,
                    moe_topk=cfg.moe_topk,
                    moe_dispatch=(
                        "replicated"
                        if cfg.moe_dispatch == "sharded"
                        else cfg.moe_dispatch
                    ),
                )
            model_cfg = init_cfg
            if seq_parallel:
                model_cfg = dataclasses.replace(
                    model_cfg, seq_axis="seq", sp_impl=cfg.sp_impl
                )
            if tp > 1:
                model_cfg = dataclasses.replace(
                    model_cfg, model_axis="model", model_parallel=tp
                )
            if ep > 1:
                model_cfg = dataclasses.replace(
                    model_cfg,
                    expert_axis="expert",
                    expert_parallel=ep,
                    moe_dispatch=cfg.moe_dispatch or "replicated",
                )
            if pp > 1:
                # Per-DP-shard rows must split into the GPipe microbatches.
                dp = mesh.shape.get("data", 1) * mesh.shape.get("replica", 1)
                micro = cfg.pipeline_microbatches or 4 * pp
                rows = cfg.global_batch // dp
                if rows % micro:
                    raise ValueError(
                        f"per-shard batch {rows} (global {cfg.global_batch} / "
                        f"dp {dp}) not divisible by pipeline_microbatches "
                        f"{micro}"
                    )
                # Init config gets pipeline_parallel (stacked params, axis
                # unset so init runs the sequential scan outside shard_map);
                # the training model additionally binds the mesh axis.
                init_cfg = dataclasses.replace(
                    init_cfg, pipeline_parallel=pp, pipeline_microbatches=micro
                )
                model_cfg = dataclasses.replace(
                    model_cfg,
                    pipeline_axis="pipeline",
                    pipeline_parallel=pp,
                    pipeline_microbatches=micro,
                )
            elif cfg.pipeline_parallel > 1:
                # No pipeline mesh axis but a pipeline-trained config: the
                # SERVING fallback path (cli/serve.py restoring a stacked
                # checkpoint onto a mesh without the axis, e.g. single-chip
                # degradation). Stacked params with the axis unset run the
                # sequential scan — mathematically identical to the GPipe
                # schedule, so one checkpoint restores either way. Training
                # never lands here: run() always puts the axis on the mesh
                # when cfg.pipeline_parallel > 1.
                init_cfg = dataclasses.replace(
                    init_cfg, pipeline_parallel=cfg.pipeline_parallel
                )
                model_cfg = dataclasses.replace(
                    model_cfg, pipeline_parallel=cfg.pipeline_parallel
                )
            if cfg.remat:
                # Training model only — init's one forward needs no remat,
                # and the param tree is identical either way.
                model_cfg = dataclasses.replace(model_cfg, remat=True)
            # Init outside shard_map must not bind the seq axis; the param
            # tree is identical either way (tests/test_bert.py).
            init_model_ = BertForPreTraining(init_cfg)
            model = BertForPreTraining(model_cfg)
            L = init_cfg.max_position
            variables = init_model_.init(
                jax.random.key(0),
                jnp.zeros((1, L), jnp.int32),
                jnp.ones((1, L), bool),
                jnp.zeros((1, L), jnp.int32),
                train=False,
            )
            # Real corpus when --data-dir holds *.txt (one sentence per
            # line, blank line between documents — the classic BERT
            # pretraining input); seeded synthetic Markov chains otherwise.
            # A val/*.txt subdirectory provides genuinely unseen eval text
            # (tokenized with the TRAIN vocab).
            txt_files, val_files = [], []
            if cfg.data_dir:
                from pathlib import Path

                txt_files = sorted(Path(cfg.data_dir).glob("*.txt"))
                val_files = sorted((Path(cfg.data_dir) / "val").glob("*.txt"))
            eval_data = None
            if txt_files:
                corpus_cfg = TextCorpusConfig(
                    seq_len=L, vocab_size=init_cfg.vocab_size, seed=0
                )
                data = TextCorpusMLM(txt_files, corpus_cfg)
                if val_files:
                    eval_data = TextCorpusMLM(
                        val_files, corpus_cfg, vocab_from=data
                    )
                else:
                    logger.warning(
                        "no val/*.txt under %s; eval will RESAMPLE THE "
                        "TRAINING TEXT with fresh masking (not held-out "
                        "documents) — provide a val split for a true "
                        "held-out metric",
                        cfg.data_dir,
                    )
            else:
                if cfg.data_dir:
                    logger.warning(
                        "no *.txt under %s; FALLING BACK TO SYNTHETIC MLM DATA%s",
                        cfg.data_dir,
                        (
                            f" (IGNORING {len(val_files)} val/*.txt files — "
                            "training text must live at the top level)"
                            if val_files
                            else ""
                        ),
                    )
                data = SyntheticMLM(
                    SyntheticMLMConfig(
                        vocab_size=init_cfg.vocab_size, seq_len=L, seed=0
                    )
                )
            from distributed_tensorflow_tpu.models.bert import make_bert_eval_metrics

            def eval_batches(n_batches: int) -> Iterator[dict]:
                # Held-out stream: the val corpus when one exists, else a
                # disjoint seed over the training source (fresh sampling and
                # masking — for synthetic data that IS unseen data; for a
                # real corpus the build-time warning above applies).
                it = mlm_device_batches(
                    eval_data if eval_data is not None else data,
                    mesh,
                    cfg.global_batch,
                    seq_sharded=bool(seq_parallel),
                    expert_sharded=expert_sharded,
                    seed=900_001,
                )
                for _ in range(n_batches):
                    yield next(it)

            return {
                "params": variables["params"],
                # Serving hook (cli/serve.py): the axis-free model, exactly
                # as init used it (no seq/model/pipeline axes bound; stacked
                # pipeline params run the sequential scan). On a mesh WITH
                # model axes the serving engine re-binds them itself
                # (BertInferenceEngine._serve_config) — param_specs below
                # carries the matching sharding contract.
                "model": init_model_,
                "param_specs": (
                    bert_param_specs(
                        variables["params"],
                        model_axis="model" if tp > 1 else None,
                        expert_axis="expert" if ep > 1 else None,
                        pipeline_axis="pipeline" if pp > 1 else None,
                    )
                    if tp > 1 or ep > 1 or pp > 1
                    else None
                ),
                "model_state": {},
                "loss_fn": make_bert_pretraining_loss(model),
                "batches": lambda start_step=0: mlm_device_batches(
                    data,
                    mesh,
                    cfg.global_batch,
                    seq_sharded=bool(seq_parallel),
                    expert_sharded=expert_sharded,
                    seed=1,
                    start_step=start_step,
                ),
                "batch_spec": bert_batch_specs(
                    mesh,
                    seq_sharded=bool(seq_parallel),
                    expert_sharded=expert_sharded,
                ),
                "metric_fn": make_bert_eval_metrics(model),
                "eval_batches": eval_batches,
            }

        return make

    return build


def _build_causal_lm_workload(cfg_kwargs: dict):
    def build(cfg: WorkloadConfig):
        from distributed_tensorflow_tpu.data.text import (
            SyntheticLM,
            SyntheticMLMConfig,
            lm_batch_specs,
            mlm_device_batches,
        )
        from distributed_tensorflow_tpu.models.causal_lm import (
            CausalLM,
            CausalLMConfig,
            causal_param_specs,
            make_causal_lm_eval_metrics,
            make_causal_lm_loss,
        )

        def make(mesh):
            tp = mesh.shape.get("model", 1)
            ep = mesh.shape.get("expert", 1)
            pp = mesh.shape.get("pipeline", 1)
            if ep > 1 or pp > 1:
                raise ValueError(
                    "causal-LM workloads shard over data/model axes only "
                    "(no MoE or pipeline decoder variant)"
                )
            kwargs = dict(cfg_kwargs)
            if cfg.bert_layers:
                kwargs["num_layers"] = cfg.bert_layers
            if cfg.bert_hidden:
                kwargs["hidden_size"] = cfg.bert_hidden
                kwargs["intermediate_size"] = 4 * cfg.bert_hidden
            if cfg.bert_vocab:
                kwargs["vocab_size"] = cfg.bert_vocab
            init_cfg = CausalLMConfig(**kwargs)
            model_cfg = init_cfg
            if tp > 1:
                model_cfg = dataclasses.replace(
                    model_cfg, model_axis="model", model_parallel=tp
                )
            # Init outside shard_map must not bind the model axis; the
            # param tree is identical either way (same rule as BERT).
            init_model_ = CausalLM(init_cfg)
            model = CausalLM(model_cfg)
            L = init_cfg.max_position
            variables = init_model_.init(
                jax.random.PRNGKey(0),
                jnp.zeros((1, L), jnp.int32),
                jnp.ones((1, L), bool),
            )
            data = SyntheticLM(
                SyntheticMLMConfig(
                    vocab_size=init_cfg.vocab_size, seq_len=L, seed=0
                )
            )

            def eval_batches(n_batches: int) -> Iterator[dict]:
                it = mlm_device_batches(
                    data, mesh, cfg.global_batch, seed=900_001
                )
                for _ in range(n_batches):
                    yield next(it)

            return {
                "params": variables["params"],
                # Serving hooks (cli/serve.py): axis-free model + the
                # decode marker that routes the config to CausalLMEngine's
                # prefill/decode grid instead of the one-shot BERT path.
                "model": init_model_,
                "decode": True,
                "param_specs": (
                    causal_param_specs(variables["params"])
                    if tp > 1
                    else None
                ),
                "model_state": {},
                "loss_fn": make_causal_lm_loss(model),
                "batches": lambda start_step=0: mlm_device_batches(
                    data,
                    mesh,
                    cfg.global_batch,
                    seed=1,
                    start_step=start_step,
                ),
                "batch_spec": lm_batch_specs(mesh),
                "metric_fn": make_causal_lm_eval_metrics(model),
                "eval_batches": eval_batches,
            }

        return make

    return build


def _presets() -> dict[str, WorkloadConfig]:
    from distributed_tensorflow_tpu.models import (
        InceptionV3,
        LeNet5,
        ResNet20,
        ResNet50,
    )

    return {
        "mnist_lenet": WorkloadConfig(
            name="mnist_lenet",
            build=_build_image_workload(LeNet5(), (28, 28, 1), 10),
            global_batch=128,
            num_steps=1000,
            learning_rate=0.05,
            dataset="mnist",
        ),
        "cifar_resnet20": WorkloadConfig(
            name="cifar_resnet20",
            build=_build_image_workload(ResNet20(), (32, 32, 3), 10),
            global_batch=256,
            num_steps=2000,
            learning_rate=0.1,
            lr_schedule="piecewise",
            dataset="cifar10",
            augment="cifar",
        ),
        "imagenet_resnet50": WorkloadConfig(
            name="imagenet_resnet50",
            build=_build_image_workload(
                ResNet50(dtype=jnp.bfloat16), (224, 224, 3), 1000, n_examples=8192
            ),
            global_batch=256,
            num_steps=5000,
            learning_rate=0.4,  # linear-scaling rule for large global batch
            lr_schedule="warmup_cosine",
            dataset="imagenet",
            augment="imagenet",
        ),
        "imagenet_inception_async": WorkloadConfig(
            name="imagenet_inception_async",
            build=_build_image_workload(
                None,
                (299, 299, 3),
                1000,
                n_examples=8192,
                # Aux classifier on at the canonical 299x299 geometry (the
                # reference-era Inception-v3 recipe trains main + 0.3*aux);
                # smaller smoke geometries can't feed the aux head's 5x5
                # VALID conv, so it gates on the run's image size.
                model_factory=lambda cfg, shape: InceptionV3(
                    dtype=jnp.bfloat16, aux_logits=shape[0] >= 299
                ),
            ),
            global_batch=256,
            num_steps=5000,
            learning_rate=0.05,
            momentum=0.0,
            lr_schedule="warmup_cosine",
            mode="stale",
            staleness=4,
            dataset="imagenet",
            augment="imagenet",
        ),
        "bert_base": WorkloadConfig(
            name="bert_base",
            build=_build_bert_workload(
                dict(max_position=128, dropout_rate=0.1, dtype=jnp.bfloat16)
            ),
            global_batch=256,
            num_steps=10000,
            learning_rate=1e-4,
            # The canonical BERT pretraining recipe: AdamW with decoupled
            # weight decay (masked off LayerNorm scales and all biases —
            # _decay_mask) + spec-aware global-norm clipping at 1.0
            # (applied inside the step; see make_train_step clip_norm).
            optimizer="adamw",
            weight_decay=0.01,
            clip_norm=1.0,
            lr_schedule="warmup_cosine",
            warmup_steps=1000,
        ),
        "lm_base": WorkloadConfig(
            name="lm_base",
            build=_build_causal_lm_workload(
                dict(max_position=128, dtype=jnp.bfloat16)
            ),
            global_batch=256,
            num_steps=10000,
            learning_rate=1e-4,
            # Same decoupled-decay recipe as bert_base — the decoder reuses
            # its blocks, so the optimizer hygiene carries over unchanged.
            optimizer="adamw",
            weight_decay=0.01,
            clip_norm=1.0,
            lr_schedule="warmup_cosine",
            warmup_steps=1000,
        ),
    }


PRESETS = _presets()


def run(cfg: WorkloadConfig, args: argparse.Namespace):
    from distributed_tensorflow_tpu.ckpt import Checkpointer
    from distributed_tensorflow_tpu.obs import make_metric_hook, trace_steps
    from distributed_tensorflow_tpu.parallel.mesh import (
        build_mesh,
        initialize_runtime,
    )
    from distributed_tensorflow_tpu.train import (
        create_train_state,
        fit,
        make_eval_step,
        make_rng,
        make_train_step,
    )
    from distributed_tensorflow_tpu.train.step import place_state

    # Multi-host bootstrap: on TPU pods the coordinator/process topology
    # comes from slice metadata (zero flags); the explicit flags are the
    # documented entrypoint for CPU/GPU clusters and manual launchers.
    initialize_runtime(
        coordinator_address=getattr(args, "coordinator_address", "") or None,
        num_processes=(
            args.num_processes
            if getattr(args, "num_processes", 0) > 0
            else None
        ),
        process_id=(
            args.process_id if getattr(args, "process_id", -1) >= 0 else None
        ),
    )
    mesh_spec = {"data": -1}
    if cfg.seq_parallel:
        mesh_spec["seq"] = cfg.seq_parallel
    if cfg.tensor_parallel:
        mesh_spec["model"] = cfg.tensor_parallel
    if cfg.expert_parallel:
        mesh_spec["expert"] = cfg.expert_parallel
    if cfg.pipeline_parallel:
        mesh_spec["pipeline"] = cfg.pipeline_parallel
    mesh = build_mesh(mesh_spec)
    if jax.process_index() == 0:
        logging.info("workload=%s mesh=%s", cfg.name, dict(mesh.shape))

    pieces = cfg.build(cfg)(mesh)
    # A model/expert axis with no param actually sharded over it means every
    # group of those devices computes identical grads — silent N-fold waste,
    # never what the user asked for. Check each requested axis appears in at
    # least one param spec (a non-None but all-replicated tree is just as
    # wasteful as no tree).
    for axis, width in (
        ("model", cfg.tensor_parallel),
        ("expert", cfg.expert_parallel),
        ("pipeline", cfg.pipeline_parallel),
    ):
        if width <= 1:
            continue
        specs = pieces.get("param_specs")
        leaves = (
            jax.tree_util.tree_leaves(
                specs, is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec)
            )
            if specs is not None
            else []
        )
        from distributed_tensorflow_tpu.train.step import _spec_axes

        if not any(axis in _spec_axes(s) for s in leaves):
            raise ValueError(
                f"a {width}-way {axis!r} axis was requested but workload "
                f"{cfg.name!r} shards no params over it"
            )
    tx, lr_schedule = _make_tx(cfg)
    host_state = create_train_state(
        pieces["params"],
        tx,
        pieces["model_state"],
        staleness=cfg.staleness if cfg.mode == "stale" else 0,
    )
    state_specs = None
    if pieces.get("param_specs") is not None:
        from distributed_tensorflow_tpu.train.step import make_state_specs

        state_specs = make_state_specs(host_state, tx, pieces["param_specs"])
    state = place_state(host_state, mesh, state_specs)
    step = make_train_step(
        pieces["loss_fn"],
        tx,
        mesh,
        mode=cfg.mode,
        staleness=cfg.staleness if cfg.mode == "stale" else 0,
        batch_spec=pieces["batch_spec"],
        state_specs=state_specs,
        clip_norm=cfg.clip_norm,
        grad_accum=cfg.grad_accum,
    )

    # Flight recorder (--dump-dir, obs/flightrec.py): train records almost
    # nothing per step (the hot loop stays clean), but the resilience/
    # fault-injection paths record their events here and an unhandled
    # failure dumps the ring for postmortem. Built BEFORE the checkpointer
    # and feed so the injector hooks below can carry it.
    recorder = None
    dump_dir = getattr(args, "dump_dir", "") or ""
    if dump_dir:
        from distributed_tensorflow_tpu.obs.flightrec import FlightRecorder
        from distributed_tensorflow_tpu.obs.memory import default_registry

        recorder = FlightRecorder(dump_dir=dump_dir)
        recorder.attach(memz_fn=default_registry().snapshot)

    # Deterministic fault injection (--fault-plan, train/faultinject.py):
    # a seeded schedule of slow_step/feeder_error/nonfinite_loss/
    # ckpt_write_error/host_drop events carried into the loop, the feed
    # stage, and the checkpointer. Chaos rehearsals reproduce from the
    # same spec string.
    fault_injector = None
    fault_plan_spec = getattr(args, "fault_plan", "") or ""
    if fault_plan_spec:
        from distributed_tensorflow_tpu.train.faultinject import (
            FaultInjector,
            FaultPlan,
        )

        plan = FaultPlan.parse(fault_plan_spec, num_steps=cfg.num_steps)
        fault_injector = FaultInjector(plan, recorder=recorder)
        logging.info(
            "fault plan armed: %d scheduled events", len(plan.events)
        )

    resilient = bool(getattr(args, "resilient", False))
    if resilient and cfg.device_pool > 0:
        raise SystemExit(
            "--resilient does not compose with --device-pool (the pool is "
            "rebuilt per restart and would replay positions 0..N-1)"
        )
    ckpt = (
        Checkpointer(args.ckpt_dir, fault_injector=fault_injector)
        if args.ckpt_dir
        else None
    )
    start = 0
    if ckpt is not None:
        state, start = ckpt.restore_latest(state)
    # Resume-correct stream: batches start at N, not 0 (the fix for the
    # reference-era replay-on-restart). Resilient mode builds its streams
    # through make_batches below instead (one per restart segment).
    batches = None
    if not resilient:
        batches = pieces["batches"](0 if cfg.device_pool > 0 else start)
    if cfg.device_pool > 0:
        # Device-resident pool: materialize the first N batches in HBM once
        # and cycle — the host (and on this platform, the tunnel) leaves the
        # hot loop entirely. Safe to reuse batches across steps: the train
        # step donates only the state, never the batch. Resume-correctness
        # for pool mode means something different than for streams: the
        # pool is ALWAYS stream positions 0..N-1 and a resumed run re-enters
        # the cycle at step % N, exactly reproducing the uninterrupted
        # trajectory (building the pool from position `start` instead would
        # silently train on different data after every restart).
        src = batches
        pool = [next(src) for _ in range(cfg.device_pool)]
        # Block on the WHOLE pool before rotating: after rotation pool[-1]
        # is no longer the last-enqueued transfer, so a single-leaf wait
        # would let later transfers bleed into the first timed step.
        jax.block_until_ready(pool)
        pool = pool[start % cfg.device_pool:] + pool[: start % cfg.device_pool]
        close_src = getattr(src, "close", None)
        if close_src is not None:
            close_src()
        if jax.process_index() == 0:
            logging.info(
                "device_pool=%d batches resident in HBM; host feed is out "
                "of the hot loop", cfg.device_pool,
            )

        import itertools

        batches = itertools.cycle(pool)

    from distributed_tensorflow_tpu.data.prefetch import prefetch
    from distributed_tensorflow_tpu.obs.metrics import FeedMetrics

    feed_metrics = FeedMetrics()
    if cfg.device_pool <= 0 and not resilient:
        # Async feed stage: assembly + host->device transfer run on a
        # feeder thread, cfg.prefetch batches ahead (0 = synchronous with
        # the same metrics surface). Device-pool runs skip it — the pool is
        # already resident in HBM, there is nothing to overlap.
        batches = prefetch(
            batches, cfg.prefetch, metrics=feed_metrics,
            fault_injector=fault_injector,
        )

    evaluate = None
    if args.eval_every and pieces.get("metric_fn") and pieces.get("eval_batches"):
        eval_step = make_eval_step(
            pieces["metric_fn"],
            mesh,
            batch_spec=pieces["batch_spec"],
            state_specs=state_specs,
            return_sums=True,
        )

        def evaluate(state):
            # (num, den) sums carry across the whole pass and divide once —
            # the global ratio, not a mean of per-batch ratios (which would
            # over-weight batches with few masked tokens).
            from distributed_tensorflow_tpu.train.step import aggregate_metric_sums

            return aggregate_metric_sums(
                eval_step(state, batch)
                for batch in pieces["eval_batches"](args.eval_batches)
            )

    def lr_hook(step_: int, state_, metrics: dict) -> None:
        # Mutates before the writers run (hook order) — `lr` lands in every
        # JSONL/TB record without touching the compiled step.
        if "loss" in metrics:
            metrics["lr"] = float(lr_schedule(step_ - 1))

    hook = make_metric_hook(logdir=args.tb_dir, jsonl=args.metrics_jsonl)

    # Fleet health beacon (--beacon-dir): per-step timeline + straggler
    # detector feeding one atomically-replaced JSON file per host, refreshed
    # at the log cadence. Aggregation is pull-based (obs/fleet.py
    # read_beacons / fleet_summary) — hosts never talk to each other.
    timeline = None
    hooks = (lr_hook, hook)
    beacon_dir = getattr(args, "beacon_dir", "") or ""
    if beacon_dir:
        from distributed_tensorflow_tpu.obs.fleet import HostBeacon, StepTimeline

        timeline = StepTimeline()
        beacon = HostBeacon(
            beacon_dir, jax.process_index(), timeline,
            extras=fault_injector.summary if fault_injector is not None else None,
        )

        def beacon_hook(step_: int, state_, metrics_: dict) -> None:
            beacon.write()

        hooks = (lr_hook, hook, beacon_hook)
    import contextlib

    # Host-side span tracing (obs/trace.py): ring-buffered step-phase
    # spans, exported as Chrome trace-event JSON at run end. Distinct from
    # --profile-dir, which captures the DEVICE side via jax.profiler.
    from distributed_tensorflow_tpu.obs.trace import Tracer

    trace_dir = getattr(args, "trace_dir", "") or ""
    tracer = (
        Tracer(buffer_size=getattr(args, "trace_buffer", 4096) or 4096)
        if trace_dir
        else None
    )
    profile_steps = getattr(args, "profile_steps", 0) or 0
    if profile_steps and not args.profile_dir:
        raise SystemExit("--profile-steps requires --profile-dir")
    profile_cm = (
        trace_steps(args.profile_dir, num_steps=profile_steps or None)
        if args.profile_dir
        else contextlib.nullcontext()
    )
    if recorder is not None and tracer is not None:
        recorder.attach(tracer_fn=tracer.summary)
    try:
        with profile_cm as win:
            step_fn = step
            if profile_steps:
                # Armed window: the profiler runs for exactly N dispatched
                # steps instead of the whole run.
                def step_fn(state_, batch_, rng_):
                    win.before_step()
                    out = step(state_, batch_, rng_)
                    win.after_step(out)
                    return out

            common = dict(
                num_steps=cfg.num_steps,
                rng=make_rng(args.seed, args.rng_impl),
                log_every=cfg.log_every,
                hooks=hooks,
                checkpointer=ckpt,
                ckpt_every=cfg.ckpt_every or args.ckpt_every,
                evaluate=evaluate,
                eval_every=args.eval_every,
                feed_metrics=feed_metrics,
                tracer=tracer,
                timeline=timeline,
                recorder=recorder,
                nonfinite=getattr(args, "nonfinite", "abort") or "abort",
            )
            if resilient:
                # Preemption-safe supervision (train/resilience.py):
                # SIGTERM/SIGINT -> final sync checkpoint + clean exit;
                # transient feeder/ckpt-IO failures restore from the last
                # checkpoint and re-enter the loop with backoff.
                from distributed_tensorflow_tpu.train.resilience import (
                    ResilienceConfig,
                    run_resilient,
                )

                def make_batches(start_step: int):
                    return prefetch(
                        pieces["batches"](start_step),
                        cfg.prefetch,
                        metrics=feed_metrics,
                        fault_injector=fault_injector,
                    )

                report = run_resilient(
                    state,
                    step_fn,
                    make_batches,
                    config=ResilienceConfig(
                        max_restarts=getattr(args, "max_restarts", 3)
                    ),
                    fault_injector=fault_injector,
                    **common,
                )
                state, last = report.state, report.metrics
                if report.preempted:
                    logging.info(
                        "preempted at step %d after %d restart(s); "
                        "checkpoint is durable",
                        report.final_step, report.restarts,
                    )
            else:
                state, last = fit(
                    state,
                    step_fn,
                    batches,
                    fault_injector=fault_injector,
                    **common,
                )
        if ckpt is not None and ckpt.latest_step() != int(state.step):
            ckpt.save(int(state.step), state, force=True)
    except Exception as e:
        if recorder is not None:
            recorder.record("engine_failure", error=type(e).__name__)
            recorder.dump("train_failure", force=True)
        raise
    finally:
        if ckpt is not None:
            ckpt.close()
        for w in getattr(hook, "writers", ()):
            w.close()
        close = getattr(batches, "close", None)
        if close is not None:
            close()
        if timeline is not None:
            beacon.write()  # final state, even for runs shorter than log_every
        if tracer is not None and jax.process_index() == 0:
            out = tracer.export(Path(trace_dir) / "train_trace.json")
            logging.info("wrote host span trace to %s", out)
    return state, last


def main(argv: list[str] | None = None):
    parser = argparse.ArgumentParser(
        description="TPU-native distributed training (single SPMD entrypoint)"
    )
    parser.add_argument("--config", required=True, choices=sorted(PRESETS))
    parser.add_argument("--steps", type=int, default=0, help="override num_steps")
    parser.add_argument("--global-batch", type=int, default=0)
    parser.add_argument("--image-size", type=int, default=0)
    parser.add_argument("--seq-parallel", type=int, default=-1,
                        help="seq axis size for sequence parallelism (BERT)")
    parser.add_argument("--sp-impl", default="", choices=["", "ring", "ulysses"],
                        help="sequence-parallel strategy: ring (K/V streamed "
                        "over ICI) or ulysses (all-to-all head re-partition)")
    parser.add_argument("--tensor-parallel", type=int, default=-1,
                        help="model axis size for Megatron-TP sharding (BERT)")
    parser.add_argument("--moe-experts", type=int, default=-1,
                        help="switch-MoE FFN with N experts (BERT; 0 = dense FFN)")
    parser.add_argument("--moe-dispatch", default="",
                        choices=["", "replicated", "alltoall", "sharded"],
                        help="MoE dispatch layout: alltoall = capacity-buffer "
                        "exchange over replicated tokens; sharded = the "
                        "production GShard layout (batch sharded over the "
                        "expert axis, zero replicated non-MoE compute)")
    parser.add_argument("--moe-topk", type=int, default=-1,
                        help="routing fan-out: 1 = Switch top-1 (default), "
                        "2 = GShard top-2 (renormalized gates, per-expert "
                        "capacity unchanged)")
    parser.add_argument("--pipeline-parallel", type=int, default=-1,
                        help="pipeline-stage axis size for the BERT encoder "
                        "(GPipe schedule; 0 disables)")
    parser.add_argument("--pipeline-microbatches", type=int, default=0,
                        help="GPipe microbatch count M (default 4x stages)")
    parser.add_argument("--grad-accum", type=int, default=0,
                        help="accumulate gradients over N micro-slices of "
                        "each device's batch inside the compiled step "
                        "(mean of per-slice grads) — train global batches "
                        "whose activations don't fit; composes with --remat")
    parser.add_argument("--remat", action="store_true",
                        help="rematerialise encoder-layer activations during "
                        "backward (jax.checkpoint): ~1 extra fwd pass of "
                        "layer FLOPs for O(num_layers) less activation "
                        "memory — enables longer --seq-len / larger batch "
                        "per chip (BERT)")
    parser.add_argument("--expert-parallel", type=int, default=-1,
                        help="expert axis size for MoE sharding (BERT)")
    parser.add_argument("--bert-layers", type=int, default=0,
                        help="override BERT encoder depth (smoke runs)")
    parser.add_argument("--bert-hidden", type=int, default=0,
                        help="override BERT hidden size (intermediate = 4x)")
    parser.add_argument("--bert-vocab", type=int, default=0,
                        help="override BERT vocab size (smoke runs)")
    parser.add_argument("--staleness", type=int, default=-1)
    parser.add_argument("--lr", type=float, default=0.0)
    parser.add_argument("--lr-schedule", default="",
                        choices=["", "constant", "warmup_cosine", "piecewise"])
    parser.add_argument("--log-every", type=int, default=0)
    parser.add_argument("--data-dir", default="",
                        help="directory with real dataset files (synthetic fallback)")
    parser.add_argument("--no-native-input", action="store_true",
                        help="force the numpy input path (skip the C++ pipeline)")
    parser.add_argument("--device-pool", type=int, default=0,
                        help="pre-place N batches in HBM and cycle them "
                        "(device-rate runs on feed-bound hosts; revisits "
                        "the pool every N steps)")
    parser.add_argument("--prefetch", type=int, default=-1,
                        help="feed lookahead depth: a feeder thread runs "
                        "batch assembly + host->device transfer N batches "
                        "ahead of the step stream (default 2; 0 = "
                        "synchronous feed). Batch streams are bit-identical "
                        "for any N")
    parser.add_argument("--coordinator-address", default="",
                        help="multi-host bootstrap: coordinator ip:port for "
                        "jax.distributed.initialize (TPU pods auto-detect; "
                        "required for CPU/GPU clusters / manual launch)")
    parser.add_argument("--num-processes", type=int, default=0,
                        help="multi-host bootstrap: total process count "
                        "(with --coordinator-address)")
    parser.add_argument("--process-id", type=int, default=-1,
                        help="multi-host bootstrap: this process's rank in "
                        "[0, --num-processes)")
    parser.add_argument("--eval-every", type=int, default=0,
                        help="run held-out eval every N steps (0 = off)")
    parser.add_argument("--eval-batches", type=int, default=8,
                        help="number of global batches per eval pass")
    parser.add_argument("--ckpt-dir", default="")
    parser.add_argument("--ckpt-every", type=int, default=0)
    parser.add_argument("--tb-dir", default="")
    parser.add_argument("--metrics-jsonl", default="")
    parser.add_argument("--beacon-dir", default="",
                        help="shared directory for per-host health beacons "
                        "(host_<i>.json, atomically replaced at the log "
                        "cadence): step-time/host-wait windows + straggler "
                        "anomalies, aggregated by obs.fleet.fleet_summary")
    parser.add_argument("--profile-dir", default="",
                        help="capture an xprof trace of the whole run to this dir")
    parser.add_argument("--profile-steps", type=int, default=0,
                        help="arm the --profile-dir window for exactly N "
                        "dispatched steps (starts at the first step, stops "
                        "after the Nth; 0 = trace the whole run)")
    parser.add_argument("--trace-dir", default="",
                        help="record host-side step-phase spans (host_wait/"
                        "dispatch/device/metrics_fetch/checkpoint) and "
                        "write them here as Chrome trace-event JSON "
                        "(Perfetto / chrome://tracing)")
    parser.add_argument("--trace-buffer", type=int, default=4096,
                        help="span ring-buffer size for --trace-dir (the "
                        "export holds the most recent N spans)")
    parser.add_argument("--dump-dir", default="",
                        help="flight-recorder dump directory: an unhandled "
                        "training failure writes one timestamped JSON with "
                        "the event ring + memory/tracer digests (see "
                        "OBS.md \"Flight recorder\"; empty = disabled)")
    parser.add_argument("--resilient", action="store_true",
                        help="preemption-safe supervised training "
                        "(train/resilience.py): SIGTERM/SIGINT triggers a "
                        "final synchronous checkpoint + clean exit; "
                        "transient feeder/checkpoint-IO failures restore "
                        "from the last checkpoint and retry with capped "
                        "exponential backoff; non-finite loss and shape "
                        "errors stay fatal (with a flight-recorder dump "
                        "when --dump-dir is set)")
    parser.add_argument("--max-restarts", type=int, default=3,
                        help="consecutive no-progress restart budget for "
                        "--resilient (a restart that resumes from a newer "
                        "checkpoint resets the count)")
    parser.add_argument("--fault-plan", default="",
                        help="deterministic fault injection "
                        "(train/faultinject.py): either a seeded spec like "
                        "'seed=7,feeder_error=2,ckpt_write_error=1,"
                        "slow_step=1,slow_step_s=0.1' or a path to a JSON "
                        "plan; scheduled events fire in the train loop, "
                        "the feed stage, and the checkpointer, and are "
                        "recorded to the flight recorder and host beacon")
    parser.add_argument("--nonfinite", default="abort",
                        choices=["abort", "skip"],
                        help="NaN/Inf step-loss policy, checked at the log "
                        "cadence: abort (default) raises NonFiniteLossError "
                        "(+ flight-recorder event and forced dump with "
                        "--dump-dir); skip records the event and trains on")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--rng-impl",
        default="auto",
        choices=["auto", "threefry", "rbg"],
        help="PRNG for the per-step rng (dropout etc.). auto = rbg on TPU "
        "(counter-based hardware generator — measured 15%% faster BERT-base "
        "steps than threefry at L=512, docs/PERF.md r5; the semantics class "
        "of the reference's Philox dropout), threefry elsewhere (bit-stable "
        "across versions/backends).",
    )
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s: %(message)s"
    )
    cfg = PRESETS[args.config]
    overrides = {}
    if args.steps:
        overrides["num_steps"] = args.steps
    if args.global_batch:
        overrides["global_batch"] = args.global_batch
    if args.image_size:
        overrides["image_size"] = args.image_size
    if args.seq_parallel >= 0:
        overrides["seq_parallel"] = args.seq_parallel
    if args.sp_impl:
        overrides["sp_impl"] = args.sp_impl
    if args.tensor_parallel >= 0:
        overrides["tensor_parallel"] = args.tensor_parallel
    if args.moe_experts >= 0:
        overrides["moe_experts"] = args.moe_experts
    if args.moe_dispatch:
        overrides["moe_dispatch"] = args.moe_dispatch
    if args.moe_topk == 0:
        raise SystemExit("--moe-topk must be >= 1")
    if args.moe_topk > 0:
        overrides["moe_topk"] = args.moe_topk
    if args.expert_parallel >= 0:
        overrides["expert_parallel"] = args.expert_parallel
    if args.pipeline_parallel >= 0:
        overrides["pipeline_parallel"] = args.pipeline_parallel
    if args.pipeline_microbatches:
        overrides["pipeline_microbatches"] = args.pipeline_microbatches
    if args.remat:
        overrides["remat"] = True
    if args.grad_accum:
        if args.grad_accum < 1:
            raise SystemExit("--grad-accum must be >= 1")
        overrides["grad_accum"] = args.grad_accum
    if args.bert_layers:
        overrides["bert_layers"] = args.bert_layers
    if args.bert_hidden:
        overrides["bert_hidden"] = args.bert_hidden
    if args.bert_vocab:
        overrides["bert_vocab"] = args.bert_vocab
    if args.staleness >= 0:
        overrides["staleness"] = args.staleness
        if args.staleness:
            overrides["mode"] = "stale"
    if args.lr:
        overrides["learning_rate"] = args.lr
    if args.lr_schedule:
        overrides["lr_schedule"] = args.lr_schedule
    if args.log_every:
        overrides["log_every"] = args.log_every
    if args.data_dir:
        overrides["data_dir"] = args.data_dir
    if args.no_native_input:
        overrides["native_input"] = False
    if args.device_pool:
        overrides["device_pool"] = args.device_pool
    if args.prefetch >= 0:
        overrides["prefetch"] = args.prefetch
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    state, last = run(cfg, args)
    if jax.process_index() == 0 and last is not None:
        logging.info("final: %s", last)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
