"""``python -m distributed_tensorflow_tpu.cli.serve --config=<workload>``.

Serve a trained checkpoint behind the dynamic micro-batcher: rebuild the
workload's model exactly as training did (same preset + overrides), restore
the newest checkpoint from ``--ckpt-dir`` directly onto the serving mesh,
AOT-compile the forward per sequence bucket / image geometry, and expose it
over HTTP (serve/server.py routes).

The serving mesh defaults to DP-only (one chip per replica). ``--tp`` /
``--pp`` / ``--ep`` (or an explicit ``--mesh data=2,model=4``) shard each
BERT engine across that many chips — Megatron tensor parallelism,
GPipe pipeline stages, expert-parallel MoE — with the remainder going to
data parallelism. The restore template carries the target layout's
shardings, so the checkpoint reads straight into place with no
single-device staging. A mesh that doesn't fit the available devices
degrades to single-chip DP with a warning, never an XLA shape error.

The config flags MUST match the training run's — the checkpoint template is
rebuilt from them (same optimizer, same staleness; for pipeline/MoE runs
also ``--pp`` / ``--moe-experts`` / ``--moe-topk``), and a mismatched tree
fails loudly at restore rather than serving garbage.

``--selftest N`` runs N synthetic requests through the in-process
:class:`Client` instead of binding a port (CI smoke; also a quick "does
this checkpoint answer" check) and prints the metrics snapshot.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging

import numpy as np

logger = logging.getLogger(__name__)


def _resolve_mesh_spec(args, n_devices: int):
    """Serving mesh spec from ``--mesh`` / ``--tp/--pp/--ep`` -> (spec,
    fell_back). Requests that cannot fit ``n_devices`` degrade to
    single-chip DP with a warning — never an XLA shape error at startup."""
    from distributed_tensorflow_tpu.parallel.mesh import MeshSpec
    from distributed_tensorflow_tpu.serve.engine import plan_serve_mesh

    if args.mesh:
        try:
            spec = {}
            for part in args.mesh.split(","):
                name, _, size = part.partition("=")
                spec[name.strip()] = int(size)
            MeshSpec(spec).resolve(n_devices)  # loud fit check, result unused
            return spec, False
        except ValueError as e:
            logger.warning(
                "--mesh %r does not fit the %d available devices (%s); "
                "falling back to single-chip data-parallel serving",
                args.mesh, n_devices, e,
            )
            return {"data": -1}, True
    return plan_serve_mesh(
        tp=args.tp, pp=args.pp, ep=args.ep, n_devices=n_devices
    )


def build_serving_client(cfg, args):
    """Workload config -> (Client, payload_maker) over the restored ckpt."""
    import jax

    from distributed_tensorflow_tpu.ckpt import restore_serving_state
    from distributed_tensorflow_tpu.cli.train import _make_tx
    from distributed_tensorflow_tpu.obs.flightrec import FlightRecorder
    from distributed_tensorflow_tpu.obs.slo import SloSpec
    from distributed_tensorflow_tpu.parallel.mesh import (
        build_mesh,
        data_axes,
        initialize_runtime,
    )
    from distributed_tensorflow_tpu.obs.trace import Tracer
    from distributed_tensorflow_tpu.serve import (
        BatcherConfig,
        BertInferenceEngine,
        CausalLMEngine,
        Client,
        ImageClassifierEngine,
    )
    from distributed_tensorflow_tpu.train import create_train_state
    from distributed_tensorflow_tpu.train.step import (
        make_state_specs,
        place_state,
    )

    initialize_runtime()
    # Serving mesh: DP-only by default; --mesh/--tp/--pp/--ep add model
    # axes (BERT engines shard over them; see serve/engine.py). The
    # builders hand back the axis-free model either way — the engine binds
    # the axes itself — plus param_specs when the layout shards params.
    spec, _ = _resolve_mesh_spec(args, len(jax.devices()))
    mesh = build_mesh(spec)
    pieces = cfg.build(cfg)(mesh)
    if "image_shape" in pieces and set(mesh.axis_names) - set(data_axes(mesh)):
        # Model parallelism is a BERT feature: an image config on a mesh
        # with model axes would just compute redundantly across them —
        # rebuild DP-only instead of silently wasting the chips.
        logger.warning(
            "--tp/--pp/--ep apply to BERT configs only; serving %s "
            "data-parallel", cfg.name,
        )
        mesh = build_mesh({"data": -1})
        pieces = cfg.build(cfg)(mesh)

    # The restore template: a TrainState built exactly like training's
    # (same tx -> same opt_state slots, same staleness -> same grad ring),
    # placed in the TARGET serving layout — param_specs present means the
    # mesh shards params, and tensorstore then restores every shard
    # directly into place (no single-device staging round-trip).
    tx, _ = _make_tx(cfg)
    host_state = create_train_state(
        pieces["params"],
        tx,
        pieces["model_state"],
        staleness=cfg.staleness if cfg.mode == "stale" else 0,
    )
    state_specs = None
    if pieces.get("param_specs") is not None:
        state_specs = make_state_specs(host_state, tx, pieces["param_specs"])
    template = place_state(host_state, mesh, state_specs)
    # The flight recorder exists BEFORE restore so the ckpt_restore event
    # (step, reclaimed bytes) is the first entry in any later dump.
    fbuf = getattr(args, "flight_buffer", 2048)
    recorder = FlightRecorder(
        capacity=fbuf,
        enabled=fbuf > 0,
        dump_dir=getattr(args, "dump_dir", "") or None,
    )
    weight_dtype = getattr(args, "weight_dtype", "") or None
    kv_dtype = getattr(args, "kv_dtype", "") or None
    if weight_dtype is not None and "image_shape" in pieces:
        raise ValueError(
            "--weight-dtype is not supported for image serving (the "
            "classifier forward has no dequantize step)"
        )
    if kv_dtype is not None and not pieces.get("decode"):
        raise ValueError(
            "--kv-dtype only applies to causal-LM decode serving "
            "(nothing else owns a KV cache)"
        )
    params, model_state, step = restore_serving_state(
        args.ckpt_dir, template, recorder=recorder,
        weight_dtype=weight_dtype,
    )
    logger.info(
        "restored %s step %d for serving (mesh %s)",
        cfg.name, step, dict(mesh.shape),
    )

    if "image_shape" in pieces:
        shape = pieces["image_shape"]
        engine = ImageClassifierEngine(
            pieces["model"],
            params,
            model_state,
            mesh,
            image_shape=shape,
            max_batch=args.max_batch,
            batch_tiers=tuple(args.batch_tiers),
            top_k=args.top_k,
        )

        def make_payload(rng: np.random.Generator) -> dict:
            return {"image": rng.standard_normal(shape).astype(np.float32)}

    elif pieces.get("decode"):
        engine = CausalLMEngine(
            pieces["model"],
            params,
            mesh,
            buckets=tuple(args.buckets),
            slots=args.slots,
            max_batch=args.max_batch,
            batch_tiers=tuple(args.batch_tiers),
            max_new_tokens=args.max_new_tokens,
            prefix_cache_mb=args.prefix_cache_mb,
            block_tokens=args.block_tokens,
            prefill_chunk=args.prefill_chunk,
            spec_tokens=args.spec_tokens,
            spec_min_match=args.spec_min_match,
            spec_backoff=args.spec_backoff,
            # Disaggregated-serving roles move KV-page chains between
            # engines; the export/import executables are compiled at
            # startup like the rest of the grid.
            kv_transfer=bool(getattr(args, "disagg_role", "")),
            # Live stream migration compiles the slot-page export/import
            # pair so in-flight generations can checkpoint off their
            # slots and resume on a peer (see DEPLOY.md "Migrating live
            # streams").
            stream_migrate=bool(getattr(args, "stream_migrate", False)),
            # restore_serving_state already quantized/cast the params;
            # the ctor detects the quantized tree and plans the KV
            # storage dtype (see DEPLOY.md "Quantized serving").
            weight_dtype=weight_dtype,
            kv_dtype=kv_dtype,
        )
        vocab = pieces["model"].cfg.vocab_size

        def make_payload(rng: np.random.Generator) -> dict:
            l = int(rng.integers(4, engine.buckets[-1] + 1))
            return {
                "input_ids": rng.integers(5, vocab, size=l),
                "max_new_tokens": int(
                    rng.integers(1, args.max_new_tokens + 1)
                ),
            }

    else:
        engine = BertInferenceEngine(
            pieces["model"],
            params,
            mesh,
            buckets=tuple(args.buckets),
            max_batch=args.max_batch,
            batch_tiers=tuple(args.batch_tiers),
            weight_dtype=weight_dtype,
        )
        vocab = pieces["model"].cfg.vocab_size

        def make_payload(rng: np.random.Generator) -> dict:
            l = int(rng.integers(4, engine.buckets[-1] + 1))
            ids = rng.integers(5, vocab, size=l)
            return {"input_ids": ids, "mlm_targets": ids}

    # Span tracing is always-on-capable: --trace-buffer 0 turns it into
    # branch-cheap no-ops at every call site.
    buf = getattr(args, "trace_buffer", 4096)
    # Declared SLOs drive /sloz burn rates and the /healthz degraded
    # overlay; the Client inserts the latency threshold as an explicit
    # histogram bound so windowed attainment at it is exact.
    slo = SloSpec(
        latency_threshold_ms=getattr(args, "slo_p99_ms", 0.0),
        latency_target=getattr(args, "slo_target", 0.99),
        availability_target=getattr(args, "slo_availability", 0.0),
    )
    client = Client(
        engine,
        BatcherConfig(
            max_batch=args.max_batch,
            max_delay_ms=args.max_delay_ms,
            max_queue=args.max_queue,
            max_in_flight=args.max_in_flight,
            bucket_queues=args.bucket_queues,
            sched=getattr(args, "sched", "fifo"),
            preempt=getattr(args, "preempt", False),
            preempt_margin_ms=getattr(args, "preempt_margin_ms", 20.0),
            default_priority=getattr(args, "default_priority", 1),
        ),
        tracer=Tracer(buffer_size=buf, enabled=buf > 0),
        slo=slo,
        admission="flush" if getattr(args, "flush_admission", False)
        else "continuous",
        recorder=recorder,
        warmup_ready_fraction=getattr(args, "warmup_ready_fraction", 1.0),
        # Deployment identity for the router's hot-swap verification:
        # defaults to the restored step so a rolled checkpoint is visible
        # on /healthz without any operator input.
        tag=getattr(args, "tag", None) or f"ckpt-{step}",
    )
    return client, make_payload


def _selftest(client, make_payload, n: int) -> int:
    rng = np.random.default_rng(0)
    futures = [client.submit(make_payload(rng)) for _ in range(n)]
    results = [f.result(timeout=120) for f in futures]
    assert len(results) == n
    snap = client.metrics.snapshot()
    print(json.dumps(snap, indent=2, default=float))
    logger.info("selftest ok: %d requests served", n)
    return 0


def main(argv: list[str] | None = None):
    from distributed_tensorflow_tpu.cli.train import PRESETS

    parser = argparse.ArgumentParser(
        description="serve a trained checkpoint (dynamic-batching inference)"
    )
    parser.add_argument("--config", required=True, choices=sorted(PRESETS))
    parser.add_argument("--ckpt-dir", required=True,
                        help="training checkpoint directory (newest step served)")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--tag", default=None,
                        help="deployment tag surfaced on /healthz (default "
                             "ckpt-<restored step>); the router's hot-swap "
                             "drill asserts it after a rolling restart")
    parser.add_argument("--port", type=int, default=8000,
                        help="0 = ephemeral (logged at startup)")
    parser.add_argument("--buckets", type=int, nargs="+",
                        default=[128, 256, 512],
                        help="sequence-length buckets (clamped to the "
                        "model's max_position); one executable each")
    parser.add_argument("--max-batch", type=int, default=8,
                        help="largest executable batch size / flush size")
    parser.add_argument("--batch-tiers", type=int, nargs="+",
                        default=[1, 2, 4, 8],
                        help="batch-size tiers to AOT-compile (clamped to "
                        "--max-batch); a partial flush runs the smallest "
                        "tier that fits instead of padding to max-batch")
    parser.add_argument("--max-in-flight", type=int, default=2,
                        help="batches dispatched but not yet fetched; >1 "
                        "overlaps host assembly with device compute")
    parser.add_argument("--bucket-queues", action="store_true",
                        help="queue per sequence bucket so short requests "
                        "flush together instead of padding to a long "
                        "batchmate's bucket")
    parser.add_argument("--max-delay-ms", type=float, default=8.0,
                        help="flush a partial batch after this wait")
    parser.add_argument("--max-queue", type=int, default=64,
                        help="queue bound; beyond -> 429 + Retry-After")
    parser.add_argument("--top-k", type=int, default=5,
                        help="classes returned per classify request")
    # Decode engine (causal-LM presets; see DEPLOY.md "Continuous-batching
    # decode"). Requests admit into KV-cache slots mid-flight between
    # decode steps unless --flush-admission pins static batching.
    parser.add_argument("--slots", type=int, default=8,
                        help="KV-cache slots = max concurrently decoding "
                        "sequences (one fixed decode executable at this "
                        "width)")
    parser.add_argument("--max-new-tokens", type=int, default=32,
                        help="generation cap per request (requests may ask "
                        "for less; also sizes the per-slot cache pages)")
    parser.add_argument("--prefix-cache-mb", type=float, default=0.0,
                        help="device bytes (MiB) for the prefix-cache KV "
                        "page pool; shared prompt heads prefill once and "
                        "admissions reuse the cached pages (0 disables; "
                        "see DEPLOY.md \"Prefix-cache KV reuse\")")
    parser.add_argument("--block-tokens", type=int, default=16,
                        help="tokens per prefix-cache page; prompts share "
                        "whole pages only, so smaller blocks match more "
                        "but index/gather more")
    parser.add_argument("--prefill-chunk", type=int, default=0,
                        help="prefill prompts in chunks of at most this "
                        "many tokens, interleaved with decode steps so "
                        "long-prompt admission bounds in-flight requests' "
                        "inter-token latency (0 = monolithic prefill "
                        "unless --prefix-cache-mb is set)")
    parser.add_argument("--spec-tokens", type=int, default=0,
                        help="speculative-decoding draft length k: verify "
                        "up to k n-gram-drafted tokens per slot in one "
                        "[slots, k+1] forward, emitting the accepted run "
                        "as multiple tokens per step (0 disables; output "
                        "is bit-identical either way — see DEPLOY.md "
                        "\"Speculative decoding\")")
    parser.add_argument("--spec-min-match", type=int, default=2,
                        help="shortest history n-gram the drafter may "
                        "match; longer = fewer but better drafts")
    parser.add_argument("--spec-backoff", type=float, default=0.25,
                        help="per-slot acceptance-EMA threshold below "
                        "which speculation backs off to plain decode "
                        "(re-probing periodically)")
    # Quantized serving (see DEPLOY.md "Quantized serving"): checkpoints
    # stay fp32 on disk; --weight-dtype int8 quantizes kernels at
    # restore (per-output-channel absmax, dequantized inside the
    # matmul), --kv-dtype int8 stores KV pages as int8 + per-position
    # scales (~3.5x more decode slots per HBM byte).
    parser.add_argument("--weight-dtype", default="",
                        choices=["", "float32", "bfloat16", "int8"],
                        help="serving dtype for restored params: int8 = "
                        "per-channel quantize at restore (fp32 kernel "
                        "HBM reclaimed, logged by the restore); empty "
                        "keeps the config dtype")
    parser.add_argument("--kv-dtype", default="",
                        choices=["", "float32", "bfloat16", "int8"],
                        help="KV-cache storage dtype (causal-LM decode "
                        "only): int8 pages carry per-position scales "
                        "through prefill, decode, the prefix cache, and "
                        "the KV wire format; empty keeps the config "
                        "dtype")
    # Disaggregated prefill/decode serving (see DEPLOY.md "Disaggregated
    # serving"): run this process as ONE role of a prefill/decode pair.
    # A decode-role server compiles the KV-page import executable and
    # accepts chains on POST /v1/kv_transfer (serve/disagg.py wire
    # format); a prefill-role server is an ordinary chunked-prefill
    # engine whose operators cap max_new_tokens at 1 and ship the
    # published pages with serve.disagg.post_kv_transfer.
    parser.add_argument("--disagg-role", default="",
                        choices=["", "prefill", "decode"],
                        help="disaggregated-serving role; decode requires "
                        "--prefix-cache-mb > 0 (the adopted chains land "
                        "in the prefix-cache page pool)")
    parser.add_argument("--kv-transfer-budget-mb", type=float, default=64.0,
                        help="bytes-in-flight cap (MiB) for inbound KV-page "
                        "transfers on a decode-role server; transfers "
                        "beyond it queue briefly then shed with 429 + "
                        "Retry-After (the sender re-prefills instead)")
    # Live decode-stream migration (see DEPLOY.md "Migrating live
    # streams"): compile the slot-page export/import executables, accept
    # migrated streams on POST /v1/stream_migrate, and export every live
    # stream to survivors on POST /migratez (the router drives both
    # during hot_swap deadline expiry and failover).
    parser.add_argument("--stream-migrate", action="store_true",
                        help="enable live decode-stream migration: mount "
                        "POST /v1/stream_migrate + /v1/stream_wait "
                        "(receive side) and POST /migratez (export side); "
                        "causal-LM engines only")
    parser.add_argument("--fault-plan", default="",
                        help="serving-side fault-injection plan (drills): "
                        "'seed=..,dispatch_error=N,slow_decode_step=N,"
                        "wire_corrupt=N,probe_timeout=N,replica_kill=N' or "
                        "a FaultPlan JSON path; injected into the decode "
                        "loop and migration wire path "
                        "(serve/faultinject.py)")
    parser.add_argument("--fault-steps", type=int, default=1000,
                        help="decode-step horizon --fault-plan events are "
                        "placed within when the spec is key=value form")
    parser.add_argument("--flush-admission", action="store_true",
                        help="admit new requests only when the slot table "
                        "is EMPTY (static batching; the A/B baseline for "
                        "continuous admission)")
    # Priority-preemptive scheduling (see DEPLOY.md "Priority &
    # preemption"): requests may carry "priority" (class 0 = most urgent)
    # and "deadline_ms" (TTFT deadline relative to enqueue) on
    # /v1/generate; EDF admission orders the queue by them, and --preempt
    # parks a lower-priority slot (KV lanes into prefix-pool pages,
    # resume via resume_tokens replay) when a deadline would be missed.
    parser.add_argument("--sched", default="fifo",
                        choices=["fifo", "edf"],
                        help="admission order: fifo (arrival) or edf "
                        "(earliest deadline first within priority class)")
    parser.add_argument("--preempt", action="store_true",
                        help="preempt a lower-priority decode slot when a "
                        "queued deadline holder would otherwise miss its "
                        "deadline (requires --sched edf; preempted "
                        "streams resume bit-identically)")
    parser.add_argument("--preempt-margin-ms", type=float, default=20.0,
                        help="preempt when now + margin crosses a queued "
                        "request's deadline — headroom for the park + "
                        "re-prefill round trip")
    parser.add_argument("--default-priority", type=int, default=1,
                        help="priority class for requests that don't send "
                        "one (0 = most urgent; keep the default above 0 "
                        "so explicit high-priority traffic can outrank "
                        "the unlabelled crowd)")
    # Multi-chip serving mesh (BERT engines; see DEPLOY.md "Multi-chip
    # serving"). A layout that doesn't fit the device count falls back to
    # single-chip DP with a warning.
    parser.add_argument("--mesh", default="",
                        help="explicit serving mesh, e.g. 'data=2,model=4' "
                        "(axes from parallel.mesh.AXIS_ORDER; one axis may "
                        "be -1). Overrides --tp/--pp/--ep")
    parser.add_argument("--tp", type=int, default=1,
                        help="tensor-parallel (Megatron) chips per engine; "
                        "must divide num_heads and intermediate_size")
    parser.add_argument("--pp", type=int, default=1,
                        help="pipeline-parallel stages per engine; the "
                        "checkpoint must be a --pipeline-parallel=N run "
                        "(stacked encoder)")
    parser.add_argument("--ep", type=int, default=1,
                        help="expert-parallel chips per engine; needs a "
                        "--moe-experts checkpoint divisible by it")
    parser.add_argument("--moe-experts", type=int, default=0,
                        help="training run's --moe-experts (MoE ckpts)")
    parser.add_argument("--moe-topk", type=int, default=1,
                        help="training run's --moe-topk")
    parser.add_argument("--global-batch", type=int, default=0,
                        help="training run's --global-batch (only needed "
                        "when the preset default doesn't match, e.g. "
                        "pipeline runs validating microbatch divisibility)")
    # Model-geometry overrides — MUST match the training run's.
    parser.add_argument("--bert-layers", type=int, default=0)
    parser.add_argument("--bert-hidden", type=int, default=0)
    parser.add_argument("--bert-vocab", type=int, default=0)
    parser.add_argument("--image-size", type=int, default=0)
    parser.add_argument("--staleness", type=int, default=-1,
                        help="training run's staleness (stale-mode ckpts)")
    # Declared SLOs (0 disables a dimension): /sloz reports attainment +
    # error-budget burn; a paging-level burn turns /healthz "degraded".
    parser.add_argument("--slo-p99-ms", type=float, default=0.0,
                        help="latency SLO threshold in ms: --slo-target of "
                        "requests must complete within it (0 = no latency "
                        "SLO)")
    parser.add_argument("--slo-target", type=float, default=0.99,
                        help="target fraction for the latency SLO "
                        "(e.g. 0.99 = p99 under --slo-p99-ms)")
    parser.add_argument("--slo-availability", type=float, default=0.0,
                        help="availability SLO target fraction, e.g. 0.999 "
                        "(0 = no availability SLO)")
    parser.add_argument("--trace-dir", default="",
                        help="where POST /profilez drops jax.profiler "
                        "captures; also receives a Chrome span trace at "
                        "shutdown (GET /tracez drains spans live)")
    parser.add_argument("--trace-buffer", type=int, default=4096,
                        help="span ring-buffer size (0 disables tracing: "
                        "every span call becomes a cheap no-op)")
    # Black-box flight recorder (see OBS.md "Flight recorder"): a bounded
    # ring of structured lifecycle events, dumped with a full observability
    # snapshot on engine failure / paging SLO burn / POST /debugz/dump.
    parser.add_argument("--flight-buffer", type=int, default=2048,
                        help="flight-recorder event ring size (0 disables "
                        "the recorder: every record call becomes a cheap "
                        "no-op and /debugz/dump answers 503)")
    parser.add_argument("--dump-dir", default="",
                        help="where flight-recorder dumps land as "
                        "timestamped JSON (empty: POST /debugz/dump "
                        "returns the snapshot inline; automatic triggers "
                        "have nowhere to write and are skipped)")
    parser.add_argument("--warmup-ready-fraction", type=float, default=1.0,
                        help="/healthz reports 'starting' (HTTP 503) until "
                        "this fraction of the AOT executable grid is "
                        "compiled; routers should withhold traffic until "
                        "ready (see DEPLOY.md \"Warmup-gated readiness\")")
    parser.add_argument("--selftest", type=int, default=0,
                        help="serve N synthetic requests in-process and "
                        "exit (no HTTP socket)")
    args = parser.parse_args(argv)
    if args.disagg_role == "decode" and args.prefix_cache_mb <= 0:
        parser.error("--disagg-role decode requires --prefix-cache-mb > 0 "
                     "(adopted KV-page chains land in the prefix-cache "
                     "page pool)")

    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s: %(message)s"
    )
    cfg = PRESETS[args.config]
    overrides = {}
    for k in ("bert_layers", "bert_hidden", "bert_vocab", "image_size",
              "global_batch"):
        if getattr(args, k):
            overrides[k] = getattr(args, k)
    if args.moe_experts:
        overrides["moe_experts"] = args.moe_experts
        overrides["moe_topk"] = args.moe_topk
    if args.pp > 1:
        # Stacked-encoder checkpoints need the stacked template even when
        # the mesh falls back to no pipeline axis (sequential scan).
        overrides["pipeline_parallel"] = args.pp
    if args.staleness >= 0:
        overrides["staleness"] = args.staleness
        overrides["mode"] = "stale" if args.staleness else "sync"
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)

    client, make_payload = build_serving_client(cfg, args)
    try:
        if args.selftest:
            return _selftest(client, make_payload, args.selftest)
        from distributed_tensorflow_tpu.serve import build_http_server

        kv_receiver = transfer_budget = None
        if args.disagg_role == "decode":
            from distributed_tensorflow_tpu.serve.disagg import (
                TransferBudget,
                make_kv_receiver,
            )

            transfer_budget = TransferBudget(
                int(args.kv_transfer_budget_mb * 1024 * 1024)
            )
            kv_receiver = make_kv_receiver(
                client.batcher,
                client.engine,
                budget=transfer_budget,
                metrics=client.metrics,
                recorder=client.recorder,
            )
            logger.info(
                "disaggregated decode role: accepting KV-page chains on "
                "POST /v1/kv_transfer (budget %.1f MiB in flight)",
                args.kv_transfer_budget_mb,
            )
        elif args.disagg_role == "prefill":
            logger.info(
                "disaggregated prefill role: operators should cap "
                "max_new_tokens at 1 and ship published pages with "
                "serve.disagg.post_kv_transfer"
            )
        stream_receiver = migrator = None
        if args.stream_migrate:
            if not hasattr(client.engine, "decode"):
                parser.error("--stream-migrate applies to causal-LM "
                             "(decode) presets only")
            from distributed_tensorflow_tpu.serve.disagg import (
                TransferBudget,
                make_stream_receiver,
                migrate_streams,
            )

            # Inbound stream payloads share the KV-transfer budget when a
            # disagg decode role already sized one; otherwise size a
            # dedicated pool from the same flag.
            if transfer_budget is None:
                transfer_budget = TransferBudget(
                    int(args.kv_transfer_budget_mb * 1024 * 1024)
                )
            stream_receiver = make_stream_receiver(
                client.batcher,
                client.engine,
                budget=transfer_budget,
                metrics=client.metrics,
                recorder=client.recorder,
            )

            def migrator(targets):
                return migrate_streams(
                    client.batcher,
                    client.engine,
                    targets,
                    metrics=client.metrics,
                    recorder=client.recorder,
                    fault_injector=client.batcher.fault_injector,
                )

            logger.info(
                "live stream migration enabled: POST /v1/stream_migrate "
                "(budget %.1f MiB in flight), /v1/stream_wait, /migratez",
                args.kv_transfer_budget_mb,
            )
        if args.fault_plan:
            from distributed_tensorflow_tpu.serve.faultinject import (
                FaultInjector,
                FaultPlan,
            )

            plan = FaultPlan.parse(
                args.fault_plan, num_steps=args.fault_steps
            )
            client.batcher.fault_injector = FaultInjector(
                plan, recorder=client.recorder
            )
            logger.info(
                "serving fault plan armed: %d scheduled events (seed %s)",
                len(plan.events), plan.seed,
            )
        server = build_http_server(
            client, args.host, args.port, trace_dir=args.trace_dir or None,
            kv_receiver=kv_receiver, transfer_budget=transfer_budget,
            stream_receiver=stream_receiver, migrator=migrator,
        )
        logger.info(
            "ready on http://%s:%d (POST /v1/%s; GET /healthz /sloz "
            "/statusz /memz /compilez /tracez /metrics?format=prom, "
            "POST /profilez /drainz /debugz/dump)",
            *server.server_address,
            "classify" if hasattr(client.engine, "image_shape")
            else "generate" if hasattr(client.engine, "decode")
            else "mlm",
        )
        try:
            server.serve_forever()
        except KeyboardInterrupt:
            logger.info("shutting down")
        finally:
            server.server_close()
        return 0
    finally:
        client.close()
        if args.trace_dir and client.tracer.enabled:
            from pathlib import Path

            out = client.tracer.export(Path(args.trace_dir) / "serve_trace.json")
            logger.info("wrote span trace to %s", out)


if __name__ == "__main__":
    raise SystemExit(main())
