"""CLI: the single pod-level SPMD entrypoint.

The reference needs one ``run_ps.py`` process per ps task plus one
``run_worker.py`` per worker, each with job-name/task-index/hosts flags
(SURVEY.md §1 L7, §3a-3b). Under SPMD all of that collapses
(BASELINE.json:5): every host runs the *same* command —

    python -m distributed_tensorflow_tpu.cli.train --config=<workload>

and topology comes from the slice metadata. No roles, no per-role flags.
"""

from distributed_tensorflow_tpu.cli.train import PRESETS, WorkloadConfig, main  # noqa: F401
