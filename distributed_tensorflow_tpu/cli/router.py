"""Run the serving fleet router: spawn (or adopt) N replica servers and
front them with health-driven balancing, failover, and hot-swap.

Spawn mode (the common case) launches ``n`` copies of ``cli/serve.py``
on consecutive ports, every extra flag after ``--`` passed through to
each replica verbatim::

    python -m distributed_tensorflow_tpu.cli.router \\
        --replicas 3 --replica-base-port 8001 --port 8000 \\
        -- --config bert-tiny --ckpt-dir /ckpts/run1 --slo-p99-ms 200

Adopt mode fronts servers somebody else manages (they are polled and
routed to, never restarted)::

    python -m distributed_tensorflow_tpu.cli.router \\
        --adopt http://10.0.0.1:8000 --adopt http://10.0.0.2:8000

The router's own HTTP face (``/healthz``, ``/fleetz``, ``/metrics``,
forwarded ``/v1/*``) comes from ``serve.router.build_router_server``;
the runbook with the hot-swap and chaos drills is docs/DEPLOY.md.
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys

logger = logging.getLogger(__name__)


def main(argv: list[str] | None = None):
    argv = list(sys.argv[1:] if argv is None else argv)
    # Everything after "--" is the replica server's own argv (spawn mode).
    replica_args: list[str] = []
    if "--" in argv:
        split = argv.index("--")
        argv, replica_args = argv[:split], argv[split + 1:]

    parser = argparse.ArgumentParser(
        description="fleet router over N replica serving processes"
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8000,
                        help="router listen port (0 = ephemeral)")
    parser.add_argument("--replicas", type=int, default=0,
                        help="spawn this many cli/serve.py replicas "
                             "(flags after -- pass through to each)")
    parser.add_argument("--replica-base-port", type=int, default=8001,
                        help="replica i listens on base+i")
    parser.add_argument("--adopt", action="append", default=[],
                        metavar="URL",
                        help="adopt an externally managed replica "
                             "(repeatable; polled + routed, not restarted)")
    parser.add_argument("--poll-interval", type=float, default=0.5)
    parser.add_argument("--poll-timeout", type=float, default=2.0)
    parser.add_argument("--fail-threshold", type=int, default=3)
    parser.add_argument("--max-restarts", type=int, default=3,
                        help="consecutive restarts before quarantine "
                             "(progress-aware: a replica that re-readies "
                             "resets its count)")
    parser.add_argument("--max-retries", type=int, default=2,
                        help="failover hops per request")
    parser.add_argument("--affinity-tokens", type=int, default=16,
                        help="prompt-head tokens hashed for prefix "
                             "affinity (0 disables)")
    parser.add_argument("--affinity-max-imbalance", type=float, default=8.0)
    parser.add_argument("--max-in-flight-per-replica", type=int, default=64)
    parser.add_argument("--log-dir", default="",
                        help="tee each replica's stdout/stderr to "
                             "<dir>/<name>.log")
    parser.add_argument("--flight-buffer", type=int, default=2048,
                        help="router flight-recorder ring capacity")
    parser.add_argument("--dump-dir", default="",
                        help="router flight-recorder dump directory")
    parser.add_argument("--fault-plan", default="",
                        help="router-side fault plan (chaos drills): "
                        "'seed=..,probe_timeout=N' drops the Nth health "
                        "probes as injected timeouts "
                        "(serve/faultinject.py); replica-side kinds go on "
                        "the replica's own --fault-plan after --")
    args = parser.parse_args(argv)

    if args.replicas <= 0 and not args.adopt:
        parser.error("need --replicas N (spawn) and/or --adopt URL")
    if args.replicas > 0 and not replica_args:
        parser.error("spawn mode needs replica flags after -- "
                     "(at least --config and --ckpt-dir)")

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )

    from distributed_tensorflow_tpu.obs.flightrec import FlightRecorder
    from distributed_tensorflow_tpu.serve.router import (
        Router,
        RouterConfig,
        build_router_server,
        replica_specs,
    )

    def make_cmd(name: str, port: int) -> list[str]:
        return [
            sys.executable, "-m", "distributed_tensorflow_tpu.cli.serve",
            "--host", args.host, "--port", str(port), *replica_args,
        ]

    specs = []
    if args.replicas > 0:
        specs += replica_specs(
            args.replicas, args.replica_base_port, make_cmd, host=args.host
        )
    specs += [
        (f"adopted-{i}", url, None) for i, url in enumerate(args.adopt)
    ]

    recorder = FlightRecorder(
        capacity=args.flight_buffer,
        enabled=args.flight_buffer > 0,
        dump_dir=args.dump_dir or None,
    )
    router = Router(
        specs,
        RouterConfig(
            poll_interval_s=args.poll_interval,
            poll_timeout_s=args.poll_timeout,
            fail_threshold=args.fail_threshold,
            max_restarts=args.max_restarts,
            max_retries=args.max_retries,
            affinity_tokens=args.affinity_tokens,
            affinity_max_imbalance=args.affinity_max_imbalance,
            max_in_flight_per_replica=args.max_in_flight_per_replica,
        ),
        recorder=recorder,
        log_dir=args.log_dir or None,
    )
    if args.fault_plan:
        from distributed_tensorflow_tpu.serve.faultinject import (
            FaultInjector,
            FaultPlan,
        )

        plan = FaultPlan.parse(args.fault_plan)
        router.fault_injector = FaultInjector(plan, recorder=recorder)
        logger.info("router fault plan armed: %d scheduled events",
                    len(plan.events))
    router.start()
    server = build_router_server(router, args.host, args.port)

    # SIGTERM must unwind like Ctrl-C: the default handler would kill the
    # process without running the finallys below, orphaning every owned
    # replica (found by a live kill -TERM drive).
    def _on_term(signum, frame):
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _on_term)
    try:
        try:
            server.serve_forever()
        except KeyboardInterrupt:
            logger.info("shutting down fleet")
        finally:
            server.server_close()
        return 0
    finally:
        router.close()


if __name__ == "__main__":
    raise SystemExit(main())
