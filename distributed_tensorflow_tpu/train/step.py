"""The compiled SPMD train step — sync-DP, async-stale-DP, and eval.

This one module supersedes all three data-parallel flavors of the reference
(SURVEY.md §2 parallelism inventory):

- **sync PS** (``SyncReplicasOptimizer``, SURVEY.md §3b) and **sync NCCL
  allreduce** (SURVEY.md §3d) both become ``mode="sync"``: gradients are
  ``lax.pmean``'d across the DP mesh axes inside the compiled step. The
  accumulators, chief token queue, and worker barrier are implied by the
  AllReduce; the NCCL ring becomes the ICI ring XLA lowers psum onto.
- **async PS with stale gradients** (SURVEY.md §3c) becomes
  ``mode="stale"``: a deterministic K-step delayed-gradient ring buffer.
  True PS asynchrony (races on variable state) cannot exist under SPMD —
  the emulation preserves the *statistical* property the workload stresses
  (updates computed against K-step-old information) while staying
  reproducible and testable. The divergence is documented, deliberate, and
  strictly better for debugging (SURVEY.md §7 hard-part 1).

Design notes (TPU-first):
- The step is built with ``shard_map`` over the mesh so every collective is
  explicit, then ``jit``'d with buffer donation: params/opt-state update in
  place in HBM, and XLA fuses the pmean into the backward pass.
- Loss functions should compute in bf16 where possible and return f32
  scalars; the engine does not impose a dtype policy.
- Nothing in the step depends on Python-level step count or data values —
  one trace, one executable, zero retraces across the run.
"""

from __future__ import annotations

from collections.abc import Callable
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.parallel import collectives as coll
from distributed_tensorflow_tpu.parallel.mesh import batch_pspec, data_axes
from distributed_tensorflow_tpu.train.state import TrainState

# loss_fn(params, model_state, batch, rng) -> (loss, (new_model_state, metrics))
LossFn = Callable[[Any, Any, Any, jax.Array], tuple[jax.Array, tuple[Any, dict]]]


def _spec_axes(spec) -> tuple[str, ...]:
    """Flatten a PartitionSpec's entries into the mesh axis names it uses."""
    return tuple(
        a
        for entry in (spec or ())
        if entry is not None
        for a in ((entry,) if isinstance(entry, str) else tuple(entry))
    )


def _batch_dim_axes(batch_spec) -> set[str]:
    """Mesh axes the batch's LEADING dim is sharded over, across all leaves.

    Under the GShard token-sharded MoE layout the batch rows split over the
    ``expert`` axis in addition to the DP axes (data/text.py
    ``bert_batch_specs(expert_sharded=True)``); the engine must then reduce
    metrics/model_state over that axis too — it carries data, like DP.
    """
    axes: set[str] = set()
    for s in jax.tree.leaves(
        batch_spec, is_leaf=lambda x: isinstance(x, P)
    ):
        if isinstance(s, P) and len(s) and s[0] is not None:
            entry = s[0]
            axes |= set((entry,) if isinstance(entry, str) else tuple(entry))
    return axes


def _extra_batch_axes(batch_spec, dp_axes) -> tuple[str, ...]:
    """Non-DP mesh axes carrying batch rows (data-like reductions apply).

    Shared by the train and eval steps so their notion of "data-carrying
    axis" can never diverge.
    """
    return tuple(
        a
        for a in ("pipeline", "expert", "model")
        if a in _batch_dim_axes(batch_spec) and a not in dp_axes
    )


def make_rng(seed: int, impl: str = "auto") -> jax.Array:
    """The per-step rng key under the framework's PRNG policy.

    ``"auto"`` = rbg on TPU (the counter-based hardware generator; dropout
    bit generation via software threefry measured +36 ms/step on BERT-base
    L=512 b=48 — docs/PERF.md r5 — and the reference's TF dropout used the
    same Philox family), threefry elsewhere (bit-stable across versions and
    backends). One definition shared by the CLI trainer and every benchmark
    so "the benched step is the production step" stays true by
    construction.
    """
    if impl == "auto":
        impl = "rbg" if jax.devices()[0].platform == "tpu" else "threefry2x32"
    elif impl == "threefry":
        impl = "threefry2x32"
    return jax.random.key(seed, impl=impl)


def make_train_step(
    loss_fn: LossFn,
    tx: optax.GradientTransformation,
    mesh,
    *,
    mode: str = "sync",
    staleness: int = 0,
    batch_spec: P | None = None,
    state_specs: "TrainState | None" = None,
    clip_norm: float = 0.0,
    donate: bool = True,
    grad_accum: int = 1,
):
    """Build the compiled ``train_step(state, batch, rng) -> (state, metrics)``.

    Args:
      loss_fn: ``(params, model_state, batch, rng) -> (loss, (model_state,
        metrics))``. Runs on the per-device batch shard; the engine averages
        gradients/metrics/model_state across the DP axes.
      tx: optax transformation (the inner optimizer the reference would wrap
        in SyncReplicasOptimizer, SURVEY.md §1 L4).
      mesh: the device mesh; DP axes are ``("replica", "data")`` ∩ mesh axes.
      mode: ``"sync"`` or ``"stale"`` (K-step delayed gradients).
      staleness: K for ``mode="stale"``; state must be created with the same K.
      batch_spec: PartitionSpec for batch leaves; default: leading dim over
        the DP axes (replicated along any other mesh axes).
      state_specs: a :class:`TrainState` pytree of PartitionSpecs for runs
        with sharded params (see :func:`make_state_specs`); default fully
        replicated. With a ``"model"`` (tensor-parallel), ``"pipeline"``
        (stage-sharded stack), or ``"expert"`` (MoE) mesh axis, the engine resolves the grad
        contract per leaf: axis-sharded leaves keep their local grad
        (scaled 1/t for the psum-transpose factor), replicated leaves pmean
        their partial grads across that axis — verified against unsharded
        models in tests/test_bert_tp.py and tests/test_pipeline.py.
      clip_norm: > 0 enables global-norm gradient clipping INSIDE the step.
        Clipping must live here, not in an ``optax.clip_by_global_norm``
        chained into ``tx``: inside shard_map each shard's grad leaves hold
        only the local slice of model/pipeline/expert-sharded params, so an
        optax-side "global" norm — and hence the clip scale — differs per
        shard, and replicated leaves silently desynchronize across shards.
        The engine computes the spec-aware global norm (sharded-leaf squared
        norms psum'd over their sharding axes) and applies one identical
        scale everywhere. Semantics match optax.clip_by_global_norm.
      donate: donate state buffers so params update in place in HBM.
      grad_accum: > 1 splits each device's batch rows into that many
        micro-slices and accumulates their gradients in one lax.scan
        BEFORE the DP/shard-axis reductions (which are linear, so the
        grad contract is untouched) — the standard big-global-batch lever
        when activations for the full per-device batch don't fit
        (composes with --remat). Semantics, stated: the accumulated grad
        is the MEAN of per-slice grads — exactly the full-batch grad for
        row-mean losses (pinned in tests/test_grad_accum.py), and the
        conventional mean-of-ratios for ratio-normalized losses like
        BERT's MLM (each slice normalizes by its own masked-token count).
        Dropout draws fold a per-slice rng (same distribution, different
        draws than the unsliced step); batch-norm models see per-slice
        batch statistics with EMAs averaged — the same ghost-BN semantics
        the DP axes already have (models/resnet.py).
    """
    if mode not in ("sync", "stale"):
        raise ValueError(f"mode must be 'sync' or 'stale', got {mode!r}")
    if mode == "stale" and staleness < 1:
        raise ValueError("mode='stale' requires staleness >= 1")
    if grad_accum < 1:
        raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
    dp_axes = data_axes(mesh)
    if batch_spec is None:
        batch_spec = batch_pspec(mesh)
    # Non-DP axes the batch rows are split over (the expert axis under the
    # token-sharded MoE layout) reduce metrics/model_state like DP axes; the
    # GRAD contract needs no change — the per-leaf shard-axis loop below
    # already pmeans replicated leaves over those axes and scales sharded
    # leaves 1/t.
    extra_batch_axes = _extra_batch_axes(batch_spec, dp_axes)
    metric_axes = tuple(dp_axes) + extra_batch_axes
    if state_specs is None:
        state_spec_tree = P()
        param_specs = None
    else:
        state_spec_tree = state_specs
        param_specs = state_specs.params

    def per_device_step(state: TrainState, batch, rng: jax.Array):
        if mode == "stale":
            # Trace-time state validation: XLA clamps out-of-range dynamic
            # indices silently, so a buffer/staleness mismatch would corrupt
            # training with no error. Shapes are static — check here.
            if state.grad_buffer is None:
                raise ValueError(
                    "mode='stale' needs a state built with create_train_state"
                    f"(..., staleness={staleness})"
                )
            depth = jax.tree.leaves(state.grad_buffer)[0].shape[0]
            if depth != staleness:
                raise ValueError(
                    f"state.grad_buffer depth {depth} != staleness {staleness}"
                )
        # Per-device RNG: fold in the global step and the device's coordinate
        # along every batch-sharding axis (DP axes, any non-DP row-carrying
        # axis like "expert" under the token-sharded MoE layout, and "seq"
        # under sequence parallelism) so dropout/augmentation is iid per
        # step and per shard — without the fold, shards along that axis
        # would draw the SAME dropout mask for different data.
        rng = jax.random.fold_in(rng, state.step)
        rng_axes = (
            list(dp_axes)
            + list(extra_batch_axes)
            + (["seq"] if "seq" in mesh.axis_names else [])
        )
        for ax in rng_axes:
            rng = jax.random.fold_in(rng, lax.axis_index(ax))

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
        if grad_accum > 1:
            rows = jax.tree.leaves(batch)[0].shape[0]
            if rows % grad_accum:
                raise ValueError(
                    f"per-device batch rows {rows} not divisible by "
                    f"grad_accum {grad_accum}"
                )
            micro = jax.tree.map(
                lambda x: x.reshape((grad_accum, rows // grad_accum) + x.shape[1:]),
                batch,
            )

            def accum_body(carry, mb_a):
                mb, a = mb_a
                (loss_a, (ms_a, metrics_a)), g_a = grad_fn(
                    state.params,
                    state.model_state,
                    mb,
                    jax.random.fold_in(rng, a),
                )
                g_sum, l_sum, ms_sum, m_sum = carry
                g_sum = jax.tree.map(jnp.add, g_sum, g_a)
                ms_sum = jax.tree.map(jnp.add, ms_sum, ms_a)
                m_sum = jax.tree.map(jnp.add, m_sum, dict(metrics_a))
                return (g_sum, l_sum + loss_a, ms_sum, m_sum), None

            # One probe trace sizes the carry zeros (shapes only, no FLOPs
            # at runtime — eval_shape never executes).
            shapes = jax.eval_shape(
                grad_fn,
                state.params,
                state.model_state,
                jax.tree.map(lambda x: x[0], micro),
                rng,
            )
            (_, (ms_shape, metric_shape)), g_shape = shapes
            zeros = lambda t: jax.tree.map(  # noqa: E731
                lambda s: jnp.zeros(s.shape, s.dtype), t
            )
            init = (
                zeros(g_shape),
                jnp.zeros((), jnp.float32),
                zeros(ms_shape),
                zeros(dict(metric_shape)),
            )
            (g_sum, l_sum, ms_sum, m_sum), _ = lax.scan(
                accum_body, init, (micro, jnp.arange(grad_accum))
            )
            inv = 1.0 / grad_accum

            def _slice_mean(leaf):
                # Inexact leaves average in f32 (casting 1/ga to the leaf
                # dtype would be fine for floats but ROUNDS TO ZERO for any
                # integer leaf, silently zeroing it); integer leaves — e.g.
                # a future count metric — stay as the accumulated SUM, the
                # only mean-free reduction that keeps them meaningful.
                if not jnp.issubdtype(leaf.dtype, jnp.inexact):
                    return leaf
                return (leaf.astype(jnp.float32) * inv).astype(leaf.dtype)

            grads = jax.tree.map(_slice_mean, g_sum)
            loss = l_sum * inv
            model_state = jax.tree.map(_slice_mean, ms_sum)
            metrics = jax.tree.map(_slice_mean, m_sum)
        else:
            (loss, (model_state, metrics)), grads = grad_fn(
                state.params, state.model_state, batch, rng
            )
        metrics = dict(metrics)
        metrics["loss"] = loss

        for shard_axis in ("model", "pipeline", "expert"):
            if shard_axis not in mesh.axis_names:
                continue
            # Param-sharded-axis grad contract (mirrors the seq contract
            # below, but per-leaf; applies to tensor AND pipeline
            # parallelism): forward psums over the axis (row-parallel TP
            # outputs; the pipeline's last-stage output broadcast) transpose
            # to psums (check_vma=False), so every grad path through the
            # sharded branches carries one factor of t = |axis|. Sharded
            # leaves hold their LOCAL slice's grad — scale it 1/t;
            # replicated leaves hold t x their local partial — pmean sums
            # the partials and removes the factor in one collective.
            # Verified against unsharded models in tests/test_bert_tp.py
            # and tests/test_pipeline.py.
            t = mesh.shape[shard_axis]

            def _fix(g, spec, axis=shard_axis, t=t):
                if axis in _spec_axes(spec):
                    return g / t
                return lax.pmean(g, axis)

            if param_specs is None:
                grads = jax.tree.map(
                    lambda g, axis=shard_axis: lax.pmean(g, axis), grads
                )
            else:
                grads = jax.tree.map(_fix, grads, param_specs)
        if "seq" in mesh.axis_names:
            # Sequence-parallel contract: the loss_fn must return the
            # *global* scalar on every seq shard (psum its numerator/
            # denominator over "seq" — see models/bert.py). Under shard_map
            # without replication tracking (check_vma=False), psum transposes
            # to psum, so each shard's backward already carries the global
            # cotangent and every param-grad path picks up exactly one factor
            # of the ring size — whether the path crosses a loss psum
            # (partitioned compute) or is shard-replicated (post-psum heads).
            # pmean removes that uniform factor exactly; verified against the
            # dense model in tests/test_bert.py.
            grads = coll.pmean_tree(grads, "seq")
        if dp_axes:
            # THE sync point: one fused AllReduce over ICI replaces the
            # reference's entire ps round-trip / NCCL ring (SURVEY.md §3b/3d).
            grads = coll.pmean_tree(grads, dp_axes)
        if metric_axes:
            metrics = coll.pmean_tree(metrics, metric_axes)
            if model_state:
                model_state = coll.pmean_tree(model_state, metric_axes)

        new_buffer, new_index = state.grad_buffer, state.buffer_index
        if mode == "stale":
            # Ring buffer: apply the gradient from K steps ago, store the
            # fresh one in its slot — the deterministic image of async-PS
            # staleness (SURVEY.md §3c: "updates computed against stale
            # weights"; here the staleness is exactly K instead of a race).
            idx = state.buffer_index
            apply_grads = jax.tree.map(
                lambda buf: lax.dynamic_index_in_dim(buf, idx, 0, keepdims=False),
                state.grad_buffer,
            )
            new_buffer = jax.tree.map(
                lambda buf, g: lax.dynamic_update_index_in_dim(
                    buf, g.astype(buf.dtype), idx, 0
                ),
                state.grad_buffer,
                grads,
            )
            new_index = (idx + 1) % staleness
            grads = apply_grads
            metrics["staleness"] = jnp.asarray(staleness, jnp.float32)

        shard_axes = tuple(
            a for a in ("model", "pipeline", "expert") if a in mesh.axis_names
        )
        if param_specs is not None and shard_axes:
            # Sharded leaves hold only this shard's slice: psum their
            # squared norms over the sharding axes so grad_norm is the
            # GLOBAL norm on every shard (out_specs=P() would otherwise
            # surface one shard's partial value).
            def _sq(g, spec):
                s = jnp.sum(jnp.square(g.astype(jnp.float32)))
                axes = _spec_axes(spec)
                for ax in shard_axes:
                    if ax in axes:
                        s = lax.psum(s, ax)
                return s

            total = sum(jax.tree.leaves(jax.tree.map(_sq, grads, param_specs)))
            grad_norm = jnp.sqrt(total)
        else:
            grad_norm = coll.global_norm(grads)
        if clip_norm > 0:
            # Spec-aware global-norm clipping (see the docstring): one scale,
            # identical on every shard, from the true global norm. Same
            # trust-ratio form as optax.clip_by_global_norm.
            scale = clip_norm / jnp.maximum(grad_norm, clip_norm)
            grads = jax.tree.map(lambda g: g * scale.astype(g.dtype), grads)

        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        metrics["grad_norm"] = grad_norm

        new_state = TrainState(
            step=state.step + 1,
            params=params,
            opt_state=opt_state,
            model_state=model_state,
            grad_buffer=new_buffer,
            buffer_index=new_index,
        )
        return new_state, metrics

    # State/rng replicated; batch sharded over DP axes. Outputs replicated —
    # identical on every device by construction (same reduced grads, same
    # update), which is exactly the post-allreduce invariant of SURVEY.md §3d.
    smapped = jax.shard_map(
        per_device_step,
        mesh=mesh,
        in_specs=(state_spec_tree, batch_spec, P()),
        out_specs=(state_spec_tree, P()),
        check_vma=False,
    )
    return jax.jit(smapped, donate_argnums=(0,) if donate else ())


def make_eval_step(
    metric_fn: Callable[[Any, Any, Any], dict],
    mesh,
    *,
    batch_spec: P | None = None,
    state_specs: "TrainState | None" = None,
    return_sums: bool = False,
):
    """Build ``eval_step(state, batch) -> metrics`` (metrics reduced over DP).

    ``metric_fn(params, model_state, batch) -> dict`` runs on the shard.
    Plain scalar values are pmean'd across the DP axes. A ``(num, den)``
    tuple value is reduced as a GLOBAL ratio — psum both then divide — for
    metrics whose per-shard denominators differ (e.g. MLM loss over a
    variable number of masked tokens, where an unweighted mean-of-ratios
    would over-weight sparse shards). ``state_specs`` matches the train
    step's (sharded params evaluate in their sharded layout — the
    metric_fn's model must carry the same tp/pp config). The reference had
    no eval path beyond running the train graph without the train op
    (SURVEY.md §5) — this is the deliberate do-better (SURVEY.md §4
    "Consequence for the rebuild").

    With ``return_sums=True`` every metric comes back as a ``(num, den)``
    pair of global sums instead of a ratio (scalars become
    ``(pmean(v), 1.0)``), so a multi-batch eval loop can carry numerators
    and denominators across the whole pass and divide ONCE — the same
    mean-of-ratios bias the per-shard reduction avoids would otherwise
    reappear at the batch level (variable masked-token counts per batch).
    Aggregate with :func:`aggregate_metric_sums`.
    """
    dp_axes = data_axes(mesh)
    if batch_spec is None:
        batch_spec = batch_pspec(mesh)
    # Mirror the train step: batch rows split over a non-DP axis (the
    # expert axis in the token-sharded MoE layout) reduce like DP.
    red_axes = tuple(dp_axes) + _extra_batch_axes(batch_spec, dp_axes)
    state_spec_tree = P() if state_specs is None else state_specs

    def per_device_eval(state: TrainState, batch):
        metrics = metric_fn(state.params, state.model_state, batch)
        out = {}
        for k, v in dict(metrics).items():
            if isinstance(v, tuple):
                num, den = v
                if red_axes:
                    num = lax.psum(num, red_axes)
                    den = lax.psum(den, red_axes)
                if return_sums:
                    out[k] = (num, den)
                else:
                    out[k] = num / jnp.maximum(den, 1.0)
            else:
                val = lax.pmean(v, red_axes) if red_axes else v
                out[k] = (val, jnp.float32(1.0)) if return_sums else val
        return out

    smapped = jax.shard_map(
        per_device_eval,
        mesh=mesh,
        in_specs=(state_spec_tree, batch_spec),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(smapped)


def aggregate_metric_sums(batch_metrics) -> dict:
    """Reduce an iterable of ``{k: (num, den)}`` dicts to global ratios.

    The companion of ``make_eval_step(..., return_sums=True)``: numerators
    and denominators accumulate across the whole eval pass and divide once
    at the end, so batches with more masked tokens (larger ``den``) weigh
    proportionally more — the global ratio, not a mean of per-batch ratios.
    """
    nums: dict[str, float] = {}
    dens: dict[str, float] = {}
    for metrics in batch_metrics:
        for k, (num, den) in metrics.items():
            nums[k] = nums.get(k, 0.0) + float(num)
            dens[k] = dens.get(k, 0.0) + float(den)
    return {k: nums[k] / max(dens[k], 1e-12) for k in nums}


def make_state_specs(state: TrainState, tx, param_specs) -> TrainState:
    """Build the TrainState-of-PartitionSpecs for a sharded-param run.

    ``param_specs`` is a tree matching ``state.params`` (e.g.
    ``models.bert.bert_param_specs``). Optimizer slots inherit their param's
    spec (via ``optax.tree_map_params``); the stale grad ring buffer gets
    the param spec behind its leading K dim; everything else is replicated.
    """
    import optax as _optax

    opt_specs = _optax.tree_map_params(
        tx,
        lambda _, spec: spec,
        state.opt_state,
        param_specs,
        transform_non_params=lambda _: P(),
    )
    buf_specs = None
    if state.grad_buffer is not None:
        buf_specs = jax.tree.map(lambda s: P(None, *s), param_specs)
    return TrainState(
        step=P(),
        params=param_specs,
        opt_state=opt_specs,
        model_state=jax.tree.map(lambda _: P(), state.model_state),
        grad_buffer=buf_specs,
        buffer_index=None if state.buffer_index is None else P(),
    )


def place_state(state: TrainState, mesh, state_specs: TrainState | None = None) -> TrainState:
    """Put a host-built TrainState onto the mesh.

    Replicated by default (the DP-parity layout — SURVEY.md §2 inventory);
    pass ``state_specs`` (see :func:`make_state_specs`) to shard params and
    optimizer slots over a ``model`` axis (tensor parallelism) and/or a
    ``pipeline`` axis (stage-sharded layer stacks, parallel/pipeline.py).
    """
    if state_specs is None:
        return jax.device_put(state, NamedSharding(mesh, P()))
    shardings = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        state_specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    return jax.device_put(state, shardings)
