"""Training driver loop — the replacement for MonitoredTrainingSession.

The reference's L6 (SURVEY.md §1): ``MonitoredTrainingSession`` + hooks +
``while not sess.should_stop(): sess.run(train_op)``. Here the loop is plain
Python around one compiled step; hooks become plain callables; there is no
chief (every host runs the identical loop; host-dependent work like metric
printing is gated on ``jax.process_index() == 0``).

TPU-first detail: the loop never blocks on device values except at the
logging cadence — metrics come back as device arrays and are only fetched
every ``log_every`` steps, keeping the step stream fully async.
"""

from __future__ import annotations

import logging
import time
from collections.abc import Callable, Iterable, Iterator
from typing import Any

import jax

logger = logging.getLogger(__name__)

# hook(step: int, state, metrics: dict[str, float]) -> None, called at log cadence
Hook = Callable[[int, Any, dict], None]


def fit(
    state,
    train_step,
    data: Iterable,
    *,
    num_steps: int,
    rng: jax.Array | None = None,
    log_every: int = 100,
    hooks: tuple[Hook, ...] = (),
    checkpointer=None,
    ckpt_every: int = 0,
    evaluate: Callable[[Any], dict] | None = None,
    eval_every: int = 0,
):
    """Run the training loop; returns the final state.

    ``data`` yields already-placed global batches (see ``data`` package).
    ``checkpointer``/``ckpt_every`` wire in periodic async checkpointing —
    the analog of the reference chief's periodic ``tf.train.Saver`` writes
    (SURVEY.md §5 checkpoint row), minus the chief: saving is collective.
    ``evaluate(state) -> dict`` runs every ``eval_every`` steps (and at the
    end); its metrics reach the hooks prefixed ``eval_`` — the held-out
    accuracy loop the reference never had (SURVEY.md §4 "do better").
    """
    if rng is None:
        rng = jax.random.key(0)
    it: Iterator = iter(data)
    pending_metrics = None
    t0 = time.perf_counter()
    start_step = int(state.step)
    for step in range(start_step, num_steps):
        batch = next(it)
        state, metrics = train_step(state, batch, rng)
        if log_every and ((step + 1) % log_every == 0 or step + 1 == num_steps):
            # Fetch (blocks on the step stream only here) — ONE device_get
            # for the whole dict, not a per-leaf float() sync each.
            fetched = {
                k: float(v) for k, v in jax.device_get(metrics).items()
            }
            dt = time.perf_counter() - t0
            steps_done = step + 1 - start_step
            fetched["steps_per_sec"] = steps_done / dt if dt > 0 else 0.0
            if jax.process_index() == 0:
                logger.info(
                    "step %d: %s",
                    step + 1,
                    " ".join(f"{k}={v:.5g}" for k, v in sorted(fetched.items())),
                )
            for hook in hooks:
                hook(step + 1, state, fetched)
            pending_metrics = fetched
        if evaluate is not None and eval_every and (
            (step + 1) % eval_every == 0 or step + 1 == num_steps
        ):
            ev = {
                f"eval_{k}": float(v)
                for k, v in jax.device_get(evaluate(state)).items()
            }
            if jax.process_index() == 0:
                logger.info(
                    "step %d eval: %s",
                    step + 1,
                    " ".join(f"{k}={v:.5g}" for k, v in sorted(ev.items())),
                )
            for hook in hooks:
                hook(step + 1, state, ev)
            pending_metrics = {**(pending_metrics or {}), **ev}
        if checkpointer is not None and ckpt_every and (step + 1) % ckpt_every == 0:
            checkpointer.save(step + 1, state)
    return state, pending_metrics
