"""Training driver loop — the replacement for MonitoredTrainingSession.

The reference's L6 (SURVEY.md §1): ``MonitoredTrainingSession`` + hooks +
``while not sess.should_stop(): sess.run(train_op)``. Here the loop is plain
Python around one compiled step; hooks become plain callables; there is no
chief (every host runs the identical loop; host-dependent work like metric
printing is gated on ``jax.process_index() == 0``).

TPU-first details:

- the loop never blocks on device values except at the logging cadence —
  metrics come back as device arrays and are only fetched every
  ``log_every`` steps, keeping the step stream fully async;
- the feed is **pull-ahead**: step ``i`` is dispatched *before* batch
  ``i+1`` is fetched, so host batch assembly/transfer overlaps device
  compute even for unwrapped producers, and composes with
  ``data.prefetch`` (which moves the assembly itself onto a feeder
  thread — in steady state ``next(it)`` is then a queue pop ≈ 0);
- feed stalls are measured, not inferred: every blocking ``next(it)`` is
  timed into ``feed_metrics`` and surfaced as ``host_wait_ms`` at the log
  cadence alongside ``steps_per_sec``.
"""

from __future__ import annotations

import logging
import math
import time
from collections.abc import Callable, Iterable, Iterator
from typing import Any

import jax

from distributed_tensorflow_tpu.obs.flightrec import NULL_RECORDER
from distributed_tensorflow_tpu.obs.memory import default_registry
from distributed_tensorflow_tpu.obs.metrics import FeedMetrics
from distributed_tensorflow_tpu.obs.trace import NULL_TRACER, Tracer

logger = logging.getLogger(__name__)

# hook(step: int, state, metrics: dict[str, float]) -> None, called at log cadence
Hook = Callable[[int, Any, dict], None]


class NonFiniteLossError(RuntimeError):
    """The step loss went NaN/Inf — training state is garbage from here.

    Raised by the loop's non-finite guard (``fit(nonfinite="abort")``, the
    default). Deliberately NOT a transient failure class: restarting from
    the last checkpoint would replay the same divergence, so
    ``train/resilience.py`` classifies it fatal-with-dump.
    """

    def __init__(self, step: int, loss: float):
        super().__init__(
            f"non-finite loss {loss!r} at step {step}; aborting (use "
            "--nonfinite=skip to tolerate)"
        )
        self.step = step
        self.loss = loss


def fit(
    state,
    train_step,
    data: Iterable,
    *,
    num_steps: int,
    rng: jax.Array | None = None,
    log_every: int = 100,
    hooks: tuple[Hook, ...] = (),
    checkpointer=None,
    ckpt_every: int = 0,
    evaluate: Callable[[Any], dict] | None = None,
    eval_every: int = 0,
    feed_metrics: FeedMetrics | None = None,
    tracer: Tracer | None = None,
    timeline=None,
    memory=None,
    recorder=None,
    fault_injector=None,
    nonfinite: str = "abort",
    should_stop: Callable[[], bool] | None = None,
):
    """Run the training loop; returns the final state.

    ``data`` yields already-placed global batches (see ``data`` package).
    ``checkpointer``/``ckpt_every`` wire in periodic async checkpointing —
    the analog of the reference chief's periodic ``tf.train.Saver`` writes
    (SURVEY.md §5 checkpoint row), minus the chief: saving is collective.
    ``evaluate(state) -> dict`` runs every ``eval_every`` steps (and at the
    end); its metrics reach the hooks prefixed ``eval_`` — the held-out
    accuracy loop the reference never had (SURVEY.md §4 "do better").

    ``feed_metrics`` collects host-wait observations (every blocking
    ``next(it)`` in the loop is timed into it); when ``data`` carries its
    own bundle (a ``data.prefetch`` wrapper exposes ``.metrics``) that one
    is picked up automatically so feeder- and consumer-side numbers land in
    one place. Logged throughput is **steady-state**: the wall-clock origin
    resets after the first step of the run completes, so step-0
    tracing+compilation never dilutes ``steps_per_sec``.

    ``tracer`` (obs/trace.py) records the per-step phase timeline —
    ``host_wait`` (blocked on the feed) and ``dispatch`` (handing the step
    to the device stream) every step, ``device``/``metrics_fetch`` at the
    log cadence (the only points the loop blocks on device values), plus
    ``checkpoint_save`` and ``eval`` spans — each carrying its ``step``
    correlation key. Disabled (the default) it is a no-op context manager
    per call site, cheap enough to leave in the hot loop.

    ``timeline`` (obs/fleet.py :class:`StepTimeline`) records every step's
    wall / host-wait / dispatch durations into windowed series and runs the
    in-line straggler detector — the per-host health view the fleet
    beacons publish (cli/train.py ``--beacon-dir``). Three clock reads and
    a histogram insert per step; ``None`` (the default) costs nothing.

    ``memory`` (obs/memory.py :class:`MemoryRegistry`; default the
    process-wide registry) receives the ``params`` / ``opt_state`` /
    ``grad_ring`` byte footprints once at loop entry — shape-derived, so
    the accounting never touches the step stream.

    ``recorder`` (obs/flightrec.py) receives the loop's failure-path
    events (``nonfinite_loss``); ``fault_injector``
    (train/faultinject.py) is consulted once per step before dispatch —
    both default to no-ops and cost nothing in the hot loop.

    ``nonfinite`` is the NaN/Inf-loss policy, checked at the metrics
    cadence (``log_every`` — the loop only ever blocks on device values
    there, so the guard adds ZERO extra syncs; up to ``log_every - 1``
    poisoned steps can run before detection): ``"abort"`` (default)
    raises :class:`NonFiniteLossError`, ``"skip"`` records the event and
    trains on.

    ``should_stop`` is polled once per step; returning True ends the loop
    cleanly with the current state (the preemption path —
    ``train/resilience.py`` wires its SIGTERM/SIGINT flag here and then
    writes the final synchronous checkpoint).
    """
    if rng is None:
        rng = jax.random.key(0)
    if tracer is None:
        tracer = NULL_TRACER
    if recorder is None:
        recorder = NULL_RECORDER
    if nonfinite not in ("abort", "skip"):
        raise ValueError(f"nonfinite must be 'abort' or 'skip', got {nonfinite!r}")
    # HBM accounting (obs/memory.py): shape-derived byte counts, no device
    # sync. ``memory`` defaults to the process-wide registry so a train
    # process's footprints show up anywhere /memz-style tooling looks.
    if memory is None:
        memory = default_registry()
    for component, tree in (
        ("params", getattr(state, "params", None)),
        ("opt_state", getattr(state, "opt_state", None)),
        ("grad_ring", getattr(state, "grad_buffer", None)),
    ):
        if tree is not None:
            memory.register_tree(component, tree)
    it: Iterator = iter(data)
    if feed_metrics is None:
        feed_metrics = getattr(data, "metrics", None) or FeedMetrics()
    pending_metrics = None
    start_step = int(state.step)
    if start_step >= num_steps:
        return state, None  # restored at (or past) the final step
    poison_step = None  # injected nonfinite_loss pending detection
    t0 = time.perf_counter()  # run origin (only used if the run is 1 step)
    t_steady = None           # reset after the first step: excludes compile
    t_fetch = time.perf_counter()
    with tracer.span("host_wait", "train", step=start_step):
        batch = next(it)
    feed_metrics.observe_wait(time.perf_counter() - t_fetch)
    for step in range(start_step, num_steps):
        if should_stop is not None and should_stop():
            logger.info("stop requested before step %d; leaving the loop", step)
            break
        poison = (
            fault_injector.on_step(step) if fault_injector is not None else False
        )
        t_iter = time.perf_counter()
        wait_s = 0.0
        with tracer.span("dispatch", "train", step=step):
            state, metrics = train_step(state, batch, rng)
        if poison and poison_step is None:
            # Injected nonfinite_loss: poison the METRIC (what the guard
            # watches), leaving the state untouched — the guard path is
            # exercised without actually diverging the model. Sticky until
            # the next metrics fetch, which is where the guard runs.
            poison_step = step + 1
        dispatch_s = time.perf_counter() - t_iter
        if t_steady is None:
            # The first call paid tracing + compilation (dispatch itself is
            # async); everything after this point is the steady-state
            # stream the logged throughput should describe.
            t_steady = time.perf_counter()
        if step + 1 < num_steps:
            # Pull-ahead: fetch batch i+1 while the device runs step i.
            t_fetch = time.perf_counter()
            with tracer.span("host_wait", "train", step=step + 1):
                batch = next(it)
            wait_s = time.perf_counter() - t_fetch
            feed_metrics.observe_wait(wait_s)
        if log_every and ((step + 1) % log_every == 0 or step + 1 == num_steps):
            # Fetch (blocks on the step stream only here) — ONE device_get
            # for the whole dict, not a per-leaf float() sync each. The
            # `device` span is the honest device edge: the blocking wait on
            # the dispatched step stream; `metrics_fetch` is the host-side
            # conversion after it.
            with tracer.span("device", "train", step=step + 1):
                fetched_dev = jax.device_get(metrics)
            with tracer.span("metrics_fetch", "train", step=step + 1):
                fetched = {k: float(v) for k, v in fetched_dev.items()}
            if poison_step is not None and "loss" in fetched:
                fetched["loss"] = float("nan")
                poison_step = None
            loss = fetched.get("loss")
            if loss is not None and not math.isfinite(loss):
                # str(), not the float: NaN/Inf are not valid JSON and the
                # recorder's dump must stay strictly parseable.
                recorder.record(
                    "nonfinite_loss", step=step + 1, loss=str(loss),
                    action=nonfinite,
                )
                if nonfinite == "abort":
                    raise NonFiniteLossError(step + 1, loss)
                logger.warning(
                    "non-finite loss %r at step %d (nonfinite=skip: training on)",
                    loss, step + 1,
                )
            now = time.perf_counter()
            steps_done = step - start_step  # steady-state steps completed
            if steps_done > 0:
                dt = now - t_steady
            else:
                # Log fired on the very first step: nothing but the compile
                # step exists, so report the honest compile-inclusive rate.
                dt, steps_done = now - t0, 1
            fetched["steps_per_sec"] = steps_done / dt if dt > 0 else 0.0
            fetched.update(feed_metrics.window())
            if jax.process_index() == 0:
                logger.info(
                    "step %d: %s",
                    step + 1,
                    " ".join(f"{k}={v:.5g}" for k, v in sorted(fetched.items())),
                )
            for hook in hooks:
                hook(step + 1, state, fetched)
            pending_metrics = fetched
        if evaluate is not None and eval_every and (
            (step + 1) % eval_every == 0 or step + 1 == num_steps
        ):
            with tracer.span("eval", "train", step=step + 1):
                ev = {
                    f"eval_{k}": float(v)
                    for k, v in jax.device_get(evaluate(state)).items()
                }
            if jax.process_index() == 0:
                logger.info(
                    "step %d eval: %s",
                    step + 1,
                    " ".join(f"{k}={v:.5g}" for k, v in sorted(ev.items())),
                )
            for hook in hooks:
                hook(step + 1, state, ev)
            pending_metrics = {**(pending_metrics or {}), **ev}
        if checkpointer is not None and ckpt_every and (step + 1) % ckpt_every == 0:
            with tracer.span("checkpoint_save", "train", step=step + 1):
                checkpointer.save(step + 1, state)
        if timeline is not None:
            # Whole-iteration wall time on purpose: a step slowed by eval
            # or a checkpoint save IS slow from the fleet's point of view;
            # the detector's trailing MEDIAN keeps periodic spikes from
            # shifting the baseline.
            timeline.record_step(
                step + 1,
                time.perf_counter() - t_iter,
                host_wait_s=wait_s,
                dispatch_s=dispatch_s,
            )
    return state, pending_metrics
