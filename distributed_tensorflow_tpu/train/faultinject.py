"""Deterministic fault injection: seeded failure schedules for chaos runs.

The reference cluster's failure modes (SURVEY.md §5: preempted workers,
wedged input readers, corrupt saver writes) are *hypothesized* in most
rebuilds — here every one of them is a first-class, reproducible event. A
:class:`FaultPlan` is a seeded schedule of fault events; a
:class:`FaultInjector` carries that schedule into the three hook points
that cover the failure surface:

- ``train/loop.py::fit`` — ``slow_step`` (a seeded sleep before dispatch,
  exactly what the straggler detector must flag), ``nonfinite_loss`` (the
  step's loss metric is poisoned to NaN so the non-finite guard trips on
  the real signal path), ``host_drop`` (SIGKILL of this very process —
  the preemption that never says goodbye);
- ``data/prefetch.py`` — ``feeder_error`` raised inside the feeder so it
  reaches the consumer through the real ``_ERROR`` queue channel;
- ``ckpt/checkpoint.py`` — ``ckpt_write_error`` raised from
  ``Checkpointer.save`` (the transient-storage failure class).

Every fired event is recorded to the flight recorder (kind
``fault_injected``) and counted for the host beacon, so detection and
reaction are exercised against the same signal path production would see.
Events are one-shot: a plan with ``feeder_error`` at batch 5 fires once;
after a resilient restart replays that position the stream proceeds —
which is precisely the transient-fault shape ``run_resilient`` exists
for. Schedule duplicates (two events, same kind, same step) fire once
each.

Reproduction workflow (docs/DEPLOY.md "Surviving a cluster"): a failure
seen with ``--fault-plan seed=7,...`` is re-run bit-identically with the
same spec — the schedule is a pure function of the spec string.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import random
import signal
import threading
import time
from collections.abc import Mapping
from pathlib import Path

logger = logging.getLogger(__name__)

__all__ = [
    "FAULT_KINDS",
    "FaultEvent",
    "FaultPlan",
    "FaultInjector",
    "InjectedFault",
]

#: the failure surface this module can schedule, one per hook point class.
FAULT_KINDS = (
    "slow_step",         # seeded sleep before dispatching a train step
    "feeder_error",      # exception raised inside the feed producer
    "nonfinite_loss",    # step loss metric poisoned to NaN
    "ckpt_write_error",  # Checkpointer.save raises (transient storage IO)
    "host_drop",         # SIGKILL this process (unannounced preemption)
)


class InjectedFault(OSError):
    """A scheduled fault firing as an exception.

    Subclasses :class:`OSError` deliberately: injected feeder/ckpt-IO
    faults must travel the same classification path as real storage and
    pipe errors (``train/resilience.py`` treats ``OSError`` as transient).
    """

    def __init__(self, kind: str, step: int):
        super().__init__(f"injected fault {kind!r} at step {step}")
        self.kind = kind
        self.step = step


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    """One scheduled fault. ``step`` is the train-step index for
    step-scoped kinds, the feed-stream batch index for ``feeder_error``,
    and the checkpoint step for ``ckpt_write_error``."""

    kind: str
    step: int
    duration_s: float = 0.0  # slow_step only: how long the sleep is

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; expected one of {FAULT_KINDS}"
            )
        if self.step < 0:
            raise ValueError(f"fault step must be >= 0, got {self.step}")


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """An ordered, seeded schedule of :class:`FaultEvent`.

    Build one three ways: explicitly (tests pinning exact steps),
    :meth:`generate` (seeded random placement — the chaos-suite form), or
    :meth:`parse` (the ``--fault-plan`` CLI surface: either a
    ``key=value,...`` spec or a path to a JSON file)."""

    events: tuple[FaultEvent, ...]
    seed: int | None = None

    @classmethod
    def generate(
        cls,
        seed: int,
        num_steps: int,
        counts: Mapping[str, int],
        *,
        slow_step_s: float = 0.05,
        min_step: int = 1,
    ) -> "FaultPlan":
        """Seeded schedule: ``counts[kind]`` events per kind, placed on
        distinct steps drawn uniformly from ``[min_step, num_steps)``.
        Pure function of the arguments — same seed, same schedule."""
        if num_steps <= min_step:
            raise ValueError(f"num_steps {num_steps} must exceed min_step {min_step}")
        rng = random.Random(seed)
        events = []
        for kind in sorted(counts):
            n = counts[kind]
            if kind not in FAULT_KINDS:
                raise ValueError(f"unknown fault kind {kind!r}")
            if n <= 0:
                continue
            span = range(min_step, num_steps)
            steps = rng.sample(span, min(n, len(span)))
            for s in sorted(steps):
                events.append(
                    FaultEvent(
                        kind,
                        s,
                        duration_s=slow_step_s if kind == "slow_step" else 0.0,
                    )
                )
        events.sort(key=lambda e: (e.step, e.kind))
        return cls(tuple(events), seed=seed)

    @classmethod
    def parse(cls, spec: str, *, num_steps: int = 0) -> "FaultPlan":
        """The ``--fault-plan`` surface.

        A path to a ``.json`` file loads an explicit plan
        (``{"seed": .., "events": [{"kind": .., "step": ..}, ..]}``).
        Otherwise a comma spec drives :meth:`generate`::

            seed=7,feeder_error=2,ckpt_write_error=1,slow_step=1,slow_step_s=0.1

        ``num_steps`` bounds the random placement (required for specs,
        supplied by the CLI from the workload config).
        """
        spec = spec.strip()
        if spec.endswith(".json") or os.path.sep in spec:
            return cls.from_file(spec)
        seed, counts, slow_s, min_step = 0, {}, 0.05, 1
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"bad --fault-plan entry {part!r}: expected key=value")
            key, _, val = part.partition("=")
            key = key.strip()
            if key == "seed":
                seed = int(val)
            elif key == "slow_step_s":
                slow_s = float(val)
            elif key == "min_step":
                min_step = int(val)
            elif key in FAULT_KINDS:
                counts[key] = int(val)
            else:
                raise ValueError(
                    f"unknown --fault-plan key {key!r}; expected seed/"
                    f"slow_step_s/min_step or one of {FAULT_KINDS}"
                )
        if not num_steps:
            raise ValueError("a --fault-plan spec needs num_steps to place events")
        return cls.generate(
            seed, num_steps, counts, slow_step_s=slow_s, min_step=min_step
        )

    @classmethod
    def from_file(cls, path: str | Path) -> "FaultPlan":
        doc = json.loads(Path(path).read_text())
        events = tuple(
            FaultEvent(
                e["kind"], int(e["step"]), duration_s=float(e.get("duration_s", 0.0))
            )
            for e in doc.get("events", ())
        )
        return cls(events, seed=doc.get("seed"))

    def to_json(self) -> str:
        return json.dumps(
            {
                "seed": self.seed,
                "events": [dataclasses.asdict(e) for e in self.events],
            }
        )


class FaultInjector:
    """Runtime carrier of a :class:`FaultPlan` across the hook points.

    One injector serves one training process; the feed hook runs on the
    prefetch feeder thread while the step/ckpt hooks run on the loop
    thread, so the fired-event ledger is lock-protected. ``recorder`` is
    any :class:`~distributed_tensorflow_tpu.obs.flightrec.FlightRecorder`
    (the NULL recorder when absent).
    """

    def __init__(self, plan: FaultPlan, *, recorder=None, sleep=time.sleep):
        from distributed_tensorflow_tpu.obs.flightrec import NULL_RECORDER

        self.plan = plan
        self.recorder = recorder if recorder is not None else NULL_RECORDER
        self._sleep = sleep
        self._lock = threading.Lock()
        # Multiset of pending events per kind: {kind: {step: [events]}} —
        # one-shot semantics with support for stacked duplicates.
        self._pending: dict[str, dict[int, list[FaultEvent]]] = {
            k: {} for k in FAULT_KINDS
        }
        for ev in plan.events:
            self._pending[ev.kind].setdefault(ev.step, []).append(ev)
        self.fired: list[dict] = []

    def _take(self, kind: str, step: int) -> FaultEvent | None:
        """Pop one pending event of ``kind`` at ``step`` and ledger it."""
        with self._lock:
            stack = self._pending[kind].get(step)
            if not stack:
                return None
            ev = stack.pop()
            if not stack:
                del self._pending[kind][step]
            self.fired.append({"kind": kind, "step": step})
        # detail key is "fault", not "kind" — record()'s own first
        # parameter is named kind.
        self.recorder.record("fault_injected", fault=kind, step=step)
        logger.warning("fault injection: %s at step %d", kind, step)
        return ev

    # ---- hook points -----------------------------------------------------

    def on_step(self, step: int) -> bool:
        """Called by ``fit`` before dispatching ``step``. Applies
        ``slow_step``/``host_drop``; returns True when this step's loss
        metric should be poisoned (``nonfinite_loss``)."""
        ev = self._take("slow_step", step)
        if ev is not None:
            self._sleep(ev.duration_s)
        if self._take("host_drop", step) is not None:
            # The unannounced preemption: flush the flight recorder so the
            # event survives the process (there is no atexit after SIGKILL),
            # then die the way a preempted host dies.
            self.recorder.dump("host_drop", force=True)
            os.kill(os.getpid(), signal.SIGKILL)
        return self._take("nonfinite_loss", step) is not None

    def check_feeder(self, index: int) -> None:
        """Called by the feed stage before producing batch ``index``."""
        if self._take("feeder_error", index) is not None:
            raise InjectedFault("feeder_error", index)

    def check_ckpt_save(self, step: int) -> None:
        """Called by ``Checkpointer.save`` before queueing the write."""
        if self._take("ckpt_write_error", step) is not None:
            raise InjectedFault("ckpt_write_error", step)

    # ---- observability ---------------------------------------------------

    def summary(self) -> dict:
        """Beacon payload: fired-event counts + the recent ledger tail."""
        with self._lock:
            counts: dict[str, int] = {}
            for f in self.fired:
                counts[f["kind"]] = counts.get(f["kind"], 0) + 1
            return {
                "injected_faults": counts,
                "recent_injected": list(self.fired)[-8:],
            }
