"""Training engine: the compiled SPMD train step and driver loop.

Replaces the reference's L4+L6 stack (SURVEY.md §1): the
``SyncReplicasOptimizer`` / per-worker ``apply_gradients`` machinery and the
``MonitoredTrainingSession`` ``sess.run`` loop. The entire per-step diagram of
SURVEY.md §3b (pull variables ⇄ compute ⇄ push gradients ⇄ accumulate ⇄
token barrier) collapses into ONE jit-compiled function with a single fused
AllReduce inside it.
"""

from distributed_tensorflow_tpu.train.state import TrainState, create_train_state  # noqa: F401
from distributed_tensorflow_tpu.train.step import (  # noqa: F401
    make_eval_step,
    make_rng,
    make_train_step,
)
from distributed_tensorflow_tpu.train.loop import NonFiniteLossError, fit  # noqa: F401
from distributed_tensorflow_tpu.train.faultinject import (  # noqa: F401
    FaultEvent,
    FaultInjector,
    FaultPlan,
    InjectedFault,
)
from distributed_tensorflow_tpu.train.resilience import (  # noqa: F401
    ResilienceConfig,
    ResilienceReport,
    run_resilient,
)
