"""Standard objectives: classification loss/metric builders over flax models.

The reference pairs each model with ``loss(logits, labels)`` graph-builders
(SURVEY.md §1 L5). Here one builder covers all image-classification
workloads; BERT's MLM+NSP objective lives with the model.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax


def make_classification_loss(
    model, *, label_smoothing: float = 0.0, aux_weight: float = 0.3
):
    """Return a ``LossFn`` for a flax classifier.

    Expects batches ``{"image": [B,H,W,C], "label": [B] int}``. Handles
    mutable ``batch_stats`` (BN models) and a ``dropout`` rng. Models that
    return ``(logits, aux_logits)`` in train mode (Inception-v3's auxiliary
    head) contribute ``aux_weight`` x the aux cross-entropy to the loss.
    """

    def ce(logits, labels):
        onehot = jax.nn.one_hot(labels, logits.shape[-1], dtype=jnp.float32)
        if label_smoothing:
            n = logits.shape[-1]
            onehot = onehot * (1.0 - label_smoothing) + label_smoothing / n
        return optax.softmax_cross_entropy(logits.astype(jnp.float32), onehot).mean()

    def loss_fn(params, model_state, batch, rng):
        variables = {"params": params, **model_state}
        mutable = [k for k in model_state if k != "params"]
        if mutable:
            out, new_model_state = model.apply(
                variables,
                batch["image"],
                train=True,
                mutable=mutable,
                rngs={"dropout": rng},
            )
        else:
            out = model.apply(
                variables, batch["image"], train=True, rngs={"dropout": rng}
            )
            new_model_state = model_state
        logits, aux = out if isinstance(out, tuple) else (out, None)
        labels = batch["label"]
        loss = ce(logits, labels)
        if aux is not None:
            loss = loss + aux_weight * ce(aux, labels)
        acc = (jnp.argmax(logits, -1) == labels).mean()
        return loss, (new_model_state, {"accuracy": acc})

    return loss_fn


def make_classification_metrics(model):
    """Return a ``metric_fn`` for eval: loss + accuracy, no mutation."""

    def metric_fn(params, model_state, batch):
        variables = {"params": params, **model_state}
        logits = model.apply(variables, batch["image"], train=False)
        labels = batch["label"]
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), labels
        ).mean()
        acc = (jnp.argmax(logits, -1) == labels).mean()
        return {"loss": loss, "accuracy": acc}

    return metric_fn


def init_model(model, rng, sample_batch, **kwargs) -> tuple[Any, Any]:
    """Initialize a flax model; returns ``(params, model_state)``."""
    variables = model.init(rng, sample_batch, train=False, **kwargs)
    params = variables.pop("params")
    return params, dict(variables)
