"""Preemption-safe supervised training: the reaction half of fleet health.

PR 10 built the *detection* substrate (StragglerDetector, HostBeacons,
``fleet_summary``); this module is what a host actually DOES about
failure — the modern image of the reference's MonitoredTrainingSession +
Supervisor recovery loop (SURVEY.md §5), minus the chief:

- :func:`run_resilient` wraps :func:`~..train.loop.fit` in a restart
  loop. Transient failures (feeder errors, checkpoint-storage IO —
  anything a retry can fix) restore from ``Checkpointer.restore_latest``
  and re-enter the loop with capped exponential backoff; the data stream
  is rebuilt through the producers' ``start_step`` resume contract
  (data/prefetch.py), so a restarted run consumes batches N.. exactly as
  an uninterrupted one would. Fatal failures (non-finite loss, shape
  errors — a restart would replay the divergence) dump the flight
  recorder and re-raise.
- :class:`PreemptionHandler` turns SIGTERM/SIGINT into a clean stop: the
  loop exits at the next step boundary, a final SYNCHRONOUS checkpoint is
  written, and the run returns with ``preempted=True`` — the
  maintenance-event discipline every TPU-pod scheduler expects.
- :class:`ResilientCheckpointer` makes periodic saves non-fatal: one
  immediate retry, then the failure is absorbed (flight-recorder
  ``ckpt_save_error`` event + ``ckpt_save_errors_total`` counter) and
  training continues on the still-good step stream — aborting only after
  ``max_consecutive`` failed save CADENCES, when the restart-loss bound
  the operator configured via ``ckpt_every`` no longer holds.

The restart budget is progress-aware: a restart that resumes from a
NEWER checkpoint than the previous failure resets the consecutive-failure
count (the job is limping forward); only restarts that make no progress
burn the budget, so a persistent fault cannot flap forever.

Elastic re-mesh composes from the outside: when the
:class:`~..obs.fleet.FleetSupervisor` decides ``re_mesh``, the relaunch
builds ``parallel.mesh.plan_elastic_mesh(surviving)``'s layout, places a
fresh abstract state on it, and ``restore_latest`` reads the sharded
checkpoint directly into the new layout (the PR 7 template machinery —
orbax/tensorstore reshards on read). docs/DEPLOY.md "Surviving a
cluster" is the runbook.
"""

from __future__ import annotations

import dataclasses
import logging
import signal
import threading
import time
from collections.abc import Callable, Iterable
from typing import Any

import jax

from distributed_tensorflow_tpu.obs.flightrec import NULL_RECORDER
from distributed_tensorflow_tpu.obs.metrics import Counter
from distributed_tensorflow_tpu.train.loop import NonFiniteLossError, fit

logger = logging.getLogger(__name__)

__all__ = [
    "CheckpointSaveError",
    "PreemptionHandler",
    "ResilienceConfig",
    "ResilienceReport",
    "ResilientCheckpointer",
    "RestartBudgetExhausted",
    "abstract_like",
    "classify_failure",
    "run_resilient",
    "train_restarts_total",
    "ckpt_save_errors_total",
]

#: process-wide resilience counters (docs/OBS.md "Training resilience").
train_restarts_total = Counter()
ckpt_save_errors_total = Counter()


class CheckpointSaveError(RuntimeError):
    """Too many consecutive periodic-save failures — the operator's
    configured restart-loss bound (``ckpt_every``) no longer holds, so
    continuing would be silent risk accumulation. Fatal by design."""


class RestartBudgetExhausted(RuntimeError):
    """The consecutive no-progress restart budget ran out."""


def classify_failure(exc: BaseException) -> str:
    """``"transient"`` (retry from the last checkpoint) or ``"fatal"``.

    Transient: storage/feed IO — :class:`OSError` (which covers
    ``InjectedFault``, ``ConnectionError``, ``TimeoutError``) and the
    prefetch wrapper's feeder-death RuntimeError. Fatal: everything a
    replay would reproduce — non-finite loss, shape/dtype errors
    (TypeError/ValueError), exhausted save budget, and anything unknown
    (when in doubt, stop loudly rather than loop).
    """
    if isinstance(exc, (NonFiniteLossError, CheckpointSaveError)):
        return "fatal"
    if isinstance(exc, OSError):
        return "transient"
    if isinstance(exc, RuntimeError) and "feeder" in str(exc):
        return "transient"
    return "fatal"


@dataclasses.dataclass
class ResilienceConfig:
    """Knobs for :func:`run_resilient` (CLI: ``--max-restarts``)."""

    max_restarts: int = 3            # consecutive no-progress restarts
    backoff_base_s: float = 0.5      # first retry delay
    backoff_factor: float = 2.0      # exponential growth per retry
    backoff_max_s: float = 30.0      # cap
    max_consecutive_ckpt_failures: int = 3
    preemption_signals: tuple[int, ...] = (signal.SIGTERM, signal.SIGINT)
    sleep: Callable[[float], None] = time.sleep  # injectable for tests

    def backoff_s(self, consecutive: int) -> float:
        return min(
            self.backoff_base_s * self.backoff_factor ** max(consecutive - 1, 0),
            self.backoff_max_s,
        )


class PreemptionHandler:
    """SIGTERM/SIGINT → a stop flag the training loop polls.

    The handler body only sets a :class:`threading.Event` and remembers
    the signal — no locks, no I/O (a signal can interrupt the main thread
    while it holds e.g. the flight-recorder lock; anything lock-taking
    here could deadlock). The interesting work (final checkpoint, the
    ``preempt_exit`` event) happens in :func:`run_resilient` after the
    loop exits. Installs only from the main thread (``signal.signal``'s
    own rule); elsewhere it degrades to a manual flag.
    """

    def __init__(self, signals: tuple[int, ...] = (signal.SIGTERM, signal.SIGINT)):
        self._signals = signals
        self._flag = threading.Event()
        self._prev: dict[int, Any] = {}
        self.signum: int | None = None

    def install(self) -> "PreemptionHandler":
        for s in self._signals:
            try:
                self._prev[s] = signal.signal(s, self._handle)
            except ValueError:
                # Not the main thread: no OS hook, the flag still works.
                logger.warning(
                    "cannot install preemption handler outside the main thread"
                )
                break
        return self

    def _handle(self, signum, frame) -> None:
        self.signum = signum
        self._flag.set()

    def should_stop(self) -> bool:
        return self._flag.is_set()

    @property
    def triggered(self) -> bool:
        return self._flag.is_set()

    def restore(self) -> None:
        """Reinstall the previous handlers (idempotent)."""
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        self._prev.clear()


class ResilientCheckpointer:
    """``Checkpointer`` wrapper making periodic saves non-fatal.

    ``save`` retries once immediately; a cadence where both attempts fail
    is absorbed (event + counter + warning) until ``max_consecutive``
    cadences fail in a row — then :class:`CheckpointSaveError` (fatal).
    Any successful save resets the run. ``restore_latest`` first drains
    in-flight async saves (a restore racing its own pending write would
    read a half-finalized step).
    """

    def __init__(self, inner, *, max_consecutive: int = 3, recorder=None):
        self._inner = inner
        self.max_consecutive = max_consecutive
        self.recorder = recorder if recorder is not None else NULL_RECORDER
        self.consecutive_failures = 0

    def save(self, step: int, state: Any, *, force: bool = False) -> None:
        err = None
        for attempt in (1, 2):
            try:
                self._inner.save(step, state, force=force)
                self.consecutive_failures = 0
                return
            except Exception as e:  # noqa: BLE001 — absorbing is the point
                err = e
                ckpt_save_errors_total.inc()
                self.recorder.record(
                    "ckpt_save_error", step=step, attempt=attempt,
                    error=type(e).__name__,
                )
                logger.warning(
                    "checkpoint save at step %d failed (attempt %d): %s",
                    step, attempt, e,
                )
        self.consecutive_failures += 1
        if self.consecutive_failures >= self.max_consecutive:
            raise CheckpointSaveError(
                f"{self.consecutive_failures} consecutive checkpoint-save "
                f"cadences failed (last at step {step}); the configured "
                "restart-loss bound no longer holds"
            ) from err
        logger.warning(
            "continuing without checkpoint at step %d (%d/%d consecutive "
            "save failures)",
            step, self.consecutive_failures, self.max_consecutive,
        )

    def wait_quiet(self) -> None:
        """Drain async writes; a failed in-flight write counts as a save
        error instead of propagating (the restore falls back to the last
        durable step either way)."""
        try:
            self._inner.wait()
        except Exception as e:  # noqa: BLE001
            ckpt_save_errors_total.inc()
            self.recorder.record(
                "ckpt_save_error", step=-1, attempt=0, error=type(e).__name__
            )
            logger.warning("async checkpoint flush failed: %s", e)

    def latest_step(self):
        return self._inner.latest_step()

    def restore_latest(self, state: Any):
        self.wait_quiet()
        return self._inner.restore_latest(state)

    def wait(self) -> None:
        self._inner.wait()

    def close(self) -> None:
        self._inner.close()


def abstract_like(state: Any):
    """Shape/dtype/sharding skeleton of a state pytree.

    ``run_resilient`` captures this BEFORE the first step: the compiled
    step donates the live state's buffers, so after one step the original
    object can never serve as a restore template again — the abstract
    tree (no buffers, just the layout contract) can, forever.
    """
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        if isinstance(x, jax.Array)
        else x,
        state,
    )


@dataclasses.dataclass
class ResilienceReport:
    """What :func:`run_resilient` hands back."""

    state: Any
    metrics: dict | None
    final_step: int
    completed: bool            # reached num_steps
    preempted: bool            # stopped on SIGTERM/SIGINT
    restarts: int              # transient-failure restarts performed
    failures: list[dict]       # [{step, error, kind}] per caught failure


def run_resilient(
    state,
    train_step,
    make_batches: Callable[[int], Iterable],
    *,
    num_steps: int,
    checkpointer=None,
    ckpt_every: int = 0,
    config: ResilienceConfig | None = None,
    recorder=None,
    fault_injector=None,
    make_state: Callable[[], Any] | None = None,
    **fit_kwargs,
) -> ResilienceReport:
    """Supervised :func:`fit`: restarts on transient failure, stops
    cleanly on preemption.

    ``make_batches(start_step)`` builds a fresh batch stream positioned
    at ``start_step`` — the producers' resume contract; each segment's
    stream is closed when the segment ends. ``make_state()`` (optional)
    rebuilds a fresh initial state for a restart that finds NO checkpoint
    to restore (without it, such a failure is re-raised — restarting a
    donated state from scratch silently would hide real data loss).

    Returns a :class:`ResilienceReport`; transient restarts are invisible
    to the caller beyond its counters. See the module docstring for the
    classification and budget rules.
    """
    config = config or ResilienceConfig()
    recorder = recorder if recorder is not None else NULL_RECORDER
    rckpt = None
    if checkpointer is not None:
        rckpt = ResilientCheckpointer(
            checkpointer,
            max_consecutive=config.max_consecutive_ckpt_failures,
            recorder=recorder,
        )
    template = abstract_like(state)
    handler = PreemptionHandler(config.preemption_signals).install()
    failures: list[dict] = []
    restarts = 0
    consecutive = 0
    last_resume_step = int(state.step)
    try:
        while True:
            start = int(state.step)
            batches = make_batches(start)
            try:
                state, metrics = fit(
                    state,
                    train_step,
                    batches,
                    num_steps=num_steps,
                    checkpointer=rckpt,
                    ckpt_every=ckpt_every,
                    recorder=recorder,
                    fault_injector=fault_injector,
                    should_stop=handler.should_stop,
                    **fit_kwargs,
                )
            except Exception as e:  # noqa: BLE001 — classified below
                _close(batches)
                kind = classify_failure(e)
                failures.append(
                    {"step": start, "error": type(e).__name__, "kind": kind}
                )
                if kind != "transient":
                    recorder.record(
                        "train_fatal", error=type(e).__name__, start_step=start
                    )
                    recorder.dump("train_fatal", force=True)
                    raise
                resume_step = rckpt.latest_step() if rckpt is not None else None
                progress = resume_step is not None and resume_step > last_resume_step
                consecutive = 1 if progress else consecutive + 1
                if consecutive > config.max_restarts:
                    recorder.record(
                        "train_fatal", error="RestartBudgetExhausted",
                        start_step=start,
                    )
                    recorder.dump("train_fatal", force=True)
                    raise RestartBudgetExhausted(
                        f"{consecutive - 1} consecutive restarts made no "
                        f"progress past step {last_resume_step} "
                        f"(budget {config.max_restarts}); last failure: "
                        f"{type(e).__name__}: {e}"
                    ) from e
                restarts += 1
                train_restarts_total.inc()
                delay = config.backoff_s(consecutive)
                recorder.record(
                    "train_restart", restart=restarts, error=type(e).__name__,
                    resume_step=resume_step if resume_step is not None else -1,
                    backoff_s=delay,
                )
                logger.warning(
                    "transient failure (%s: %s); restart %d in %.1fs",
                    type(e).__name__, e, restarts, delay,
                )
                config.sleep(delay)
                state = _restore(rckpt, template, make_state, e)
                last_resume_step = int(state.step)
                continue
            _close(batches)
            step_now = int(state.step)
            if handler.triggered:
                if rckpt is not None and rckpt.latest_step() != step_now:
                    # The preemption contract: a SYNCHRONOUS save — queue
                    # it, then block until durable before exiting.
                    rckpt.save(step_now, state, force=True)
                    rckpt.wait_quiet()
                recorder.record(
                    "preempt_exit", step=step_now,
                    signum=handler.signum if handler.signum is not None else -1,
                )
                logger.warning(
                    "preempted (signal %s): checkpointed at step %d, "
                    "exiting cleanly", handler.signum, step_now,
                )
                return ResilienceReport(
                    state=state, metrics=metrics, final_step=step_now,
                    completed=False, preempted=True, restarts=restarts,
                    failures=failures,
                )
            return ResilienceReport(
                state=state, metrics=metrics, final_step=step_now,
                completed=True, preempted=False, restarts=restarts,
                failures=failures,
            )
    finally:
        handler.restore()


def _restore(rckpt, template, make_state, cause: BaseException):
    """Fresh state for a restart: the newest checkpoint when one exists,
    ``make_state()`` when the run never checkpointed, else give up."""
    if rckpt is not None and rckpt.latest_step() is not None:
        state, step = rckpt.restore_latest(template)
        logger.info("restarting from checkpoint at step %d", step)
        return state
    if make_state is not None:
        logger.info("no checkpoint to restore; restarting from a fresh state")
        return make_state()
    raise RuntimeError(
        "transient failure before any checkpoint existed and no make_state "
        "factory was provided; cannot restart (the original state's buffers "
        "were donated to the step)"
    ) from cause


def _close(batches) -> None:
    close = getattr(batches, "close", None)
    if close is not None:
        try:
            close()
        except Exception as e:  # noqa: BLE001 — teardown must not mask the run
            logger.warning("batch-stream close failed: %s", e)
