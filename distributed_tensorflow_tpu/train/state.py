"""Train state: everything the compiled step reads and writes.

The reference scatters this state across processes — variables on ps shards,
optimizer slots beside them, ``global_step`` on the chief, SyncReplicas
accumulators in the ps graph (SURVEY.md §3b). Here it is one pytree, resident
on the mesh, threaded functionally through the jit'd step.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from flax import struct


@struct.dataclass
class TrainState:
    """One pytree holding the full training state.

    Attributes:
      step: global step — the single global step of SURVEY.md §3b, but with
        no chief to own it: every device holds the same replicated scalar.
      params: model parameters.
      opt_state: optax optimizer state (momentum/Adam slots — the analog of
        the reference's ps-hosted slot variables).
      model_state: mutable model collections (flax ``batch_stats`` for BN).
      grad_buffer: ``None`` for sync DP; for the async-stale flavor, a
        K-deep ring buffer of past aggregated gradients (leading dim K)
        emulating PS staleness deterministically (SURVEY.md §7 hard-part 1).
      buffer_index: next slot to overwrite in ``grad_buffer``.
    """

    step: jax.Array
    params: Any
    opt_state: Any
    model_state: Any = struct.field(default_factory=dict)
    grad_buffer: Any = None
    buffer_index: jax.Array | None = None


def create_train_state(
    params,
    tx: optax.GradientTransformation,
    model_state: Any = None,
    staleness: int = 0,
) -> TrainState:
    """Build an initial :class:`TrainState` on host (place with ``replicate``).

    ``staleness=K > 0`` pre-allocates the K-deep zero gradient ring buffer for
    the async-stale flavor: the first K applied updates are zero, exactly like
    a PS whose workers haven't delivered yet (SURVEY.md §3c).
    """
    grad_buffer = None
    buffer_index = None
    if staleness > 0:
        grad_buffer = jax.tree.map(
            lambda p: jnp.zeros((staleness,) + p.shape, p.dtype), params
        )
        buffer_index = jnp.zeros((), jnp.int32)
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=tx.init(params),
        model_state=model_state if model_state is not None else {},
        grad_buffer=grad_buffer,
        buffer_index=buffer_index,
    )
