"""distributed_tensorflow_tpu — a TPU-native distributed training framework.

A ground-up rebuild of the capabilities of ``hwang595/distributed_tensorflow``
(a TF-1.x gRPC parameter-server / NCCL-allreduce data-parallel harness; see
SURVEY.md for the full layer map) as an idiomatic JAX/XLA SPMD framework:

- one pod-level SPMD entrypoint over a ``jax.sharding.Mesh`` (replaces
  ``tf.train.ClusterSpec`` / ``tf.train.Server`` / ``run_ps.py`` +
  ``run_worker.py``, SURVEY.md §1 L1-L2, §3a-3b),
- gradient aggregation as XLA collectives over ICI (``lax.psum``) inside one
  compiled train step (replaces ``SyncReplicasOptimizer`` accumulators and the
  NCCL ring, SURVEY.md §2 native-component table),
- an explicit, deterministic staleness emulator for the reference's async-PS
  stale-gradient flavor (SURVEY.md §3c, §7 hard-part 1),
- five parity workloads: MNIST LeNet-5, CIFAR-10 ResNet-20, ImageNet
  ResNet-50, ImageNet Inception-v3 (async-stale), BERT-base pretraining
  (BASELINE.json "configs"),
- ring-attention sequence/context parallelism over an ICI mesh axis
  (``shard_map`` + ``lax.ppermute``) as a first-class capability.

NOTE on citations: the reference mount ``/root/reference`` was empty in every
session of this build (verified in SURVEY.md "EVIDENCE STATUS"), so docstrings
cite SURVEY.md sections and BASELINE.json lines — the only checkable sources
describing the reference — instead of reference ``file:line``.
"""

__version__ = "0.1.0"

from distributed_tensorflow_tpu import compat as _compat  # noqa: F401  (shims)
from distributed_tensorflow_tpu.parallel.mesh import (  # noqa: F401
    MeshSpec,
    build_mesh,
    initialize_runtime,
)
