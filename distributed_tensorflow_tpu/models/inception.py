"""Inception-v3 — the reference's async-PS stress workload (BASELINE.json:10).

The reference trains Inception-v3 on ImageNet with plain per-worker
``apply_gradients`` against ps-hosted variables — the stale-gradient flavor
(SURVEY.md §3c). Here the model pairs with the engine's ``mode="stale"``
deterministic staleness emulator (train/step.py).

Architecture follows the canonical Inception-v3 (Szegedy et al. 2015,
torchvision layout): BasicConv (conv-BN-relu, no bias, BN eps 1e-3)
everywhere; stages A(x3) → B → C(x4) → D → E(x2); optional auxiliary
classifier on the 17x17 grid. ~23.8M params without aux, ~27.2M with.

TPU notes: all branches are 1x1/3x3/5x5/1x7/7x1 convs — MXU-friendly; the
four branches of each block are independent and XLA schedules them into one
fused region; concatenation along channels is layout-free in NHWC.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax.numpy as jnp
from flax import linen as nn


class BasicConv(nn.Module):
    """conv(no bias) + BN(eps=1e-3) + relu — the Inception building block.

    With ``fused=True`` (and in train mode), qualified 1x1/stride-1 units
    run the fused conv+BN+ReLU Pallas backward (ops/fused_conv_bn.py) —
    the same substrate ResNet's ``pw_backend="fused"`` uses, wired here so
    the r4 kernel-family verdict is validated on BOTH conv workloads
    (VERDICT r3 Weak #2). Param trees are identical across paths (holder
    modules reuse the nn.Conv/nn.BatchNorm auto-names Conv_0/BatchNorm_0).
    """

    features: int
    kernel: tuple[int, int]
    strides: tuple[int, int] = (1, 1)
    padding: Any = "SAME"
    dtype: jnp.dtype = jnp.float32
    fused: bool = False

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        from distributed_tensorflow_tpu.ops.fused_conv_bn import (
            fused_supported,
            fused_unit,
        )

        b, h, w, cin = x.shape
        if (
            self.fused
            and train
            and self.kernel == (1, 1)
            and tuple(self.strides) == (1, 1)
            # A 1x1/stride-1 conv is padding-free only under SAME/VALID;
            # explicit numeric padding must take the plain path.
            and self.padding in ("SAME", "VALID")
            and fused_supported(b * h * w, cin, self.features)
        ):
            return fused_unit(
                x,
                self.features,
                relu=True,
                conv_name="Conv_0",
                bn_name="BatchNorm_0",
                dtype=self.dtype,
                eps=1e-3,
            )
        x = nn.Conv(
            self.features,
            self.kernel,
            strides=self.strides,
            padding=self.padding,
            use_bias=False,
            dtype=self.dtype,
            kernel_init=nn.initializers.he_normal(),
            name="Conv_0",
        )(x)
        x = nn.BatchNorm(
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-3,
            dtype=self.dtype,
            name="BatchNorm_0",
        )(x)
        return nn.relu(x)


def _avg_pool_same(x):
    return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")


class InceptionA(nn.Module):
    pool_features: int
    dtype: jnp.dtype = jnp.float32
    fused: bool = False

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        conv = partial(BasicConv, dtype=self.dtype, fused=self.fused)
        b1 = conv(64, (1, 1))(x, train=train)
        b5 = conv(48, (1, 1))(x, train=train)
        b5 = conv(64, (5, 5))(b5, train=train)
        b3 = conv(64, (1, 1))(x, train=train)
        b3 = conv(96, (3, 3))(b3, train=train)
        b3 = conv(96, (3, 3))(b3, train=train)
        bp = _avg_pool_same(x)
        bp = conv(self.pool_features, (1, 1))(bp, train=train)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class InceptionB(nn.Module):
    """35x35 → 17x17 grid reduction."""

    dtype: jnp.dtype = jnp.float32
    fused: bool = False

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        conv = partial(BasicConv, dtype=self.dtype, fused=self.fused)
        b3 = conv(384, (3, 3), strides=(2, 2), padding="VALID")(x, train=train)
        bd = conv(64, (1, 1))(x, train=train)
        bd = conv(96, (3, 3))(bd, train=train)
        bd = conv(96, (3, 3), strides=(2, 2), padding="VALID")(bd, train=train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionC(nn.Module):
    """17x17 blocks with factorized 1x7/7x1 convolutions."""

    channels_7x7: int
    dtype: jnp.dtype = jnp.float32
    fused: bool = False

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        conv = partial(BasicConv, dtype=self.dtype, fused=self.fused)
        c7 = self.channels_7x7
        b1 = conv(192, (1, 1))(x, train=train)
        b7 = conv(c7, (1, 1))(x, train=train)
        b7 = conv(c7, (1, 7))(b7, train=train)
        b7 = conv(192, (7, 1))(b7, train=train)
        bd = conv(c7, (1, 1))(x, train=train)
        bd = conv(c7, (7, 1))(bd, train=train)
        bd = conv(c7, (1, 7))(bd, train=train)
        bd = conv(c7, (7, 1))(bd, train=train)
        bd = conv(192, (1, 7))(bd, train=train)
        bp = _avg_pool_same(x)
        bp = conv(192, (1, 1))(bp, train=train)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class InceptionD(nn.Module):
    """17x17 → 8x8 grid reduction."""

    dtype: jnp.dtype = jnp.float32
    fused: bool = False

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        conv = partial(BasicConv, dtype=self.dtype, fused=self.fused)
        b3 = conv(192, (1, 1))(x, train=train)
        b3 = conv(320, (3, 3), strides=(2, 2), padding="VALID")(b3, train=train)
        b7 = conv(192, (1, 1))(x, train=train)
        b7 = conv(192, (1, 7))(b7, train=train)
        b7 = conv(192, (7, 1))(b7, train=train)
        b7 = conv(192, (3, 3), strides=(2, 2), padding="VALID")(b7, train=train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionE(nn.Module):
    """8x8 blocks with split 1x3/3x1 branches."""

    dtype: jnp.dtype = jnp.float32
    fused: bool = False

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        conv = partial(BasicConv, dtype=self.dtype, fused=self.fused)
        b1 = conv(320, (1, 1))(x, train=train)
        b3 = conv(384, (1, 1))(x, train=train)
        b3 = jnp.concatenate(
            [
                conv(384, (1, 3))(b3, train=train),
                conv(384, (3, 1))(b3, train=train),
            ],
            axis=-1,
        )
        bd = conv(448, (1, 1))(x, train=train)
        bd = conv(384, (3, 3))(bd, train=train)
        bd = jnp.concatenate(
            [
                conv(384, (1, 3))(bd, train=train),
                conv(384, (3, 1))(bd, train=train),
            ],
            axis=-1,
        )
        bp = _avg_pool_same(x)
        bp = conv(192, (1, 1))(bp, train=train)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class InceptionAux(nn.Module):
    """Auxiliary classifier over the 17x17x768 grid."""

    num_classes: int
    dtype: jnp.dtype = jnp.float32
    fused: bool = False

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        if x.shape[1] < 17 or x.shape[2] < 17:
            # The 5x5/3 pool then the VALID 5x5 conv need a >=17x17 grid
            # (((17-5)//3)+1 == 5); anything smaller collapses to a zero-size
            # spatial dim and jnp.mean over it yields silent NaN logits.
            raise ValueError(
                f"aux head needs a >=17x17 grid, got {x.shape[1]}x{x.shape[2]} "
                "(input >=299x299); use aux_logits=False for smaller inputs"
            )
        x = nn.avg_pool(x, (5, 5), strides=(3, 3), padding="VALID")
        x = BasicConv(128, (1, 1), dtype=self.dtype, fused=self.fused)(x, train=train)
        x = BasicConv(768, (5, 5), padding="VALID", dtype=self.dtype)(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="fc")(x)


class InceptionV3(nn.Module):
    """Inception-v3 over NHWC inputs (299x299 canonical; ≥75x75 with
    ``aux_logits=False``; the aux head needs the full 299x299 train-time
    input — it raises below a 17x17 aux grid).

    When ``aux_logits`` and ``train`` are both true, returns
    ``(logits, aux_logits)``; otherwise just ``logits`` — mirroring the
    classic two-head training loss (main + 0.3 * aux).
    """

    num_classes: int = 1000
    aux_logits: bool = True
    dropout_rate: float = 0.5
    dtype: jnp.dtype = jnp.float32
    fused: bool = False

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        conv = partial(BasicConv, dtype=self.dtype, fused=self.fused)
        x = x.astype(self.dtype)
        x = conv(32, (3, 3), strides=(2, 2), padding="VALID")(x, train=train)
        x = conv(32, (3, 3), padding="VALID")(x, train=train)
        x = conv(64, (3, 3))(x, train=train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        x = conv(80, (1, 1))(x, train=train)
        x = conv(192, (3, 3), padding="VALID")(x, train=train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")

        x = InceptionA(32, dtype=self.dtype, fused=self.fused)(x, train=train)
        x = InceptionA(64, dtype=self.dtype, fused=self.fused)(x, train=train)
        x = InceptionA(64, dtype=self.dtype, fused=self.fused)(x, train=train)
        x = InceptionB(dtype=self.dtype, fused=self.fused)(x, train=train)
        x = InceptionC(128, dtype=self.dtype, fused=self.fused)(x, train=train)
        x = InceptionC(160, dtype=self.dtype, fused=self.fused)(x, train=train)
        x = InceptionC(160, dtype=self.dtype, fused=self.fused)(x, train=train)
        x = InceptionC(192, dtype=self.dtype, fused=self.fused)(x, train=train)

        aux = None
        if self.aux_logits and (train or self.is_initializing()):
            # Runs during init (so the param tree is stable regardless of
            # `train`) and in training; skipped entirely in eval, where the
            # head is dead code — eval also works below the aux size guard.
            aux_head = InceptionAux(self.num_classes, dtype=self.dtype, fused=self.fused, name="aux")
            aux = aux_head(x, train=train)

        x = InceptionD(dtype=self.dtype, fused=self.fused)(x, train=train)
        x = InceptionE(dtype=self.dtype, fused=self.fused)(x, train=train)
        x = InceptionE(dtype=self.dtype, fused=self.fused)(x, train=train)

        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        logits = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        if train and aux is not None:
            return logits, aux
        return logits
