"""Model zoo: the reference's five parity workloads, in flax.linen.

Mirrors SURVEY.md §2 workload rows / BASELINE.json "configs":

- LeNet-5 (MNIST, single-chip sanity — SURVEY.md §3e)
- ResNet-20 (CIFAR-10, sync DP) and ResNet-50 (ImageNet, the north-star)
- Inception-v3 (ImageNet, async-stale flavor)
- BERT-base (pretraining, MLM+NSP; large embedding allreduce)

All models are pure graph-builders like the reference's ``inference()``/
``loss()`` functions (SURVEY.md §1 L5) — but as flax modules whose params are
an explicit pytree, so placement is a sharding annotation instead of a
``replica_device_setter`` device scope.
"""

from distributed_tensorflow_tpu.models.lenet import LeNet5  # noqa: F401
from distributed_tensorflow_tpu.models.resnet import (  # noqa: F401
    ResNet,
    ResNet20,
    ResNet50,
)
from distributed_tensorflow_tpu.models.inception import InceptionV3  # noqa: F401
from distributed_tensorflow_tpu.models.bert import (  # noqa: F401
    BertConfig,
    BertForPreTraining,
    BertModel,
    bert_base,
    make_bert_pretraining_loss,
)
from distributed_tensorflow_tpu.models.causal_lm import (  # noqa: F401
    CausalLM,
    CausalLMConfig,
    causal_lm_base,
    causal_param_specs,
    make_causal_lm_loss,
    sample_tokens,
)
