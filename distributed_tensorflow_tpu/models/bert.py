"""BERT-base pretraining — the reference's transformer workload (BASELINE.json:11).

The reference pretrains BERT-base data-parallel, stressing the large
embedding-table allreduce (SURVEY.md §2 workload rows, §7 hard-part 4). This
rebuild keeps that capability (pure-DP: the 30k-vocab embedding gradient
rides the same fused psum as everything else) and adds what the TF-1.x
harness never had: exact sequence/context parallelism — set
``config.seq_axis`` and the encoder runs ring attention over the ``"seq"``
mesh axis (parallel/ring_attention.py), with position offsets, pooling, and
the MLM loss all seq-shard-aware.

Architecture is the original BERT-base (Devlin et al.): post-LayerNorm
encoder, learned positions, GELU FFN, tied MLM decoder, NSP head.
12L/768H/12A/3072FF/vocab 30522 ≈ 109.5M params (encoder+embeddings+pooler).

Training objective: masked-LM cross-entropy over masked positions
(targets < 0 are ignored) + next-sentence-prediction cross-entropy —
``make_bert_pretraining_loss`` plugs into the standard engine
(train/step.py), including ``mode="stale"``.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from jax import lax

from distributed_tensorflow_tpu.parallel.ring_attention import (
    dense_attention,
    ring_attention,
)


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 512
    type_vocab_size: int = 2
    dropout_rate: float = 0.1
    dtype: jnp.dtype = jnp.float32
    # Mesh axis name for sequence parallelism, or None for single-shard
    # attention. With an axis set, the model must run inside shard_map with
    # the sequence dim of all [B, L] inputs sharded over that axis.
    seq_axis: str | None = None
    # Sequence-parallel strategy: "ring" streams K/V blocks around the ICI
    # ring (parallel/ring_attention.py, no head-count constraint);
    # "ulysses" re-partitions sharding from sequence to heads with two
    # all_to_alls and runs full-sequence attention per local head group
    # (parallel/ulysses.py; needs num_heads % ring size == 0). Both exact.
    sp_impl: str = "ring"
    # Tensor (model) parallelism: Megatron-style sharding of attention heads
    # and the FFN hidden dim over ``model_axis`` with ``model_parallel``
    # shards. Params are created GLOBAL (init with model_parallel=1 config)
    # and sliced by ``bert_param_specs``; inside shard_map the module builds
    # local-head/local-FFN projections and psums the row-parallel outputs.
    model_axis: str | None = None
    model_parallel: int = 1
    # Attention implementation: "auto" (flash for L >= 256, dense below —
    # the r3 measured crossover: flash beats dense 1.8-2.3x at L in
    # {512, 2048} but loses at L=128 where one fused dense matmul wins),
    # "dense" (XLA-composed), or "flash" (Pallas kernel,
    # ops/flash_attention.py). With seq_axis set the choice also selects
    # the ring's inner step ("flash" = Pallas kernel per streamed block).
    attn_impl: str = "auto"
    # Mixture-of-experts FFN: > 0 replaces every layer's dense FFN with a
    # switch-routed MoE of ``moe_experts`` experts (parallel/moe.py). With
    # ``expert_axis``/``expert_parallel`` set, experts shard over that mesh
    # axis (params init GLOBAL with expert_parallel=1, sliced by
    # ``bert_param_specs``). The load-balance aux loss is sown into the
    # "intermediates" collection; make_bert_pretraining_loss adds it.
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    expert_axis: str | None = None
    expert_parallel: int = 1
    # "replicated": every expert shard routes all tokens, partial outputs
    # psum (exact global capacity order). "alltoall": capacity-buffer
    # dispatch over the expert axis with tokens replicated outside the MoE
    # (parallel/moe.py moe_apply_a2a). "sharded": the PRODUCTION GShard
    # layout — the batch itself shards over the expert axis (expert group ≡
    # data group), so attention/embeddings/heads compute 1/E of the rows
    # per shard (zero redundant non-MoE compute) and the a2a routes from
    # the local slice with no trailing all_gather. Requires the loaders'
    # expert_sharded batch layout (data/text.py bert_batch_specs).
    moe_dispatch: str = "replicated"
    # Routing fan-out: 1 = Switch (top-1), 2 = GShard top-2 (renormalized
    # gates, first-choice queue priority, per-expert capacity UNCHANGED —
    # so top-2 doubles capacity pressure; parallel/moe.py
    # switch_route_topk). Works with all three dispatch layouts.
    moe_topk: int = 1
    # Pipeline parallelism (GPipe schedule, parallel/pipeline.py): with
    # ``pipeline_axis`` set the encoder's params are a stacked
    # ``[num_layers, ...]`` tree (created by nn.scan; shard dim 0 over the
    # pipeline axis via ``bert_param_specs``) and the encoder runs
    # ``pipeline_apply`` over ``pipeline_microbatches`` microbatches inside
    # shard_map. Embeddings/pooler/heads stay replicated across stages.
    # Outside shard_map (init, CPU tests) the same stacked params run as a
    # sequential scan — mathematically identical, so one checkpoint serves
    # both. num_layers must divide by pipeline_parallel; the global batch by
    # pipeline_microbatches.
    pipeline_axis: str | None = None
    pipeline_parallel: int = 1
    pipeline_microbatches: int = 0  # 0 -> 4 * pipeline_parallel
    # Activation rematerialisation (jax.checkpoint) over encoder layers:
    # each layer's activations are recomputed during backward instead of
    # saved, trading ~1 extra forward pass of layer FLOPs for O(num_layers)
    # less activation memory — the standard lever for longer L / larger
    # per-chip batch. Applies to all three encoder forms (module list,
    # sequential scan, GPipe schedule); the math is unchanged, so
    # trajectories are identical (tests/test_bert.py pins it).
    remat: bool = False


def bert_base(**overrides) -> BertConfig:
    return BertConfig(**overrides)


def _seq_offset(cfg: BertConfig, l_local: int):
    """Global position of this shard's first token (0 without seq axis)."""
    if cfg.seq_axis is None:
        return 0
    return lax.axis_index(cfg.seq_axis) * l_local


class BertEmbeddings(nn.Module):
    cfg: BertConfig

    def setup(self):
        cfg = self.cfg
        init = nn.initializers.normal(0.02)
        self.word = nn.Embed(
            cfg.vocab_size, cfg.hidden_size, embedding_init=init, dtype=cfg.dtype
        )
        self.position = nn.Embed(
            cfg.max_position, cfg.hidden_size, embedding_init=init, dtype=cfg.dtype
        )
        self.token_type = nn.Embed(
            cfg.type_vocab_size, cfg.hidden_size, embedding_init=init, dtype=cfg.dtype
        )
        self.ln = nn.LayerNorm(epsilon=1e-12, dtype=cfg.dtype)
        self.dropout = nn.Dropout(cfg.dropout_rate)

    def __call__(self, input_ids, token_type_ids, *, train: bool = False):
        l_local = input_ids.shape[1]
        positions = _seq_offset(self.cfg, l_local) + jnp.arange(l_local)
        x = (
            self.word(input_ids)
            + self.position(positions)[None]
            + self.token_type(token_type_ids)
        )
        return self.dropout(self.ln(x), deterministic=not train)


def _tp_psum(cfg: BertConfig, y):
    """Sum row-parallel partial outputs across the model axis (no-op tp=1)."""
    if cfg.model_axis is not None and cfg.model_parallel > 1:
        return lax.psum(y, cfg.model_axis)
    return y


class BertSelfAttention(nn.Module):
    """Multi-head attention, Megatron-sharded over ``cfg.model_axis``.

    Column-parallel Q/K/V (each shard projects its ``num_heads /
    model_parallel`` local heads), attention runs per-head locally (the
    seq ring composes: each ring step attends the local heads), and the
    row-parallel output projection psums partial [B,L,H] results. The
    output bias lives OUTSIDE the projection (``out_bias``) so it is added
    once, after the psum, not once per shard.
    """

    cfg: BertConfig

    @nn.compact
    def __call__(self, x, mask, *, train: bool = False):
        cfg = self.cfg
        b, l, _ = x.shape
        head_dim = cfg.hidden_size // cfg.num_heads
        local_heads = cfg.num_heads // cfg.model_parallel
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (local_heads, head_dim),
            dtype=cfg.dtype,
            kernel_init=nn.initializers.normal(0.02),
            name=name,
        )
        q, k, v = dense("query")(x), dense("key")(x), dense("value")(x)
        impl = cfg.attn_impl
        if impl == "auto":
            # Measured crossover (docs/PERF.md r3): the Pallas kernel wins
            # from L ~ 256 up; below, one fused dense matmul is faster. The
            # decision length is the one the inner attention actually sees:
            # the local shard for the ring (its inner runs per L_local
            # block), but the full gathered sequence for Ulysses (its inner
            # runs over L = l * ring_size after the all-to-alls).
            eff_l = l
            if (
                cfg.seq_axis is not None
                and cfg.sp_impl == "ulysses"
                and _axis_bound(cfg.seq_axis)
            ):
                eff_l = l * lax.axis_size(cfg.seq_axis)
            impl = "flash" if eff_l >= 256 else "dense"
        if cfg.seq_axis is not None:
            if cfg.sp_impl == "ulysses":
                from distributed_tensorflow_tpu.parallel.ulysses import (
                    ulysses_attention,
                )

                ctx = ulysses_attention(
                    q, k, v, cfg.seq_axis, mask=mask,
                    inner="flash" if impl == "flash" else "dense",
                )
            else:
                # The choice picks the ring's inner step too: "flash" runs
                # the Pallas kernel per streamed K/V block (logsumexp merge).
                inner = "flash" if impl == "flash" else "einsum"
                ctx = ring_attention(
                    q, k, v, cfg.seq_axis, mask=mask, inner=inner
                )
        elif impl == "flash":
            from distributed_tensorflow_tpu.ops import flash_attention

            ctx = flash_attention(q, k, v, mask=mask)
        else:
            ctx = dense_attention(q, k, v, mask=mask)
        out = nn.DenseGeneral(
            cfg.hidden_size,
            axis=(-2, -1),
            use_bias=False,
            dtype=cfg.dtype,
            kernel_init=nn.initializers.normal(0.02),
            name="out",
        )(ctx)
        out = _tp_psum(cfg, out)
        out = out + self.param(
            "out_bias", nn.initializers.zeros_init(), (cfg.hidden_size,)
        ).astype(out.dtype)
        out = nn.Dropout(cfg.dropout_rate)(out, deterministic=not train)
        # Post-LN (original BERT): LN over the residual sum.
        return nn.LayerNorm(epsilon=1e-12, dtype=cfg.dtype, name="ln")(x + out)


class MoeFfn(nn.Module):
    """Switch-routed MoE FFN: the expert-parallel alternative to the dense
    intermediate/output projections (parallel/moe.py does routing/dispatch;
    this module owns the router and the stacked expert params)."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, x, mask=None, *, train: bool = False):
        from distributed_tensorflow_tpu.parallel.moe import moe_apply, moe_apply_a2a

        cfg = self.cfg
        # All three sharding families compose here: expert-parallel (stacked
        # expert dim over "expert"), sequence-parallel (routing statistics
        # psum over the seq ring — engine's global-loss contract), and
        # tensor-parallel (each expert's FFN hidden dim Megatron-sharded
        # over "model": column-parallel w1/b1, row-parallel w2 with the
        # partial outputs psum'd after dispatch; b2 enters as b2/tp on each
        # shard so the psum reconstructs it exactly once).
        if cfg.moe_dispatch not in ("replicated", "alltoall", "sharded"):
            raise ValueError(f"unknown moe_dispatch {cfg.moe_dispatch!r}")
        if cfg.moe_dispatch == "sharded" and cfg.expert_parallel <= 1:
            raise ValueError(
                "moe_dispatch='sharded' routes from the expert-sharded batch "
                "— it requires expert_parallel > 1"
            )
        b, l, h = x.shape
        tp = cfg.model_parallel
        ff_local = cfg.intermediate_size // tp
        e_local = cfg.moe_experts // cfg.expert_parallel
        init = nn.initializers.normal(0.02)
        router = nn.Dense(
            cfg.moe_experts,
            use_bias=False,
            dtype=jnp.float32,
            kernel_init=init,
            name="router",
        )
        w1 = self.param("experts_w1", init, (e_local, h, ff_local), jnp.float32)
        b1 = self.param(
            "experts_b1", nn.initializers.zeros_init(), (e_local, ff_local), jnp.float32
        )
        w2 = self.param("experts_w2", init, (e_local, ff_local, h), jnp.float32)
        b2 = self.param(
            "experts_b2", nn.initializers.zeros_init(), (e_local, h), jnp.float32
        )

        def expert_fn(p, tokens):
            # tanh-approx gelu: google-bert's ORIGINAL formulation, and
            # measured 14 ms/step faster than erf at L=512 b=48 (r5).
            t = nn.gelu(
                tokens @ p["w1"].astype(cfg.dtype) + p["b1"].astype(cfg.dtype),
                approximate=True,
            )
            # 1/tp of the bias per model shard: the post-dispatch _tp_psum
            # sums the row-parallel partials AND reassembles b2 exactly once.
            return t @ p["w2"].astype(cfg.dtype) + p["b2"].astype(cfg.dtype) / tp

        tokens = x.reshape(b * l, h)
        logits = router(tokens)
        # Token-sharding axes: the aux-loss statistics must psum over every
        # axis the tokens are split across so the loss is the global ratio
        # on all shards (seq contract, train/step.py). The a2a dispatch
        # additionally shards tokens over the expert axis itself.
        stats_axes = () if cfg.seq_axis is None else (cfg.seq_axis,)
        ep_active = cfg.expert_parallel > 1
        use_a2a = cfg.moe_dispatch == "alltoall" and ep_active
        apply_kwargs = dict(
            capacity_factor=cfg.moe_capacity_factor,
            # PAD positions must not consume routing capacity or bias the
            # load-balance aux — only attention-mask-valid tokens route.
            valid=None if mask is None else mask.reshape(b * l),
            topk=cfg.moe_topk,
        )
        experts = {"w1": w1, "b1": b1, "w2": w2, "b2": b2}
        if cfg.moe_dispatch == "sharded":
            # Production GShard layout (expert group ≡ data group): the
            # batch arrives ALREADY sharded over the expert axis — b here is
            # the local slice, attention/embeddings/heads computed it 1/E-
            # sized, and the a2a routes straight from it. Per-group aux
            # statistics (no expert psum): each group's aux is a complete
            # loss term that the engine's DP-mean averages like the rest.
            y, aux = moe_apply_a2a(
                expert_fn,
                experts,
                logits,
                tokens,
                axis_name=cfg.expert_axis,
                stats_axes=stats_axes,
                tokens_sharded=True,
                **apply_kwargs,
            )
        elif use_a2a:
            y, aux = moe_apply_a2a(
                expert_fn,
                experts,
                logits,
                tokens,
                axis_name=cfg.expert_axis,
                stats_axes=stats_axes + (cfg.expert_axis,),
                **apply_kwargs,
            )
        else:
            y, aux = moe_apply(
                expert_fn,
                experts,
                logits,
                tokens,
                axis_name=cfg.expert_axis if ep_active else None,
                stats_axes=stats_axes,
                **apply_kwargs,
            )
        y = _tp_psum(cfg, y)
        self.sow("intermediates", "moe_aux", aux)
        return y.reshape(b, l, h)


class BertLayer(nn.Module):
    cfg: BertConfig

    # ``train`` is positional-or-keyword (no ``*``) so nn.remat can mark it
    # static by argnum (self=0, x=1, mask=2, train=3) — see BertModel.setup.
    @nn.compact
    def __call__(self, x, mask, train: bool = False):
        cfg = self.cfg
        x = BertSelfAttention(cfg, name="attention")(x, mask, train=train)
        if cfg.moe_experts:
            # MoE FFN (dropped-overflow tokens emit 0 and ride the residual).
            y = MoeFfn(cfg, name="moe")(x, mask, train=train)
        else:
            # Column-parallel up-projection, row-parallel down-projection
            # with the bias applied post-psum (see BertSelfAttention).
            y = nn.Dense(
                cfg.intermediate_size // cfg.model_parallel,
                dtype=cfg.dtype,
                kernel_init=nn.initializers.normal(0.02),
                name="intermediate",
            )(x)
            # tanh-approx gelu == google-bert's original; 14 ms/step
            # faster than erf at the L=512 b=48 production config (r5).
            y = nn.gelu(y, approximate=True)
            y = nn.Dense(
                cfg.hidden_size,
                use_bias=False,
                dtype=cfg.dtype,
                kernel_init=nn.initializers.normal(0.02),
                name="output",
            )(y)
            y = _tp_psum(cfg, y)
            y = y + self.param(
                "output_bias", nn.initializers.zeros_init(), (cfg.hidden_size,)
            ).astype(y.dtype)
        y = nn.Dropout(cfg.dropout_rate)(y, deterministic=not train)
        return nn.LayerNorm(epsilon=1e-12, dtype=cfg.dtype, name="ln")(x + y)


class _ScanBertLayer(nn.Module):
    """nn.scan target: carry = hidden states; mask/train ride as broadcast
    positional args (train is a plain python bool — static through scan)."""

    cfg: BertConfig

    @nn.compact
    def __call__(self, x, mask, train):
        x = BertLayer(self.cfg, name="layer")(x, mask, train=train)
        return x, None


def _axis_bound(name: str) -> bool:
    """True iff ``name`` is a mesh axis bound by an enclosing shard_map."""
    try:
        lax.axis_size(name)
        return True
    except NameError:
        return False


class BertModel(nn.Module):
    """Encoder + pooler. Returns (hidden [B,L,H], pooled [B,H])."""

    cfg: BertConfig

    def setup(self):
        cfg = self.cfg
        self.embeddings = BertEmbeddings(cfg)
        if cfg.pipeline_axis is not None or cfg.pipeline_parallel > 1:
            # Every parallelism family composes with the pipeline: tp
            # (Megatron-sharded stacked layers), moe/ep (aux threaded
            # through the GPipe schedule with drain masking), and sp (the
            # microbatch split is over batch ROWS while the seq axis
            # shards length — orthogonal dims, so the ring/Ulysses
            # collectives simply run per (layer, microbatch) inside the
            # schedule; the attention-mask microbatching slices the
            # seq-LOCAL mask). Trajectories pinned in
            # tests/test_bert_pp.py.
            if cfg.num_layers % cfg.pipeline_parallel:
                raise ValueError(
                    f"num_layers {cfg.num_layers} not divisible by "
                    f"pipeline_parallel {cfg.pipeline_parallel}"
                )
            scan_target = _ScanBertLayer
            if cfg.remat:
                # remat INSIDE the scan: each layer recomputes during the
                # scan's backward sweep. prevent_cse=False — under scan the
                # XLA CSE hazard remat guards against cannot occur, and
                # leaving it True blocks useful fusion.
                scan_target = nn.remat(
                    _ScanBertLayer, static_argnums=(3,), prevent_cse=False
                )
            self.encoder = nn.scan(
                scan_target,
                # intermediates rides the scan too (stacked per layer):
                # the MoE FFN sows its aux loss there, and the sequential-
                # semantics path (init / single-stage runs) must carry it
                # exactly like the per-layer module list does.
                variable_axes={"params": 0, "intermediates": 0},
                split_rngs={"params": True, "dropout": True},
                length=cfg.num_layers,
                in_axes=(nn.broadcast, nn.broadcast),
            )(cfg, name="encoder")
            self.layers = None
        else:
            # prevent_cse=True (the default) is LOAD-BEARING here: under
            # plain jit XLA would otherwise CSE the backward's recomputed
            # forward against the saved one, silently restoring the full
            # activation footprint (measured at L=512 b=96 bf16: temp
            # 13.50 GiB unchanged with False; 5.12 GiB with True). Under
            # scan the loop boundary already blocks that CSE, so the scan
            # target above keeps False (the flax-recommended pairing).
            layer_cls = (
                nn.remat(BertLayer, static_argnums=(3,))
                if cfg.remat
                else BertLayer
            )
            self.layers = [
                layer_cls(cfg, name=f"layer_{i}") for i in range(cfg.num_layers)
            ]
        self.pooler = nn.Dense(
            cfg.hidden_size,
            dtype=cfg.dtype,
            kernel_init=nn.initializers.normal(0.02),
        )

    def _encode_pipelined(self, x, attention_mask, *, train: bool):
        """GPipe the stacked encoder over the bound pipeline axis.

        Called inside shard_map where this stage's param slice has leading
        dim ``num_layers / S``. The per-(layer, microbatch) context slices
        the attention mask and folds the dropout rng; drained-phase ticks
        compute garbage that is never collected (parallel/pipeline.py).
        """
        from distributed_tensorflow_tpu.parallel.pipeline import pipeline_apply

        cfg = self.cfg
        S = lax.axis_size(cfg.pipeline_axis)
        M = cfg.pipeline_microbatches or 4 * S
        B, L = attention_mask.shape
        need_rng = train and cfg.dropout_rate > 0.0
        base_rng = self.make_rng("dropout") if need_rng else None
        mask_mb = attention_mask.reshape(M, B // M, L)
        stacked = self.variables["params"]["encoder"]["layer"]
        # parent=None: a detached functional instance — its .apply below runs
        # on explicit param slices, never registering as a submodule here.
        layer = BertLayer(cfg, parent=None)
        moe = cfg.moe_experts > 0

        def layer_fn(p_one, h, ctx):
            m = lax.dynamic_index_in_dim(
                mask_mb, ctx["microbatch"], 0, keepdims=False
            )
            rngs = None
            if need_rng:
                r = jax.random.fold_in(base_rng, ctx["layer"])
                rngs = {"dropout": jax.random.fold_in(r, ctx["microbatch"])}
            if moe:
                # The detached apply would drop sown intermediates — pull
                # the MoE aux out explicitly and let the schedule thread it
                # (pipeline_apply with_aux masks drain-phase garbage).
                h2, mods = layer.apply(
                    {"params": p_one}, h, m, train=train, rngs=rngs,
                    mutable=["intermediates"],
                )
                leaves = jax.tree.leaves(mods["intermediates"])
                return h2, sum(leaves) / len(leaves)
            return layer.apply({"params": p_one}, h, m, train=train, rngs=rngs)

        if cfg.remat:
            # Remat per (layer, microbatch) tick: the GPipe schedule's
            # backward sweep recomputes each tick's layer activations
            # instead of saving M x S of them. All layer_fn args are array
            # pytrees (ctx's indices are traced scan counters).
            layer_fn = jax.checkpoint(layer_fn, prevent_cse=False)

        out = pipeline_apply(
            layer_fn,
            stacked,
            x,
            axis_name=cfg.pipeline_axis,
            n_microbatches=M,
            with_context=True,
            with_aux=moe,
        )
        if moe:
            x, aux = out
            # Re-sow under this module so make_bert_pretraining_loss's
            # intermediates average finds it, same as the sequential path.
            self.sow("intermediates", "moe_aux", aux)
            return x
        return out

    def __call__(self, input_ids, attention_mask, token_type_ids, *, train=False):
        cfg = self.cfg
        x = self.embeddings(input_ids, token_type_ids, train=train)
        if self.layers is None:
            if (
                cfg.pipeline_axis is not None
                and not self.is_initializing()
                and _axis_bound(cfg.pipeline_axis)
            ):
                x = self._encode_pipelined(x, attention_mask, train=train)
            else:
                # Stacked params, sequential semantics (init / tests /
                # single-stage runs) — same math as the pipelined schedule.
                x, _ = self.encoder(x, attention_mask, train)
        else:
            for layer in self.layers:
                # train POSITIONALLY: with cfg.remat the layer class is
                # nn.remat(BertLayer, static_argnums=(3,)) and the static
                # marking only applies to positional args.
                x = layer(x, attention_mask, train)
        first = x[:, 0]
        if cfg.seq_axis is not None:
            # The global [CLS] token lives on seq-shard 0: psum-select it so
            # every shard pools the same vector (grads flow back to shard 0
            # only, and the engine's seq-psum counts them exactly once).
            is_first = (lax.axis_index(cfg.seq_axis) == 0).astype(first.dtype)
            first = lax.psum(first * is_first, cfg.seq_axis)
        pooled = jnp.tanh(self.pooler(first))
        return x, pooled


class BertForPreTraining(nn.Module):
    """MLM (tied decoder) + NSP heads over BertModel.

    ``__call__(batch, train) -> (mlm_logits [B,L,V], nsp_logits [B,2])``.
    """

    cfg: BertConfig

    def setup(self):
        cfg = self.cfg
        self.bert = BertModel(cfg)
        self.mlm_transform = nn.Dense(
            cfg.hidden_size,
            dtype=cfg.dtype,
            kernel_init=nn.initializers.normal(0.02),
        )
        self.mlm_ln = nn.LayerNorm(epsilon=1e-12, dtype=cfg.dtype)
        self.mlm_bias = self.param(
            "mlm_bias", nn.initializers.zeros_init(), (cfg.vocab_size,)
        )
        self.nsp_head = nn.Dense(
            2, dtype=jnp.float32, kernel_init=nn.initializers.normal(0.02)
        )

    def _heads(self, hidden, pooled):
        h = self.mlm_ln(nn.gelu(self.mlm_transform(hidden), approximate=True))
        # Tied decoder: logits against the word-embedding table. Logits KEEP
        # the compute dtype: at BERT geometry the [B, L, V] tensor is the
        # single biggest array in the step (1.5 GB bf16 at L=512 b=48), and
        # the r5 trace showed the old f32 upcast doubling every loss-side
        # pass over it (3.0 GB reads in the CE reduce, the argmax, and the
        # bwd softmax recompute — scripts/bert_breakdown.py). _mlm_stats
        # does its reductions in f32 on the fly; bf16 storage costs no
        # stability (max is exact in bf16, exp/sum accumulate in f32).
        mlm_logits = self.bert.embeddings.word.attend(h) + self.mlm_bias.astype(
            self.cfg.dtype
        )
        nsp_logits = self.nsp_head(pooled)
        return mlm_logits, nsp_logits.astype(jnp.float32)

    def __call__(self, input_ids, attention_mask, token_type_ids, *, train=False):
        hidden, pooled = self.bert(
            input_ids, attention_mask, token_type_ids, train=train
        )
        return self._heads(hidden, pooled)

    def serve_outputs(self, input_ids, attention_mask, token_type_ids):
        """Inference-only forward for the serving engine (serve/engine.py):
        one encoder pass yielding ``(mlm_logits, nsp_logits, pooled)`` —
        the MLM scoring surface plus the pooled [CLS] sentence embedding,
        without a second encoder pass for the embedding endpoint."""
        hidden, pooled = self.bert(
            input_ids, attention_mask, token_type_ids, train=False
        )
        mlm_logits, nsp_logits = self._heads(hidden, pooled)
        return mlm_logits, nsp_logits, pooled


def _mlm_stats(mlm_logits, batch, seq_axis):
    """Shared MLM statistics for the train loss and eval metrics: CE sum,
    masked-token count, and correct count over this shard — psum'd over the
    seq ring so they are GLOBAL sums (the one masking/clamp/psum recipe both
    paths must agree on).

    The CE is computed in f32 ON THE FLY from the logits' storage dtype
    (bf16 at the production config): the row max is exact in bf16, the
    shifted exp/sum converts per element inside the fused reduce, and the
    backward emits the softmax cotangent in storage dtype. Versus upcasting
    the [B, L, V] logits to f32 first, every pass over the step's biggest
    tensor moves half the bytes (measured 6.8 ms for the old f32 CE reduce
    alone, scripts/bert_breakdown.py). Accuracy reuses the already-computed
    row max instead of a second full argmax pass over [B, L, V]: a masked
    position counts correct iff its target logit equals the row max
    (ties — measure-zero in f32, rare in bf16 — count correct)."""
    targets = batch["mlm_targets"]
    weights = (targets >= 0).astype(jnp.float32)
    m = lax.stop_gradient(jnp.max(mlm_logits, axis=-1, keepdims=True))
    # Convert-then-subtract: the convert runs in-register inside the fused
    # reduce (no f32 materialization), and the shift itself is exact f32.
    shifted = mlm_logits.astype(jnp.float32) - m.astype(jnp.float32)
    lse = jnp.log(jnp.sum(jnp.exp(shifted), axis=-1)) + m[..., 0].astype(
        jnp.float32
    )
    tgt_logit = jnp.take_along_axis(
        mlm_logits, jnp.maximum(targets, 0)[..., None], axis=-1
    )[..., 0]
    ce = lse - tgt_logit.astype(jnp.float32)
    num = jnp.sum(ce * weights)
    den = jnp.sum(weights)
    correct = jnp.sum(
        (tgt_logit == m[..., 0]).astype(jnp.float32) * weights
    )
    if seq_axis is not None:
        num = lax.psum(num, seq_axis)
        den = lax.psum(den, seq_axis)
        correct = lax.psum(correct, seq_axis)
    return num, den, correct


def make_bert_eval_metrics(model: BertForPreTraining):
    """Eval ``metric_fn`` for :func:`make_eval_step`: MLM/NSP losses and
    accuracies on held-out batches, no dropout, no mutation. MLM entries are
    ``(num, den)`` pairs so the eval step reduces them as global ratios over
    the DP axes (variable masked-token counts per shard); seq-parallel
    handling is shared with the training loss (:func:`_mlm_stats`)."""
    seq_axis = model.cfg.seq_axis

    def metric_fn(params, model_state, batch):
        del model_state
        mlm_logits, nsp_logits = model.apply(
            {"params": params},
            batch["input_ids"],
            batch["attention_mask"],
            batch["token_type_ids"],
            train=False,
        )
        num, den, correct = _mlm_stats(mlm_logits, batch, seq_axis)
        b = batch["nsp_label"].shape[0]
        nsp_ce = optax.softmax_cross_entropy_with_integer_labels(
            nsp_logits, batch["nsp_label"]
        ).sum()
        nsp_correct = (
            (jnp.argmax(nsp_logits, -1) == batch["nsp_label"])
            .astype(jnp.float32)
            .sum()
        )
        rows = jnp.asarray(b, jnp.float32)
        return {
            "mlm_loss": (num, den),
            "mlm_accuracy": (correct, den),
            "nsp_loss": (nsp_ce, rows),
            "nsp_accuracy": (nsp_correct, rows),
        }

    return metric_fn


def bert_param_specs(
    params,
    model_axis: str | None = "model",
    expert_axis: str | None = None,
    pipeline_axis: str | None = None,
):
    """PartitionSpec tree for Megatron-TP / expert sharding of BERT params.

    Pass the GLOBAL params (init'd with ``model_parallel=1`` /
    ``expert_parallel=1``) and the mesh axes actually in use (``None``
    disables that sharding family — a spec must never name an axis the mesh
    doesn't have). Returns a matching tree: Q/K/V kernels
    ``P(None, model, None)`` / biases ``P(model, None)`` (column-parallel
    over heads), attention-out and FFN down-projection kernels
    row-parallel, FFN up-projection column-parallel, stacked MoE expert
    params over the expert axis, everything else (embeddings, LayerNorms,
    post-psum biases, router, pooler, heads) replicated. Feed to
    ``place_state``/``make_train_step`` as the param sharding contract
    (train/step.py).
    """
    from jax.sharding import PartitionSpec as P

    rules = ()
    if model_axis is not None:
        rules += (
            (("query", "kernel"), P(None, model_axis, None)),
            (("key", "kernel"), P(None, model_axis, None)),
            (("value", "kernel"), P(None, model_axis, None)),
            (("query", "bias"), P(model_axis, None)),
            (("key", "bias"), P(model_axis, None)),
            (("value", "bias"), P(model_axis, None)),
            (("out", "kernel"), P(model_axis, None, None)),
            (("intermediate", "kernel"), P(None, model_axis)),
            (("intermediate", "bias"), P(model_axis)),
            (("output", "kernel"), P(model_axis, None)),
        )
    if expert_axis is not None or model_axis is not None:
        # MoE expert stacks: dim 0 over the expert axis; with TP the FFN
        # hidden dim is additionally Megatron-sharded over the model axis
        # (w1 column-parallel, w2 row-parallel, b1 column-parallel, b2
        # replicated across model — it enters as b2/tp per shard).
        rules += (
            (("experts_w1",), P(expert_axis, None, model_axis)),
            (("experts_w2",), P(expert_axis, model_axis, None)),
            (("experts_b1",), P(expert_axis, model_axis)),
            (("experts_b2",), P(expert_axis, None)),
        )

    def spec_for(path, leaf) -> P:
        names = tuple(
            p.key for p in path if isinstance(p, jax.tree_util.DictKey)
        )
        # Int8-packed kernels (models/quant.py): the "_q8" payload shards
        # exactly like the fp32 kernel it replaced, and its per-output-
        # channel "_q8_scale" vector carries only the kernel's LAST-axis
        # sharding (replicated when the output axis is unsharded) — the
        # quantize reduction keeps the trailing axis, so a shard-direct
        # restore places both leaves without a resharding round-trip.
        # Engines reject quantization for the stacked pipeline variant, so
        # the encoder branch below never sees these suffixes.
        quant = names[-1] if names and names[-1] in ("_q8", "_q8_scale") \
            else None
        if quant is not None:
            names = names[:-1]
        # Stacked encoder (pipeline config): every leaf under "encoder"
        # carries a leading [num_layers] dim sharded over the pipeline axis.
        # TP/EP rules compose — the per-layer spec slots in behind the
        # stacking dim (e.g. a stacked Q kernel [L, H, heads, hd] gets
        # P("pipeline", None, "model", None)), so one leaf shards over both
        # axes and the engine's per-leaf grad contract scales by each.
        if pipeline_axis is not None and "encoder" in names:
            for suffix, spec in rules:
                if names[-len(suffix):] == suffix:
                    inner = tuple(spec) + (None,) * (leaf.ndim - 1 - len(spec))
                    return P(pipeline_axis, *inner)
            return P(pipeline_axis, *(None,) * (leaf.ndim - 1))
        matched = P()
        for suffix, spec in rules:
            if names[-len(suffix):] == suffix:
                matched = spec
                break
        if quant == "_q8_scale":
            last = tuple(matched)[-1] if len(tuple(matched)) else None
            return P(last) if last is not None else P()
        return matched

    return jax.tree_util.tree_map_with_path(spec_for, params)


def make_bert_pretraining_loss(model: BertForPreTraining):
    """LossFn for the engine: MLM (ignore targets < 0) + NSP.

    Batches: ``input_ids, attention_mask, token_type_ids, mlm_targets`` all
    ``[B, L]`` (sharded over "seq" when seq-parallel) and ``nsp_label [B]``.
    With ``cfg.seq_axis`` set, the MLM numerator/denominator are psum'd over
    the seq ring so every shard returns the *global* loss — required by the
    engine's seq-grad contract (train/step.py).
    """
    seq_axis = model.cfg.seq_axis
    moe = model.cfg.moe_experts > 0

    def loss_fn(params, model_state, batch, rng):
        # mutable=["intermediates"] is harmless for dense BERT (nothing is
        # sown; mods comes back empty) — one apply call for both paths.
        (mlm_logits, nsp_logits), mods = model.apply(
            {"params": params},
            batch["input_ids"],
            batch["attention_mask"],
            batch["token_type_ids"],
            train=True,
            rngs={"dropout": rng},
            mutable=["intermediates"],
        )
        if moe:
            # Leaves are scalars (per-layer module list; the pipelined
            # encoder's pre-averaged sow) or stacked [num_layers] arrays
            # (the nn.scan encoder) — jnp.mean handles both uniformly.
            aux_leaves = jax.tree.leaves(mods["intermediates"])
            moe_aux = sum(jnp.mean(a) for a in aux_leaves) / len(aux_leaves)
        num, den, correct = _mlm_stats(mlm_logits, batch, seq_axis)
        den = jnp.maximum(den, 1.0)
        mlm_loss = num / den
        nsp_loss = optax.softmax_cross_entropy_with_integer_labels(
            nsp_logits, batch["nsp_label"]
        ).mean()
        loss = mlm_loss + nsp_loss
        metrics = {
            "mlm_loss": mlm_loss,
            "nsp_loss": nsp_loss,
            "mlm_accuracy": correct / den,
        }
        if moe:
            loss = loss + model.cfg.moe_aux_weight * moe_aux
            metrics["moe_aux"] = moe_aux
        return loss, (model_state, metrics)

    return loss_fn
