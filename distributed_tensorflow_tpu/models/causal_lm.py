"""Decoder-only causal LM — the generative serving workload (ROADMAP item 2).

The transformer block is the BERT one (models/bert.py) reassembled for
decoding: post-LayerNorm residual blocks, learned positions, tanh-GELU FFN,
Megatron column/row tensor-parallel projections with the bias applied after
the psum, and a TIED LM head (logits against the word-embedding table, the
``mlm_transform -> ln -> attend`` recipe of ``BertForPreTraining._heads``).
Param leaf names intentionally match BERT's (``query``/``key``/``value``/
``out``/``intermediate``/``output`` + the post-psum ``*_bias`` twins), so
:func:`bert_param_specs`' suffix rules shard this model unchanged —
:func:`causal_param_specs` just delegates.

Three forwards share one param tree:

- ``__call__(input_ids, attention_mask) -> logits [B, L, V]`` — the full
  causally-masked forward: training loss, scoring, and the one-shot
  reference the serving decode path is tested against.
- ``prefill(input_ids, attention_mask) -> (logits, k [nl,B,L,h,d], v)`` —
  same math, but also returns every layer's projected K/V so the serving
  engine can scatter them into its slot cache (serve/engine.py
  ``CausalLMEngine``).
- ``decode_step(token [S], position [S], k_cache, v_cache) -> (logits [S,V],
  k_cache', v_cache')`` — ONE token per cache slot: embed at the slot's
  position, write the new K/V at ``position``, attend positions
  ``<= position``. Shapes are fixed by the slot count, so slot
  assignment/reuse never retraces (the "fixed pool of per-slot cache
  pages" contract).
- ``prefill_chunk(input_ids [B, C], positions [B, C], k_cache, v_cache) ->
  (logits [B, C, V], k_cache', v_cache')`` — a CHUNK of each row's prompt
  at arbitrary ABSOLUTE positions against per-row caches ``[nl, B, Lc, h,
  d]``: write the chunk's K/V at ``positions``, attend the cache causally
  (each query sees positions ``<= its own``). One method covers both
  prefix-cache suffix prefill (one chunk starting at ``cached_len``) and
  fixed-size chunked prefill of long prompts; padding lanes carry the
  out-of-range sentinel position ``Lc`` so their cache writes drop
  (``mode="drop"``) while attention/embedding use the clamped position.
- ``verify_step(tokens [S, K+1], positions [S, K+1], k_cache, v_cache) ->
  (logits [S, K+1, V], k_cache', v_cache')`` — speculative decoding's
  batched verify: score a slot's last verified token plus up to K draft
  tokens in ONE dispatch. Same math as ``prefill_chunk`` (it delegates),
  which is the point: column j's logits are bit-identical to what
  ``decode_step`` would produce after j accepted tokens, so greedy
  accept-matching preserves the exact non-speculative stream.

Numerics: both attention paths accumulate scores and context in f32 with
the same masking convention (fully-masked rows -> exactly 0), so a token
decoded step-by-step matches the full forward's argmax at the same
position — tests/test_serve_decode.py pins greedy parity exactly.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import linen as nn

from distributed_tensorflow_tpu.models.bert import _tp_psum, bert_param_specs
from distributed_tensorflow_tpu.models.quant import quantize_kv

_MASK_VALUE = -1e30


def _layer_cache(cache, i):
    """Slice layer ``i`` out of a stacked cache — plain ``[nl, ...]`` array
    or the quantized ``{"q", "s"}`` pytree (models/quant.py)."""
    if isinstance(cache, dict):
        return {"q": cache["q"][i], "s": cache["s"][i]}
    return cache[i]


def _stack_cache(layers):
    """Re-stack per-layer cache returns, preserving the quantized pytree
    structure when present."""
    if isinstance(layers[0], dict):
        return {
            "q": jnp.stack([c["q"] for c in layers]),
            "s": jnp.stack([c["s"] for c in layers]),
        }
    return jnp.stack(layers)


@dataclasses.dataclass(frozen=True)
class CausalLMConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 512
    dtype: jnp.dtype = jnp.float32
    # Megatron tensor parallelism, same contract as BertConfig: params are
    # created GLOBAL (init with model_parallel=1) and sliced by
    # causal_param_specs; inside shard_map the module builds local-head /
    # local-FFN projections and psums the row-parallel outputs.
    model_axis: str | None = None
    model_parallel: int = 1

    def __post_init__(self):
        if self.hidden_size % self.num_heads:
            raise ValueError(
                f"hidden_size {self.hidden_size} not divisible by "
                f"num_heads {self.num_heads}"
            )


def causal_lm_base(**overrides) -> CausalLMConfig:
    return CausalLMConfig(**overrides)


def _causal_attention(q, k, v, pad_mask):
    """Full-sequence causally-masked attention.

    ``q, k, v: [B, L, h, d]``; ``pad_mask: [B, L]`` True = real token.
    f32 score/context accumulation, fully-masked query rows -> exactly 0
    (same conventions as parallel/ring_attention.dense_attention).
    """
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("blhd,bkhd->bhlk", q, k, preferred_element_type=jnp.float32)
    s = s * scale
    l = q.shape[1]
    causal = jnp.tril(jnp.ones((l, l), bool))
    m = causal[None, None, :, :] & pad_mask[:, None, None, :]
    s = jnp.where(m, s, _MASK_VALUE)
    p = jax.nn.softmax(s, axis=-1) * m
    return jnp.einsum(
        "bhlk,bkhd->blhd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    ).astype(q.dtype)


def _cached_attention(q, k_cache, v_cache, position, k_scale=None,
                      v_scale=None):
    """One-token-per-slot attention against the slot cache.

    ``q: [S, h, d]``; caches ``[S, Lmax, h, d]``; ``position: [S]`` — the
    index the newest token was just written at (attends ``<= position``).
    ``k_scale``/``v_scale`` (``[S, Lmax]``) carry the int8 cache's
    per-position dequant factors: the k-scale multiplies the score matrix
    after the QK^T product and the v-scale folds into the softmax weights
    before the context product, so the dense cache is never materialized.
    """
    scale = q.shape[-1] ** -0.5
    kc = k_cache if k_scale is None else k_cache.astype(jnp.float32)
    s = jnp.einsum(
        "shd,slhd->shl", q, kc, preferred_element_type=jnp.float32
    )
    if k_scale is not None:
        s = s * k_scale[:, None, :]
    s = s * scale
    valid = jnp.arange(k_cache.shape[1])[None, :] <= position[:, None]
    s = jnp.where(valid[:, None, :], s, _MASK_VALUE)
    p = jax.nn.softmax(s, axis=-1) * valid[:, None, :]
    vc = v_cache
    if v_scale is not None:
        p = p * v_scale[:, None, :]
        vc = v_cache.astype(jnp.float32)
    return jnp.einsum(
        "shl,slhd->shd", p.astype(vc.dtype), vc,
        preferred_element_type=jnp.float32,
    ).astype(q.dtype)


def _chunk_attention(q, k_cache, v_cache, position, k_scale=None,
                     v_scale=None):
    """Chunk-of-queries attention against per-row caches.

    ``q: [B, C, h, d]``; caches ``[B, Lc, h, d]``; ``position: [B, C]`` —
    the (clamped) cache index each query was written at; each attends
    ``<= its own position``. Same f32 score/context accumulation and
    exactly-0 masking as ``_cached_attention``, so a prompt prefilled in
    chunks matches the full forward's argmax position-for-position.
    Cache positions beyond a row's written length hold zeros or a prior
    occupant's values — finite either way, and their softmax weight is
    exactly 0 under the causal mask, so they never reach the output.
    ``k_scale``/``v_scale`` (``[B, Lc]``): the int8 cache's per-position
    dequant factors, applied in the SAME factored order as
    ``_cached_attention`` so verify columns stay bit-identical to the
    decode steps they replace under quantization.
    """
    scale = q.shape[-1] ** -0.5
    kc = k_cache if k_scale is None else k_cache.astype(jnp.float32)
    s = jnp.einsum(
        "bchd,blhd->bhcl", q, kc, preferred_element_type=jnp.float32
    )
    if k_scale is not None:
        s = s * k_scale[:, None, None, :]
    s = s * scale
    valid = (
        jnp.arange(k_cache.shape[1])[None, None, :]
        <= position[:, :, None]
    )  # [B, C, Lc]
    m = valid[:, None, :, :]
    s = jnp.where(m, s, _MASK_VALUE)
    p = jax.nn.softmax(s, axis=-1) * m
    vc = v_cache
    if v_scale is not None:
        p = p * v_scale[:, None, None, :]
        vc = v_cache.astype(jnp.float32)
    return jnp.einsum(
        "bhcl,blhd->bchd", p.astype(vc.dtype), vc,
        preferred_element_type=jnp.float32,
    ).astype(q.dtype)


class CausalSelfAttention(nn.Module):
    """The BERT attention block, setup-style so the full and cached paths
    share params. Column-parallel Q/K/V over local heads, row-parallel out
    projection with the bias added once, after the psum, then post-LN."""

    cfg: CausalLMConfig

    def setup(self):
        cfg = self.cfg
        head_dim = cfg.hidden_size // cfg.num_heads
        local_heads = cfg.num_heads // cfg.model_parallel
        init = nn.initializers.normal(0.02)
        dense = lambda: nn.DenseGeneral(  # noqa: E731
            (local_heads, head_dim), dtype=cfg.dtype, kernel_init=init
        )
        self.query, self.key, self.value = dense(), dense(), dense()
        self.out = nn.DenseGeneral(
            cfg.hidden_size, axis=(-2, -1), use_bias=False,
            dtype=cfg.dtype, kernel_init=init,
        )
        self.out_bias = self.param(
            "out_bias", nn.initializers.zeros_init(), (cfg.hidden_size,)
        )
        self.ln = nn.LayerNorm(epsilon=1e-12, dtype=cfg.dtype)

    def _finish(self, x, ctx):
        out = _tp_psum(self.cfg, self.out(ctx))
        out = out + self.out_bias.astype(out.dtype)
        return self.ln(x + out)

    def __call__(self, x, pad_mask):
        q, k, v = self.query(x), self.key(x), self.value(x)
        ctx = _causal_attention(q, k, v, pad_mask)
        # K/V returned pre-attention: prefill scatters exactly these into
        # the slot cache, so the decode path attends identical values.
        return self._finish(x, ctx), k, v

    def decode(self, x, k_cache, v_cache, position):
        # position == Lmax marks an idle lane: its scatter drops (writing
        # anywhere could corrupt a mid-chunk-prefill slot's pages) and its
        # attention clamps — the lane's output is garbage nobody reads.
        q, k, v = self.query(x), self.key(x), self.value(x)  # [S, h, d]
        idx = jnp.arange(x.shape[0])
        if isinstance(k_cache, dict):
            # int8 KV mode: quantize the new token per slot at the write,
            # attend with the factored per-position scales.
            qk, sk = quantize_kv(k)
            qv, sv = quantize_kv(v)
            k_cache = {
                "q": k_cache["q"].at[idx, position].set(qk, mode="drop"),
                "s": k_cache["s"].at[idx, position].set(sk, mode="drop"),
            }
            v_cache = {
                "q": v_cache["q"].at[idx, position].set(qv, mode="drop"),
                "s": v_cache["s"].at[idx, position].set(sv, mode="drop"),
            }
            ctx = _cached_attention(
                q, k_cache["q"], v_cache["q"],
                jnp.minimum(position, k_cache["q"].shape[1] - 1),
                k_scale=k_cache["s"], v_scale=v_cache["s"],
            )
            return self._finish(x, ctx), k_cache, v_cache
        k_cache = k_cache.at[idx, position].set(
            k.astype(k_cache.dtype), mode="drop"
        )
        v_cache = v_cache.at[idx, position].set(
            v.astype(v_cache.dtype), mode="drop"
        )
        ctx = _cached_attention(
            q, k_cache, v_cache,
            jnp.minimum(position, k_cache.shape[1] - 1),
        )
        return self._finish(x, ctx), k_cache, v_cache

    def prefill_chunk(self, x, positions, k_cache, v_cache):
        # x [B, C, H]; positions [B, C] absolute (sentinel == Lc on
        # padding lanes -> the scatter drops); caches [B, Lc, h, d].
        q, k, v = self.query(x), self.key(x), self.value(x)  # [B, C, h, d]
        rows = jnp.arange(x.shape[0])[:, None]
        if isinstance(k_cache, dict):
            # int8 KV mode, chunk-wise: per-(row, position) scales written
            # with the pages keep verify columns bit-identical to the
            # decode steps they stand in for (same quantize-at-write, same
            # factored dequant order).
            qk, sk = quantize_kv(k)
            qv, sv = quantize_kv(v)
            k_cache = {
                "q": k_cache["q"].at[rows, positions].set(qk, mode="drop"),
                "s": k_cache["s"].at[rows, positions].set(sk, mode="drop"),
            }
            v_cache = {
                "q": v_cache["q"].at[rows, positions].set(qv, mode="drop"),
                "s": v_cache["s"].at[rows, positions].set(sv, mode="drop"),
            }
            ctx = _chunk_attention(
                q, k_cache["q"], v_cache["q"],
                jnp.minimum(positions, k_cache["q"].shape[1] - 1),
                k_scale=k_cache["s"], v_scale=v_cache["s"],
            )
            return self._finish(x, ctx), k_cache, v_cache
        k_cache = k_cache.at[rows, positions].set(
            k.astype(k_cache.dtype), mode="drop"
        )
        v_cache = v_cache.at[rows, positions].set(
            v.astype(v_cache.dtype), mode="drop"
        )
        ctx = _chunk_attention(
            q, k_cache, v_cache,
            jnp.minimum(positions, k_cache.shape[1] - 1),
        )
        return self._finish(x, ctx), k_cache, v_cache


class CausalLmLayer(nn.Module):
    """Attention + FFN, both post-LN — BertLayer's shape with the cached
    decode twin. Leaf names (``intermediate``/``output``/``output_bias``)
    keep bert_param_specs' Megatron suffix rules applicable."""

    cfg: CausalLMConfig

    def setup(self):
        cfg = self.cfg
        init = nn.initializers.normal(0.02)
        self.attention = CausalSelfAttention(cfg)
        self.intermediate = nn.Dense(
            cfg.intermediate_size // cfg.model_parallel,
            dtype=cfg.dtype, kernel_init=init,
        )
        self.output = nn.Dense(
            cfg.hidden_size, use_bias=False, dtype=cfg.dtype, kernel_init=init
        )
        self.output_bias = self.param(
            "output_bias", nn.initializers.zeros_init(), (cfg.hidden_size,)
        )
        self.ln = nn.LayerNorm(epsilon=1e-12, dtype=cfg.dtype)

    def _ffn(self, x):
        y = nn.gelu(self.intermediate(x), approximate=True)
        y = _tp_psum(self.cfg, self.output(y))
        y = y + self.output_bias.astype(y.dtype)
        return self.ln(x + y)

    def __call__(self, x, pad_mask):
        x, k, v = self.attention(x, pad_mask)
        return self._ffn(x), k, v

    def decode(self, x, k_cache, v_cache, position):
        x, k_cache, v_cache = self.attention.decode(
            x, k_cache, v_cache, position
        )
        return self._ffn(x), k_cache, v_cache

    def prefill_chunk(self, x, positions, k_cache, v_cache):
        x, k_cache, v_cache = self.attention.prefill_chunk(
            x, positions, k_cache, v_cache
        )
        return self._ffn(x), k_cache, v_cache


class CausalLM(nn.Module):
    """Decoder-only LM over :class:`CausalLmLayer` blocks with a tied head.

    ``__call__`` is the one-shot reference; ``prefill``/``decode_step`` are
    the serving pair (see module docstring for shapes).
    """

    cfg: CausalLMConfig

    def setup(self):
        cfg = self.cfg
        init = nn.initializers.normal(0.02)
        self.word = nn.Embed(
            cfg.vocab_size, cfg.hidden_size, embedding_init=init,
            dtype=cfg.dtype,
        )
        self.position = nn.Embed(
            cfg.max_position, cfg.hidden_size, embedding_init=init,
            dtype=cfg.dtype,
        )
        self.embed_ln = nn.LayerNorm(epsilon=1e-12, dtype=cfg.dtype)
        self.layers = [
            CausalLmLayer(cfg, name=f"layer_{i}")
            for i in range(cfg.num_layers)
        ]
        self.lm_transform = nn.Dense(
            cfg.hidden_size, dtype=cfg.dtype, kernel_init=init
        )
        self.lm_ln = nn.LayerNorm(epsilon=1e-12, dtype=cfg.dtype)
        self.lm_bias = self.param(
            "lm_bias", nn.initializers.zeros_init(), (cfg.vocab_size,)
        )

    def _embed(self, token_ids, positions):
        return self.embed_ln(self.word(token_ids) + self.position(positions))

    def _head(self, h):
        # Tied decoder against the embedding table (BertForPreTraining's
        # _heads recipe): transform -> LN -> attend + bias.
        h = self.lm_ln(nn.gelu(self.lm_transform(h), approximate=True))
        return self.word.attend(h) + self.lm_bias.astype(self.cfg.dtype)

    def __call__(self, input_ids, attention_mask):
        l = input_ids.shape[1]
        x = self._embed(input_ids, jnp.arange(l)[None, :])
        for layer in self.layers:
            x, _, _ = layer(x, attention_mask)
        return self._head(x)

    def prefill(self, input_ids, attention_mask):
        l = input_ids.shape[1]
        x = self._embed(input_ids, jnp.arange(l)[None, :])
        ks, vs = [], []
        for layer in self.layers:
            x, k, v = layer(x, attention_mask)
            ks.append(k)
            vs.append(v)
        return self._head(x), jnp.stack(ks), jnp.stack(vs)

    def decode_step(self, token, position, k_cache, v_cache):
        # Clamp for the position-embedding lookup only; the raw (possibly
        # idle-lane sentinel) position drives the layers' dropped writes.
        x = self._embed(
            token, jnp.minimum(position, self.cfg.max_position - 1)
        )  # [S, H]
        new_k, new_v = [], []
        for i, layer in enumerate(self.layers):
            x, kc, vc = layer.decode(
                x, _layer_cache(k_cache, i), _layer_cache(v_cache, i),
                position,
            )
            new_k.append(kc)
            new_v.append(vc)
        return self._head(x), _stack_cache(new_k), _stack_cache(new_v)

    def prefill_chunk(self, input_ids, positions, k_cache, v_cache):
        # Absolute-position chunk prefill against the slot cache: caches
        # ahead of a row's written length may hold garbage, but the causal
        # mask gives them exactly-0 weight and every such page is
        # re-written (by this row's later chunks or decode steps) before
        # anything attends it — the same dead-store argument decode_step
        # relies on for slot reuse. Positions are clamped for embedding /
        # attention; raw (possibly sentinel) positions drive the writes.
        Lc = (k_cache["q"] if isinstance(k_cache, dict) else k_cache).shape[2]
        x = self._embed(input_ids, jnp.minimum(positions, Lc - 1))
        new_k, new_v = [], []
        for i, layer in enumerate(self.layers):
            x, kc, vc = layer.prefill_chunk(
                x, positions, _layer_cache(k_cache, i),
                _layer_cache(v_cache, i)
            )
            new_k.append(kc)
            new_v.append(vc)
        return self._head(x), _stack_cache(new_k), _stack_cache(new_v)

    def verify_step(self, tokens, positions, k_cache, v_cache):
        # Speculative-decoding verify over the slot table: [S, K+1] tokens
        # at absolute positions against per-slot caches. Column 0 is each
        # slot's last verified token re-scored at its current position;
        # columns 1..d are drafts; dead columns carry the sentinel position
        # Lc so their writes drop. This IS prefill_chunk's contract with
        # C = K+1 — delegating (rather than re-deriving the masking) keeps
        # the `valid = pos <= position` and `mode="drop"` invariants in one
        # place. K/V written for columns past the accepted prefix sit
        # beyond the rolled-back slot position: masked dead, overwritten by
        # the slot's next real tokens — rollback costs nothing.
        return self.prefill_chunk(tokens, positions, k_cache, v_cache)


def sample_tokens(logits, temperature, seed, step):
    """Per-row next-token choice: greedy at ``temperature == 0``, seeded
    categorical otherwise.

    The sampling key is ``fold_in(PRNGKey(seed), step)`` with ``step`` the
    ABSOLUTE position being generated — a function of the request alone,
    never of its batchmates or slot, so a request decoded mid-flight draws
    the identical token stream it would draw solo (the determinism contract
    tests/test_serve_decode.py pins).
    """
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def one(row, t, s, c):
        key = jax.random.fold_in(jax.random.PRNGKey(s), c)
        scaled = row.astype(jnp.float32) / jnp.maximum(t, 1e-6)
        return jax.random.categorical(key, scaled).astype(jnp.int32)

    sampled = jax.vmap(one)(logits, temperature, seed, step)
    return jnp.where(temperature > 0.0, sampled, greedy)


def causal_param_specs(params, model_axis: str | None = "model"):
    """PartitionSpec tree for Megatron-TP sharding of the causal LM.

    The block reuses BERT's leaf names, so this is exactly
    :func:`bert_param_specs`' suffix rules with the expert/pipeline
    families off — embeddings, LayerNorms, post-psum biases, and the tied
    head stay replicated."""
    return bert_param_specs(
        params, model_axis=model_axis, expert_axis=None, pipeline_axis=None
    )


def _next_token_stats(logits, batch):
    """Shift-by-one CE sums: position t's logits score token t+1; pad
    positions and the final position carry zero weight. Returns ``(ce_sum,
    weight_sum, correct_sum)`` in f32 from the storage dtype — the same
    on-the-fly recipe as the BERT loss (_mlm_stats)."""
    targets = batch["input_ids"][:, 1:]
    logits = logits[:, :-1]
    weights = batch["attention_mask"][:, 1:].astype(jnp.float32)
    m = jax.lax.stop_gradient(jnp.max(logits, axis=-1, keepdims=True))
    shifted = logits.astype(jnp.float32) - m.astype(jnp.float32)
    lse = jnp.log(jnp.sum(jnp.exp(shifted), axis=-1)) + m[..., 0].astype(
        jnp.float32
    )
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    ce_sum = jnp.sum((lse - tgt.astype(jnp.float32)) * weights)
    correct = jnp.sum(
        (jnp.argmax(logits, axis=-1) == targets).astype(jnp.float32) * weights
    )
    return ce_sum, jnp.sum(weights), correct


def make_causal_lm_loss(model: CausalLM):
    """Next-token cross-entropy LossFn for the training engine over
    ``{"input_ids" [B, L], "attention_mask" [B, L]}`` batches."""

    def loss_fn(params, model_state, batch, rng):
        del rng  # no dropout in the decoder blocks
        logits = model.apply(
            {"params": params}, batch["input_ids"], batch["attention_mask"]
        )
        ce_sum, den, correct = _next_token_stats(logits, batch)
        den = jnp.maximum(den, 1.0)
        loss = ce_sum / den
        return loss, (model_state, {
            "lm_loss": loss,
            "lm_accuracy": correct / den,
        })

    return loss_fn


def make_causal_lm_eval_metrics(model: CausalLM):
    """Eval ``metric_fn`` for ``make_eval_step``: next-token loss and
    accuracy as ``(num, den)`` pairs so the eval step reduces them as
    global ratios over the DP axes (variable pad counts per shard)."""

    def metric_fn(params, model_state, batch):
        del model_state
        logits = model.apply(
            {"params": params}, batch["input_ids"], batch["attention_mask"]
        )
        ce_sum, den, correct = _next_token_stats(logits, batch)
        return {"lm_loss": (ce_sum, den), "lm_accuracy": (correct, den)}

    return metric_fn
