"""ResNet family: ResNet-20 (CIFAR-10) and ResNet-50 (ImageNet).

Parity targets (SURVEY.md §2 workload rows):

- ResNet-20 is the reference's 2-worker ``SyncReplicasOptimizer`` PS workload
  (BASELINE.json:8) — the CIFAR-style residual net of He et al. 2015 §4.2:
  three stages of n=3 basic blocks at widths 16/32/64, ~0.27M params.
- ResNet-50 is the north-star benchmark model (BASELINE.json:2,5,9): the
  bottleneck ImageNet net, ~25.6M params, trained 8-worker sync-allreduce in
  the reference (SURVEY.md §3d) — here sync DP via ``lax.pmean`` in the
  compiled step.

TPU-first design notes:

- NHWC layout and 3x3/1x1 convs map directly onto the MXU via XLA:TPU's
  convolution tiling; compute dtype is a knob (bf16 recommended) while params
  and BN statistics stay f32.
- BatchNorm uses flax's ``batch_stats`` collection. Cross-replica stat
  handling follows the engine contract: the train step pmeans the updated
  ``batch_stats`` across the DP axes every step (train/step.py), which keeps
  replicas bit-identical — the invariant of SURVEY.md §3d. Per-shard ghost
  batch norm is therefore the normalization semantics (SURVEY.md §7
  hard-part 5), matching per-worker BN in the reference's multi-worker runs.
  Quantified (r5): 8-way-DP ResNet-20 vs the 1-device 8x-batch trajectory
  measures 0.040 max-abs param drift / 0.033 loss drift after 20 steps at
  global batch 128 (per-shard BN batches of 16); EMA means still match the
  full-batch run (mean of equal shard means == global mean) — pinned with
  2x-margin tolerances by tests/test_resnet.py::test_ghost_bn_drift_quantified.
- ``kernel_init`` is He-normal like the reference era's MSRA init.
"""

from __future__ import annotations

from collections.abc import Callable, Sequence
from functools import partial

import jax
import jax.numpy as jnp
from flax import linen as nn

ModuleDef = Callable[..., nn.Module]


class PointwiseConv(nn.Module):
    """1x1 convolution as an explicit MXU matmul, optionally Pallas-backed.

    Mathematically identical to ``nn.Conv(features, (1, 1))`` (same
    ``kernel`` param name/shape, so param trees and checkpoints are
    interchangeable). A strided 1x1 conv reads only the top-left pixel of
    each stride window, so ``strides=2`` is exactly a spatial slice
    followed by the matmul.

    ``backend="dot"`` is the r2 experiment: XLA:TPU canonicalizes the dot
    back into convolution HLO and the full-model step is unchanged
    (docs/PERF.md "dead ends").  ``backend="pallas"`` is the r3 fix: the
    forward stays an XLA dot (its fused BN+ReLU producer chain already
    saturates bandwidth) but the backward is a ``jax.custom_vjp`` calling
    Pallas matmul kernels, which XLA *cannot* re-canonicalize — this is
    what rescues the 8-25 TF/s dgrad/wgrad convs in the trace
    (ops/pointwise_conv.py).
    """

    features: int
    strides: tuple[int, int] | int = 1
    use_bias: bool = False
    dtype: jnp.dtype = jnp.float32
    kernel_init: Callable = nn.initializers.he_normal()
    backend: str = "dot"  # "dot" | "pallas"

    @nn.compact
    def __call__(self, x):
        from distributed_tensorflow_tpu.ops.pointwise_conv import (
            pointwise_conv_n64,
            pointwise_matmul,
        )

        s = self.strides if isinstance(self.strides, int) else self.strides[0]
        if s > 1:
            x = x[:, ::s, ::s, :]
        cin = x.shape[-1]
        kernel = self.param(
            "kernel", self.kernel_init, (1, 1, cin, self.features), jnp.float32
        )
        # Flattening the spatial dims is layout-preserving (C stays
        # minormost); with backend="dot" XLA canonicalizes the dot back to a
        # 1x1 convolution anyway (verified on the r2 HLO), with
        # backend="pallas" the custom-vjp boundary prevents exactly that for
        # the backward ops.
        b, h, w, _ = x.shape
        k2 = kernel[0, 0].astype(self.dtype)
        if self.backend == "pallas" and self.features == 64 and cin >= 128:
            # N=64 outputs live in XLA's B-minor layout; the dedicated
            # layout-native dgrad kernel avoids the boundary relayout that
            # sinks the generic path here (ops/pointwise_conv.py).
            y = pointwise_conv_n64(x.astype(self.dtype), k2)
        elif self.backend == "pallas":
            # Flatten in H,W,B,C order: XLA:TPU's layout assignment places
            # these conv activations as {3,0,2,1} (physically H,W,B,C), so
            # this transpose+reshape lowers to a bitcast at the Pallas
            # boundary — flattening in B,H,W,C order instead forces a
            # materialized relayout copy per call (measured +18 ms/step on
            # the b=128 ResNet-50 trace).
            x2 = x.astype(self.dtype).transpose(1, 2, 0, 3).reshape(h * w * b, cin)
            y = pointwise_matmul(x2, k2)
            y = y.reshape(h, w, b, self.features).transpose(2, 0, 1, 3)
        else:
            x2 = x.astype(self.dtype).reshape(b * h * w, cin)
            y = jnp.dot(x2, k2).reshape(b, h, w, self.features)
        if self.use_bias:
            bias = self.param(
                "bias", nn.initializers.zeros_init(), (self.features,), jnp.float32
            )
            y = y + bias.astype(self.dtype)
        return y


class BasicBlock(nn.Module):
    """3x3 + 3x3 residual block (CIFAR ResNets)."""

    filters: int
    strides: int = 1
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), strides=(self.strides,) * 2)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        # Zero-init'd final-BN scale: residual branches start as identity,
        # the standard large-batch ResNet trick (Goyal et al.) — pure win on
        # sync-DP convergence, no API cost.
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), strides=(self.strides,) * 2, name="proj"
            )(residual)
            residual = self.norm(name="proj_bn")(residual)
        return nn.relu(y + residual)


class BottleneckBlock(nn.Module):
    """1x1 down / 3x3 / 1x1 up (x4) bottleneck block (ImageNet ResNets).

    ``conv1x1`` (when set) handles the three pointwise convs — the ResNet
    wires :class:`PointwiseConv` with the Pallas backward here on TPU.
    ``fused`` + ``train`` switch qualified 1x1+BN(+ReLU) units onto the
    fully-fused Pallas backward (ops/fused_conv_bn.py — the r4 kernel
    family that absorbs the ReLU mask and BN-backward reductions XLA fuses
    into its dgrad convs, docs/PERF.md r3 conclusion). Explicit layer names
    keep the param tree identical to the historical auto-named ``nn.Conv``
    layout (Conv_0/BatchNorm_0/...), so checkpoints are interchangeable
    across all backends.
    """

    filters: int
    strides: int = 1
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    conv1x1: ModuleDef | None = None
    fused: bool = False
    train: bool = False
    dtype: jnp.dtype = jnp.float32

    def _c1(self, features: int, strides: int = 1, name: str | None = None):
        if self.conv1x1 is not None:
            return self.conv1x1(features, strides=strides, name=name)
        return self.conv(features, (1, 1), strides=(strides,) * 2, name=name)

    def _unit(self, x, features, strides, conv_name, bn_name, relu, zero_bn):
        """One conv1x1 -> BN (-> ReLU) unit; fused when shapes qualify."""
        from distributed_tensorflow_tpu.ops.fused_conv_bn import (
            fused_supported,
            fused_unit,
        )

        b, h, w, cin = x.shape
        # Ceil division: x[:, ::s, ::s] keeps ceil(h/s) rows, not floor.
        m = b * (-(-h // strides)) * (-(-w // strides))
        scale_init = (
            nn.initializers.zeros_init() if zero_bn
            else nn.initializers.ones_init()
        )
        # Strided (proj) units DO fuse: the slice lowers to gather/scatter
        # pairs around the custom-vjp boundary, but gating them off
        # measured WORSE in-step (53.5 vs 50.9 ms b=128) — the fused
        # backward win on the proj matmuls exceeds the slice tax.
        if self.fused and self.train and fused_supported(m, cin, features):
            return fused_unit(
                x,
                features,
                relu=relu,
                conv_name=conv_name,
                bn_name=bn_name,
                dtype=self.dtype,
                strides=strides,
                scale_init=scale_init,
            )
        y = self._c1(features, strides=strides, name=conv_name)(x)
        kw = {"scale_init": scale_init} if zero_bn else {}
        y = self.norm(name=bn_name, **kw)(y)
        return nn.relu(y) if relu else y

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self._unit(x, self.filters, 1, "Conv_0", "BatchNorm_0",
                       relu=True, zero_bn=False)
        y = self.conv(
            self.filters, (3, 3), strides=(self.strides,) * 2, name="Conv_1"
        )(y)
        y = self.norm(name="BatchNorm_1")(y)
        y = nn.relu(y)
        y = self._unit(y, self.filters * 4, 1, "Conv_2", "BatchNorm_2",
                       relu=False, zero_bn=True)
        if residual.shape != y.shape:
            residual = self._unit(
                residual, self.filters * 4, self.strides, "proj", "proj_bn",
                relu=False, zero_bn=False,
            )
        return nn.relu(y + residual)


class SpaceToDepthStem(nn.Module):
    """The ImageNet 7x7/s2 stem conv as a 2x2-space-to-depth 4x4/s1 conv.

    The classic MLPerf TPU ResNet transform: a stride-2 7x7 conv on 3
    channels maps terribly onto the MXU (contraction of only 7·7·3 = 147,
    strided input reads). Folding a 2x2 pixel block into channels turns the
    input into ``[B, H/2, W/2, 12]`` and the SAME math into an unstrided
    4x4 conv (contraction 4·4·12 = 192, dense reads).

    Bit-exact reparameterization, not an approximation: the 7x7 kernel is
    zero-padded to 8x8 (one leading row/col — taps that would read outside
    the original pad-3 window) and regrouped to ``[4, 4, 12, F]``; block
    padding (2, 1) reproduces the original symmetric pad-3. The param is
    the original ``kernel [7,7,3,F]`` (same name/shape as the plain conv
    stem), so checkpoints are interchangeable.
    """

    features: int = 64
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, h, w, c = x.shape
        if h % 2 or w % 2:
            raise ValueError(f"space-to-depth stem needs even H/W, got {(h, w)}")
        kernel = self.param(
            "kernel",
            nn.initializers.he_normal(),
            (7, 7, c, self.features),
            jnp.float32,
        )
        x = (
            x.reshape(b, h // 2, 2, w // 2, 2, c)
            .transpose(0, 1, 3, 2, 4, 5)
            .reshape(b, h // 2, w // 2, 4 * c)
        )
        k8 = jnp.pad(kernel.astype(self.dtype), ((1, 0), (1, 0), (0, 0), (0, 0)))
        k2 = (
            k8.reshape(4, 2, 4, 2, c, self.features)
            .transpose(0, 2, 1, 3, 4, 5)
            .reshape(4, 4, 4 * c, self.features)
        )
        return jax.lax.conv_general_dilated(
            x,
            k2,
            (1, 1),
            [(2, 1), (2, 1)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )


class ResNet(nn.Module):
    """Generic residual network over NHWC inputs.

    ``stem="imagenet"`` → 7x7/2 conv + 3x3/2 maxpool (ResNet-50 et al.),
    computed via :class:`SpaceToDepthStem` (same math, same params, MXU-
    friendly layout); ``stem="cifar"`` → single 3x3 conv (ResNet-20/32/...).
    """

    stage_sizes: Sequence[int]
    block: ModuleDef
    num_filters: int = 64
    num_classes: int = 1000
    stem: str = "imagenet"
    stem_s2d: bool = True
    remat: bool = False  # rematerialize blocks: trade (cheap) FLOPs for HBM
    # 1x1-conv path: "conv" (default) = nn.Conv everywhere — the fastest
    # UNFUSED configuration. "pallas" = custom-vjp 1x1s with Pallas dgrad
    # kernels (ops/pointwise_conv.py): 3-5x faster per-op on K>=128 shapes
    # but a net step-level LOSS (56.5 vs 47.9 ms/step at b=128), because
    # breaking the graph un-fuses XLA's relu/BN-backward epilogues from the
    # surrounding convs — the full study is in docs/PERF.md r3. "fused" =
    # the r4 answer: whole conv1x1+BN(+ReLU) units with a fully-fused
    # Pallas backward that ABSORBS those epilogues (mask + BN-bwd
    # reductions ride the dgrad/wgrad kernels, ops/fused_conv_bn.py);
    # C=64 shapes (stage 1) keep the XLA path per the layout study.
    pw_backend: str = "conv"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        conv = partial(
            nn.Conv,
            use_bias=False,
            dtype=self.dtype,
            kernel_init=nn.initializers.he_normal(),
        )
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )
        x = x.astype(self.dtype)
        if self.stem == "imagenet":
            # Explicit symmetric padding (pad-3 conv, pad-1 pool): SAME would
            # compute asymmetric (2,3)/(0,1) pads on stride-2 and silently
            # shift activations vs. the canonical ResNet-50.
            if self.stem_s2d and x.shape[1] % 2 == 0 and x.shape[2] % 2 == 0:
                x = SpaceToDepthStem(
                    self.num_filters, dtype=self.dtype, name="stem_conv"
                )(x)
            else:
                x = conv(
                    self.num_filters,
                    (7, 7),
                    strides=(2, 2),
                    padding=[(3, 3), (3, 3)],
                    name="stem_conv",
                )(x)
            x = norm(name="stem_bn")(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        elif self.stem == "cifar":
            x = conv(self.num_filters, (3, 3), name="stem_conv")(x)
            x = norm(name="stem_bn")(x)
            x = nn.relu(x)
        else:
            raise ValueError(f"unknown stem {self.stem!r}")
        use_pallas = self.pw_backend == "pallas"
        conv1x1 = (
            partial(PointwiseConv, dtype=self.dtype, backend="pallas")
            if use_pallas and self.block is BottleneckBlock
            else None
        )
        block_cls = nn.remat(self.block) if self.remat else self.block
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                kwargs = (
                    {
                        "conv1x1": conv1x1,
                        "fused": self.pw_backend == "fused",
                        "train": train,
                        "dtype": self.dtype,
                    }
                    if self.block is BottleneckBlock
                    else {}
                )
                x = block_cls(
                    self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    **kwargs,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        # Head computes in f32: the logits/loss edge is where bf16 hurts.
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)


def ResNet20(num_classes: int = 10, dtype=jnp.float32) -> ResNet:
    """He et al. CIFAR ResNet, n=3: 6n+2 = 20 layers, ~0.27M params."""
    return ResNet(
        stage_sizes=(3, 3, 3),
        block=BasicBlock,
        num_filters=16,
        num_classes=num_classes,
        stem="cifar",
        dtype=dtype,
    )


def ResNet50(num_classes: int = 1000, dtype=jnp.float32) -> ResNet:
    """Bottleneck ImageNet ResNet-50, ~25.6M params — the north-star model."""
    return ResNet(
        stage_sizes=(3, 4, 6, 3),
        block=BottleneckBlock,
        num_filters=64,
        num_classes=num_classes,
        stem="imagenet",
        dtype=dtype,
    )
