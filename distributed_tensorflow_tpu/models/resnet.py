"""ResNet family: ResNet-20 (CIFAR-10) and ResNet-50 (ImageNet).

Parity targets (SURVEY.md §2 workload rows):

- ResNet-20 is the reference's 2-worker ``SyncReplicasOptimizer`` PS workload
  (BASELINE.json:8) — the CIFAR-style residual net of He et al. 2015 §4.2:
  three stages of n=3 basic blocks at widths 16/32/64, ~0.27M params.
- ResNet-50 is the north-star benchmark model (BASELINE.json:2,5,9): the
  bottleneck ImageNet net, ~25.6M params, trained 8-worker sync-allreduce in
  the reference (SURVEY.md §3d) — here sync DP via ``lax.pmean`` in the
  compiled step.

TPU-first design notes:

- NHWC layout and 3x3/1x1 convs map directly onto the MXU via XLA:TPU's
  convolution tiling; compute dtype is a knob (bf16 recommended) while params
  and BN statistics stay f32.
- BatchNorm uses flax's ``batch_stats`` collection. Cross-replica stat
  handling follows the engine contract: the train step pmeans the updated
  ``batch_stats`` across the DP axes every step (train/step.py), which keeps
  replicas bit-identical — the invariant of SURVEY.md §3d. Per-shard ghost
  batch norm is therefore the normalization semantics (SURVEY.md §7
  hard-part 5), matching per-worker BN in the reference's multi-worker runs.
- ``kernel_init`` is He-normal like the reference era's MSRA init.
"""

from __future__ import annotations

from collections.abc import Callable, Sequence
from functools import partial

import jax.numpy as jnp
from flax import linen as nn

ModuleDef = Callable[..., nn.Module]


class BasicBlock(nn.Module):
    """3x3 + 3x3 residual block (CIFAR ResNets)."""

    filters: int
    strides: int = 1
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), strides=(self.strides,) * 2)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        # Zero-init'd final-BN scale: residual branches start as identity,
        # the standard large-batch ResNet trick (Goyal et al.) — pure win on
        # sync-DP convergence, no API cost.
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), strides=(self.strides,) * 2, name="proj"
            )(residual)
            residual = self.norm(name="proj_bn")(residual)
        return nn.relu(y + residual)


class BottleneckBlock(nn.Module):
    """1x1 down / 3x3 / 1x1 up (x4) bottleneck block (ImageNet ResNets)."""

    filters: int
    strides: int = 1
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), strides=(self.strides,) * 2)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), strides=(self.strides,) * 2, name="proj"
            )(residual)
            residual = self.norm(name="proj_bn")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """Generic residual network over NHWC inputs.

    ``stem="imagenet"`` → 7x7/2 conv + 3x3/2 maxpool (ResNet-50 et al.);
    ``stem="cifar"``    → single 3x3 conv (ResNet-20/32/...).
    """

    stage_sizes: Sequence[int]
    block: ModuleDef
    num_filters: int = 64
    num_classes: int = 1000
    stem: str = "imagenet"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        conv = partial(
            nn.Conv,
            use_bias=False,
            dtype=self.dtype,
            kernel_init=nn.initializers.he_normal(),
        )
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )
        x = x.astype(self.dtype)
        if self.stem == "imagenet":
            # Explicit symmetric padding (pad-3 conv, pad-1 pool): SAME would
            # compute asymmetric (2,3)/(0,1) pads on stride-2 and silently
            # shift activations vs. the canonical ResNet-50.
            x = conv(
                self.num_filters,
                (7, 7),
                strides=(2, 2),
                padding=[(3, 3), (3, 3)],
                name="stem_conv",
            )(x)
            x = norm(name="stem_bn")(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        elif self.stem == "cifar":
            x = conv(self.num_filters, (3, 3), name="stem_conv")(x)
            x = norm(name="stem_bn")(x)
            x = nn.relu(x)
        else:
            raise ValueError(f"unknown stem {self.stem!r}")
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block(
                    self.num_filters * 2**i, strides=strides, conv=conv, norm=norm
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        # Head computes in f32: the logits/loss edge is where bf16 hurts.
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)


def ResNet20(num_classes: int = 10, dtype=jnp.float32) -> ResNet:
    """He et al. CIFAR ResNet, n=3: 6n+2 = 20 layers, ~0.27M params."""
    return ResNet(
        stage_sizes=(3, 3, 3),
        block=BasicBlock,
        num_filters=16,
        num_classes=num_classes,
        stem="cifar",
        dtype=dtype,
    )


def ResNet50(num_classes: int = 1000, dtype=jnp.float32) -> ResNet:
    """Bottleneck ImageNet ResNet-50, ~25.6M params — the north-star model."""
    return ResNet(
        stage_sizes=(3, 4, 6, 3),
        block=BottleneckBlock,
        num_filters=64,
        num_classes=num_classes,
        stem="imagenet",
        dtype=dtype,
    )
