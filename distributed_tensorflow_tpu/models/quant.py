"""Post-training int8 quantization for the serving path (ROADMAP item 4).

Two independent numerics modes, both opt-in per engine and both invisible
to training (checkpoints stay fp32 on disk):

**Weights** — per-output-channel absmax int8. Every Dense/DenseGeneral
``kernel`` leaf (ndim >= 2) is replaced IN PLACE in the param tree by a
two-leaf dict ``{"_q8": int8[kernel.shape], "_q8_scale": f32[out]}`` where
the scale is one absmax per trailing-axis channel (``max|w| / 127`` over
every axis but the last). Embeddings, biases, LayerNorms, the router, and
the MoE expert stacks stay in their checkpoint dtype — in particular the
TIED LM head (``word.attend``) scores against the exact fp32 embedding
table. Dequantization happens INSIDE each AOT executable
(:func:`dequantize_params` as the first line of the jitted body), so HBM
holds int8 kernels and XLA fuses the ``int8 -> f32 * scale`` convert into
the matmul operand read. The packed layout keeps ``bert_param_specs``'
suffix rules applicable: ``_q8`` shards exactly like the kernel it
replaced and ``_q8_scale`` carries the kernel's last-axis sharding, so TP
layouts restore shard-direct unchanged (models/bert.py spec rules).

**KV cache** — int8 pages with per-position scales. A quantized cache
operand is the pytree ``{"q": int8[..., heads, head_dim], "s":
f32[...]}``: one absmax scale per written position (per layer, per slot/
block, per token — the finest granularity an incremental decode write can
maintain without re-scaling a page). Writers quantize at the scatter
(:func:`quantize_kv`); attention never materializes a dequantized cache —
the k-scale factors into the score matrix after the QK^T product and the
v-scale folds into the softmax weights before the context product
(models/causal_lm.py). Page copies (prefix-pool publish/gather, disagg
export/import, stream migration) move ``q`` and ``s`` together bit-exactly,
which is why cached-vs-cold and spec-on-vs-off parity survive quantization
by construction.

``normalize_quant_dtype`` is the single knob validator: engines and
shardcheck's SC002 quant sweep route every ``weight_dtype`` / ``kv_dtype``
string through it so an unsupported mode dies in a clean ``ValueError`` at
plan time, never an XLA error mid-request.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "QUANT_DTYPES",
    "cast_params",
    "dequantize_kv",
    "dequantize_params",
    "fp32_equiv_nbytes",
    "free_replaced_leaves",
    "is_quantized_leaf",
    "is_quantized_tree",
    "normalize_quant_dtype",
    "quantize_kv",
    "quantize_params",
]

#: dtype names an engine accepts for weight_dtype / kv_dtype (None = keep
#: the model's compute dtype).
QUANT_DTYPES = ("float32", "bfloat16", "int8")

# absmax floor: an all-zero channel/position must quantize to scale > 0 so
# the dequant multiply never divides-by-zero upstream (q is 0 either way).
_EPS = 1e-8


def normalize_quant_dtype(value, what: str = "dtype") -> str | None:
    """Canonicalize a quantization knob: ``None`` means "keep the model
    dtype"; anything else must name one of :data:`QUANT_DTYPES`. Raises
    ``ValueError`` on unknown names — the clean-rejection contract
    shardcheck's SC002 quant sweep pins."""
    if value is None:
        return None
    name = str(np.dtype(value).name) if not isinstance(value, str) else value
    name = {"f32": "float32", "fp32": "float32", "bf16": "bfloat16"}.get(
        name, name
    )
    if name not in QUANT_DTYPES:
        raise ValueError(
            f"{what} {value!r} not supported: pick one of {QUANT_DTYPES} "
            "(or None to keep the model dtype)"
        )
    return name


def is_quantized_leaf(x) -> bool:
    """True for the packed ``{"_q8", "_q8_scale"}`` kernel dict."""
    return isinstance(x, dict) and "_q8" in x and "_q8_scale" in x


def is_quantized_tree(tree) -> bool:
    """True when any kernel leaf in ``tree`` is already int8-packed."""
    found = False
    for leaf in jax.tree.leaves(tree, is_leaf=is_quantized_leaf):
        if is_quantized_leaf(leaf):
            found = True
            break
    return found


def _path_names(path) -> tuple:
    return tuple(
        p.key for p in path if isinstance(p, jax.tree_util.DictKey)
    )


def _eligible(names, leaf) -> bool:
    # Dense/DenseGeneral kernels only: biases are 1-D, embeddings are named
    # "embedding" (the tied LM head must stay exact), MoE expert stacks use
    # their own leaf names and keep checkpoint dtype.
    return (
        bool(names)
        and names[-1] == "kernel"
        and getattr(leaf, "ndim", 0) >= 2
        and jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating)
    )


def quantize_params(params):
    """Per-output-channel absmax int8 over every eligible kernel leaf.

    Returns a new tree where each quantized kernel is the packed dict
    ``{"_q8": int8, "_q8_scale": f32[last_dim]}``; every other leaf is the
    ORIGINAL array (shared, not copied). Idempotent: already-packed leaves
    pass through untouched."""

    def q_leaf(path, leaf):
        if is_quantized_leaf(leaf):
            return leaf
        names = _path_names(path)
        if not _eligible(names, leaf):
            return leaf
        w = jnp.asarray(leaf, jnp.float32)
        red = tuple(range(w.ndim - 1))
        s = jnp.maximum(jnp.max(jnp.abs(w), axis=red) / 127.0, _EPS)
        q = jnp.clip(jnp.round(w / s), -127, 127).astype(jnp.int8)
        return {"_q8": q, "_q8_scale": s.astype(jnp.float32)}

    return jax.tree_util.tree_map_with_path(
        q_leaf, params, is_leaf=is_quantized_leaf
    )


def dequantize_params(params, dtype=jnp.float32):
    """Unpack every ``{"_q8", "_q8_scale"}`` leaf back to a dense kernel in
    ``dtype``. Identity (same leaf objects) for unquantized trees, so every
    AOT executable body can call it unconditionally — under jit the
    int8->float convert fuses into the consuming matmul."""

    def dq(x):
        if is_quantized_leaf(x):
            return (
                x["_q8"].astype(jnp.float32) * x["_q8_scale"]
            ).astype(dtype)
        return x

    return jax.tree.map(dq, params, is_leaf=is_quantized_leaf)


def cast_params(params, dtype):
    """Cast every floating leaf (bf16 weight mode); ints and packed int8
    leaves pass through."""

    def c(x):
        if is_quantized_leaf(x):
            return x
        a = jnp.asarray(x)
        return a.astype(dtype) if jnp.issubdtype(a.dtype, jnp.floating) \
            else x

    return jax.tree.map(c, params, is_leaf=is_quantized_leaf)


def fp32_equiv_nbytes(tree) -> int:
    """Bytes the tree's payload would occupy at fp32 — the baseline the
    ``/memz`` ``bytes_saved_vs_fp32`` ledger compares against. Packed int8
    kernels count their kernel elements only (the scale vector is overhead
    the ACTUAL byte count carries, so savings stay honest); quantized KV
    trees likewise count the ``q`` payload."""
    total = 0
    for leaf in jax.tree.leaves(tree, is_leaf=is_quantized_leaf):
        if is_quantized_leaf(leaf):
            total += int(np.prod(leaf["_q8"].shape)) * 4
        elif isinstance(leaf, dict):  # pragma: no cover - defensive
            total += fp32_equiv_nbytes(leaf)
        else:
            total += int(np.prod(getattr(leaf, "shape", ()))) * 4
    return total


def free_replaced_leaves(old_tree, new_tree) -> int:
    """Delete the device buffers of every ``old_tree`` leaf that
    ``new_tree`` REPLACED (quantized or cast — leaves shared by identity
    survive). Returns the bytes reclaimed; the quantize-at-restore path
    feeds this into the memory registry's released ledger."""
    new_by_path = {
        path: leaf
        for path, leaf in jax.tree_util.tree_flatten_with_path(
            new_tree, is_leaf=is_quantized_leaf
        )[0]
    }
    reclaimed = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(old_tree)[0]:
        new = new_by_path.get(path)
        if new is leaf or not isinstance(leaf, jax.Array):
            continue
        reclaimed += int(leaf.nbytes)
        leaf.delete()
    return reclaimed


# ---------------------------------------------------------------- KV cache


def quantize_kv(x):
    """Quantize K or V activations position-wise: absmax over the trailing
    ``(heads, head_dim)`` axes. ``x: [..., h, d]`` -> ``(q int8[..., h, d],
    scale f32[...])``."""
    xf = x.astype(jnp.float32)
    s = jnp.maximum(jnp.max(jnp.abs(xf), axis=(-2, -1)) / 127.0, _EPS)
    q = jnp.clip(
        jnp.round(xf / s[..., None, None]), -127, 127
    ).astype(jnp.int8)
    return q, s


def dequantize_kv(q, s, dtype=jnp.float32):
    """Materialize a quantized KV stage back to dense (wire/debug paths
    only — attention uses the factored form and never calls this)."""
    return (q.astype(jnp.float32) * s[..., None, None]).astype(dtype)
