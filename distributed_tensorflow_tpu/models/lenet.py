"""LeNet-5 — the MNIST sanity workload (SURVEY.md §3e, BASELINE.json:7).

The reference uses this as its single-process sync-SGD floor: a conv/pool/fc
graph built by ``inference(images) -> logits`` functions. Same capability
here as a flax module; the classic LeCun-98 shape (6-16-120-84-10) on 28x28
inputs with SAME padding on the first conv.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn


class LeNet5(nn.Module):
    """Classic LeNet-5 for 28x28x1 MNIST images, NHWC."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(6, (5, 5), padding="SAME", dtype=self.dtype, name="conv1")(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(16, (5, 5), padding="VALID", dtype=self.dtype, name="conv2")(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(120, dtype=self.dtype, name="fc1")(x))
        x = nn.relu(nn.Dense(84, dtype=self.dtype, name="fc2")(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
