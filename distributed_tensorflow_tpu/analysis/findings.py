"""Shared finding model for the graftcheck analysis suite.

Every checker (jaxlint, locklint, shardcheck) reports ``Finding`` records.
A finding is identified by ``check:path:scope`` — deliberately *not* by line
number, so baseline suppressions survive unrelated edits to the same file.

The baseline file (``analysis/baseline.json``) lists intentional findings
with a one-line justification each; ``apply_baseline`` splits a run's
findings into active (fail CI) and suppressed, and reports stale baseline
entries (suppressions that no longer match anything) so the baseline cannot
silently rot.
"""

from __future__ import annotations

import ast
import dataclasses
import json
from pathlib import Path
from typing import Iterable, Sequence

__all__ = [
    "Finding",
    "SourceFile",
    "ScopeIndex",
    "Baseline",
    "BaselineResult",
    "iter_sources",
    "load_baseline",
    "apply_baseline",
    "dotted_name",
]


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic from one checker."""

    check: str  # rule id, e.g. "JL001"
    path: str  # repo-relative posix path
    line: int
    scope: str  # enclosing def/class qualname, or "<module>"
    message: str

    @property
    def suppress_id(self) -> str:
        return f"{self.check}:{self.path}:{self.scope}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.check} [{self.scope}] {self.message}"


@dataclasses.dataclass(frozen=True)
class SourceFile:
    """A parsed module handed to every AST checker (parsed once, shared)."""

    path: Path  # absolute
    rel: str  # repo-relative posix path, used in findings
    text: str
    tree: ast.Module


class ScopeIndex:
    """Maps line numbers to the innermost enclosing def/class qualname."""

    def __init__(self, tree: ast.Module) -> None:
        self._spans: list[tuple[int, int, str]] = []

        def visit(node: ast.AST, prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
                ):
                    qual = f"{prefix}.{child.name}" if prefix else child.name
                    end = getattr(child, "end_lineno", child.lineno) or child.lineno
                    self._spans.append((child.lineno, end, qual))
                    visit(child, qual)
                else:
                    visit(child, prefix)

        visit(tree, "")
        # Innermost scope wins: sort by span width descending so later
        # (narrower) entries override earlier ones during lookup.
        self._spans.sort(key=lambda s: -(s[1] - s[0]))

    def lookup(self, line: int) -> str:
        best = "<module>"
        for start, end, qual in self._spans:
            if start <= line <= end:
                best = qual  # spans sorted widest-first; keep narrowing
        return best


def dotted_name(node: ast.AST) -> str | None:
    """Render ``a.b.c`` for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


_SKIP_DIRS = {"__pycache__", ".git"}


def iter_sources(root: Path, package: str = "distributed_tensorflow_tpu") -> list[SourceFile]:
    """Parse every ``.py`` under ``root/package`` once, in stable order."""
    base = root / package
    out: list[SourceFile] = []
    for path in sorted(base.rglob("*.py")):
        if any(part in _SKIP_DIRS for part in path.parts):
            continue
        text = path.read_text(encoding="utf-8")
        try:
            tree = ast.parse(text, filename=str(path))
        except SyntaxError as exc:  # report, don't crash the suite
            rel = path.relative_to(root).as_posix()
            out.append(
                SourceFile(
                    path=path,
                    rel=rel,
                    text=text,
                    tree=ast.Module(body=[], type_ignores=[]),
                )
            )
            continue
        out.append(SourceFile(path=path, rel=path.relative_to(root).as_posix(), text=text, tree=tree))
    return out


@dataclasses.dataclass(frozen=True)
class Baseline:
    """Parsed baseline.json: suppression id -> one-line justification."""

    entries: dict[str, str]


@dataclasses.dataclass
class BaselineResult:
    active: list[Finding]
    suppressed: list[Finding]
    stale: list[str]  # baseline ids that matched nothing among checks run


def load_baseline(path: Path | None) -> Baseline:
    if path is None or not path.exists():
        return Baseline(entries={})
    raw = json.loads(path.read_text(encoding="utf-8"))
    entries: dict[str, str] = {}
    for item in raw.get("suppressions", []):
        entries[item["id"]] = item.get("reason", "")
    return Baseline(entries=entries)


def apply_baseline(
    findings: Sequence[Finding],
    baseline: Baseline,
    checks_run: Iterable[str],
) -> BaselineResult:
    """Split findings into active/suppressed and detect stale suppressions.

    Staleness is only judged for suppression ids whose check prefix is in
    ``checks_run`` — a ``--quick`` run that skips a checker must not flag
    that checker's baseline entries as stale.
    """
    run = set(checks_run)
    matched: set[str] = set()
    active: list[Finding] = []
    suppressed: list[Finding] = []
    for f in findings:
        if f.suppress_id in baseline.entries:
            matched.add(f.suppress_id)
            suppressed.append(f)
        else:
            active.append(f)
    stale = [
        sid
        for sid in baseline.entries
        if sid not in matched and sid.split(":", 1)[0] in run
    ]
    return BaselineResult(active=active, suppressed=suppressed, stale=sorted(stale))
