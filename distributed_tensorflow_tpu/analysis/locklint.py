"""locklint: static concurrency rules for the threaded serve/data/obs stack.

Rules
-----
LL001  ``threading.Lock``/``Condition`` acquired outside a ``with`` block
       (bare ``.acquire()``). Semaphores are exempt — acquire/release
       across method boundaries is their whole point (in-flight gating).
LL002  Blocking call while holding a lock: queue ``get``/``put``,
       ``Thread.join``, ``time.sleep``, ``Event.wait``, or a blocking
       device transfer inside a ``with <lock>`` body. ``Condition.wait``
       on the *held* condition is exempt (wait releases the lock).
LL003  ``threading.Thread`` spawned neither daemon nor joined with a
       timeout on some close path — a wedged worker then hangs shutdown.

Attribute classification is per-module: any ``self.X = threading.Lock()``
(or Condition/Thread/Event/Semaphore, or ``queue.Queue``) assignment —
plain or annotated — marks ``self.X`` for every method of that module.
This is what keeps dict ``.get()`` under a lock (obs/metrics.py) from
being mistaken for a blocking queue get.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable

from .findings import Finding, ScopeIndex, SourceFile, dotted_name

__all__ = ["run", "CHECKS"]

CHECKS = ("LL001", "LL002", "LL003")

_LOCK_CTORS = {"threading.Lock", "threading.RLock", "threading.Condition"}
_SEM_CTORS = {"threading.Semaphore", "threading.BoundedSemaphore"}
_QUEUE_CTORS = {"queue.Queue", "queue.SimpleQueue", "queue.LifoQueue", "queue.PriorityQueue"}
_THREAD_CTORS = {"threading.Thread"}
_EVENT_CTORS = {"threading.Event"}

_LOCKISH_NAME = re.compile(r"lock|mutex|_cv\b|cond", re.IGNORECASE)


class _AttrKinds:
    """Kinds of ``self.X`` / module-level names, scanned per module."""

    def __init__(self, tree: ast.Module) -> None:
        self.locks: set[str] = set()  # "self._cv", "_PROFILER_LOCK"
        self.sems: set[str] = set()
        self.queues: set[str] = set()
        self.threads: set[str] = set()
        self.events: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                targets = node.targets
                value = node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = [node.target]
                value = node.value
            else:
                continue
            if not isinstance(value, ast.Call):
                continue
            ctor = dotted_name(value.func) or ""
            bucket = None
            if ctor in _LOCK_CTORS:
                bucket = self.locks
            elif ctor in _SEM_CTORS:
                bucket = self.sems
            elif ctor in _QUEUE_CTORS:
                bucket = self.queues
            elif ctor in _THREAD_CTORS:
                bucket = self.threads
            elif ctor in _EVENT_CTORS:
                bucket = self.events
            if bucket is None:
                continue
            for tgt in targets:
                name = dotted_name(tgt)
                if name:
                    bucket.add(name)

    def is_lock(self, name: str | None) -> bool:
        if name is None:
            return False
        if name in self.locks:
            return True
        # Unclassified but lock-named (and not a known semaphore): treat as
        # a lock so cross-module handles still get checked.
        return name not in self.sems and bool(_LOCKISH_NAME.search(name))


def run(sources: Iterable[SourceFile]) -> list[Finding]:
    findings: list[Finding] = []
    for src in sources:
        scopes = ScopeIndex(src.tree)
        kinds = _AttrKinds(src.tree)
        findings.extend(_check_bare_acquire(src, scopes, kinds))
        findings.extend(_check_blocking_under_lock(src, scopes, kinds))
        findings.extend(_check_thread_lifecycle(src, scopes))
    return findings


# ---------------------------------------------------------------- LL001


def _check_bare_acquire(
    src: SourceFile, scopes: ScopeIndex, kinds: _AttrKinds
) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(src.tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in {"acquire", "release"}
        ):
            continue
        target = dotted_name(node.func.value)
        if target in kinds.sems:
            continue
        if kinds.is_lock(target):
            findings.append(
                Finding(
                    check="LL001",
                    path=src.rel,
                    line=node.lineno,
                    scope=scopes.lookup(node.lineno),
                    message=(
                        f"bare '{target}.{node.func.attr}()'; locks must be held "
                        "via 'with' so exceptions cannot leak them"
                    ),
                )
            )
    return findings


# ---------------------------------------------------------------- LL002

_BLOCKING_FREE_CALLS = {
    "time.sleep",
    "jax.device_get",
    "jax.block_until_ready",
}


class _LockHeldVisitor(ast.NodeVisitor):
    def __init__(self, src: SourceFile, scopes: ScopeIndex, kinds: _AttrKinds) -> None:
        self.src = src
        self.scopes = scopes
        self.kinds = kinds
        self.held: list[str] = []
        self.findings: list[Finding] = []

    def visit_With(self, node: ast.With) -> None:
        held_here: list[str] = []
        for item in node.items:
            expr = item.context_expr
            name = dotted_name(expr)
            if name is None and isinstance(expr, ast.Call):
                name = dotted_name(expr.func)
            if name and self.kinds.is_lock(name):
                held_here.append(name)
        self.held.extend(held_here)
        for stmt in node.body:
            self.visit(stmt)
        for _ in held_here:
            self.held.pop()

    # Don't descend into nested defs — they run later, not under the lock.
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]
    visit_Lambda = visit_FunctionDef  # type: ignore[assignment]

    def visit_Call(self, node: ast.Call) -> None:
        if self.held:
            self._check_call(node)
        self.generic_visit(node)

    def _check_call(self, node: ast.Call) -> None:
        callee = dotted_name(node.func) or ""
        blocked = None
        if callee in _BLOCKING_FREE_CALLS:
            blocked = f"{callee}()"
        elif isinstance(node.func, ast.Attribute):
            base = dotted_name(node.func.value)
            attr = node.func.attr
            if base in self.kinds.queues and attr in {"get", "put", "join"}:
                blocked = f"queue op '{base}.{attr}()'"
            elif base in self.kinds.threads and attr == "join":
                blocked = f"thread '{base}.join()'"
            elif base in self.kinds.events and attr == "wait":
                blocked = f"event '{base}.wait()'"
            elif attr == "block_until_ready":
                blocked = f"'{base}.block_until_ready()'"
            elif attr == "wait" and self.kinds.is_lock(base) and base not in self.held:
                # waiting on a DIFFERENT condition than the one(s) held
                blocked = f"'{base}.wait()' while holding {self.held[-1]}"
        if blocked:
            self.findings.append(
                Finding(
                    check="LL002",
                    path=self.src.rel,
                    line=node.lineno,
                    scope=self.scopes.lookup(node.lineno),
                    message=(
                        f"blocking {blocked} while holding lock "
                        f"'{self.held[-1]}'"
                    ),
                )
            )


def _check_blocking_under_lock(
    src: SourceFile, scopes: ScopeIndex, kinds: _AttrKinds
) -> list[Finding]:
    findings: list[Finding] = []
    for fn in (
        n
        for n in ast.walk(src.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ):
        visitor = _LockHeldVisitor(src, scopes, kinds)
        for stmt in fn.body:
            visitor.visit(stmt)
        findings.extend(visitor.findings)
    return findings


# ---------------------------------------------------------------- LL003


def _check_thread_lifecycle(src: SourceFile, scopes: ScopeIndex) -> list[Finding]:
    findings: list[Finding] = []
    module_src = src.text

    for node in ast.walk(src.tree):
        if not (
            isinstance(node, ast.Call)
            and (dotted_name(node.func) or "") in _THREAD_CTORS
        ):
            continue
        daemon = any(
            kw.arg == "daemon"
            and isinstance(kw.value, ast.Constant)
            and kw.value.value is True
            for kw in node.keywords
        )
        if daemon:
            continue
        # Non-daemon: require a timeout join (or daemon attr set) somewhere
        # in the module on a plausible handle for this thread.
        if _has_timeout_join_or_daemon_attr(src.tree, node):
            continue
        findings.append(
            Finding(
                check="LL003",
                path=src.rel,
                line=node.lineno,
                scope=scopes.lookup(node.lineno),
                message=(
                    "Thread is neither daemon=True nor joined-with-timeout on a "
                    "close path; a wedged worker would hang shutdown"
                ),
            )
        )
    _ = module_src
    return findings


def _has_timeout_join_or_daemon_attr(tree: ast.Module, ctor: ast.Call) -> bool:
    # Find the name the Thread was bound to (self.X = Thread(...) or X = ...).
    # A comprehension binding — self._ts = [Thread(...) for _ in ...] — makes
    # the target a handle *collection* rather than a handle.
    handles: set[str] = set()
    colls: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and node.value is ctor:
            for tgt in node.targets:
                name = dotted_name(tgt)
                if name:
                    handles.add(name)
        elif isinstance(node, ast.AnnAssign) and node.value is ctor:
            name = dotted_name(node.target)
            if name:
                handles.add(name)
        elif (
            isinstance(node, ast.Assign)
            and isinstance(node.value, (ast.ListComp, ast.SetComp, ast.GeneratorExp))
            and node.value.elt is ctor
        ):
            for tgt in node.targets:
                name = dotted_name(tgt)
                if name:
                    colls.add(name)
    if not handles and not colls:
        return False
    _propagate_handles(tree, handles, colls)
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "join"
            and dotted_name(node.func.value) in handles
            and (node.args or any(kw.arg == "timeout" for kw in node.keywords))
        ):
            return True
        if (
            isinstance(node, ast.Assign)
            and any(
                dotted_name(t) in {f"{h}.daemon" for h in handles}
                for t in node.targets
            )
            and isinstance(node.value, ast.Constant)
            and node.value.value is True
        ):
            return True
    return False


def _propagate_handles(
    tree: ast.Module, handles: set[str], colls: set[str] | None = None
) -> None:
    """Grow ``handles`` with indirect bindings of the same thread objects.

    The direct rule only sees ``self._t = Thread(...)`` ... ``self._t.join(
    timeout)``. Real shutdown paths are often indirect: workers collected
    into a list joined by a ``close()``/``shutdown()`` helper (itself called
    from ``finally``/``__exit__``), or handles returned from a spawn helper.
    Fixpoint over three propagation steps:

    * alias/return: ``x = h`` and ``y = self._spawn()`` where ``_spawn``
      returns a handle make ``x``/``y`` handles;
    * collection: ``self._workers.append(h)`` / ``ws = [h1, h2]`` mark the
      container;
    * iteration: ``for w in self._workers:`` makes the loop variable a
      handle, so ``w.join(timeout=...)`` counts.
    """
    colls = set() if colls is None else colls
    while True:
        changed = False

        def note(bucket: set[str], name: str | None) -> None:
            nonlocal changed
            if name and name not in bucket:
                bucket.add(name)
                changed = True

        returners = {
            fn.name
            for fn in ast.walk(tree)
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
            and any(
                isinstance(sub, ast.Return)
                and sub.value is not None
                and dotted_name(sub.value) in handles
                for sub in ast.walk(fn)
            )
        }
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                val = node.value
                tgt_names = [dotted_name(t) for t in node.targets]
                callee = (
                    (dotted_name(val.func) or "").split(".")[-1]
                    if isinstance(val, ast.Call)
                    else ""
                )
                if dotted_name(val) in handles or callee in returners:
                    for name in tgt_names:
                        note(handles, name)
                elif isinstance(val, (ast.List, ast.Tuple, ast.Set)) and any(
                    dotted_name(e) in handles for e in val.elts
                ):
                    for name in tgt_names:
                        note(colls, name)
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in {"append", "add", "insert"}
                and any(dotted_name(a) in handles for a in node.args)
            ):
                note(colls, dotted_name(node.func.value))
            elif isinstance(node, ast.For) and dotted_name(node.iter) in colls:
                note(handles, dotted_name(node.target))
        if not changed:
            return
