"""graftcheck: project-native static analysis for the training/serving stack.

Checkers:

* :mod:`.jaxlint`   — JAX correctness pitfalls (JL001–JL004)
* :mod:`.locklint`  — static concurrency rules (LL001–LL003)
* :mod:`.racelint`  — cross-thread shared-state rules (RC001–RC003)
* :mod:`.shardcheck`— mesh-axis and serving-layout validation (SC001–SC002)

plus the runtime lock-order + data-race sanitizers in
:mod:`distributed_tensorflow_tpu.obs.sanitizer`. Run everything via
``scripts/analyze.py``; see ``docs/ANALYSIS.md`` for the check catalog and
baseline workflow.
"""

from .findings import (
    Baseline,
    BaselineResult,
    Finding,
    SourceFile,
    apply_baseline,
    iter_sources,
    load_baseline,
)

__all__ = [
    "Baseline",
    "BaselineResult",
    "Finding",
    "SourceFile",
    "apply_baseline",
    "iter_sources",
    "load_baseline",
]
