"""racelint: cross-thread shared-state rules for the threaded stack.

Where locklint asks "are locks used *correctly*", racelint asks the prior
question: "is shared state guarded *at all*". It builds a thread-entry map
from ``threading.Thread(target=self.X)`` sites, walks every method
reachable from each entry (and from the public caller surface)
interprocedurally while tracking the ``with self._lock:`` blocks in
effect, and compares the per-thread-context read/write sets that fall out.

Rules
-----
RC001  Attribute written in >= 2 thread contexts with no lock common to
       all of those writes. ``__init__`` writes are exempt — construction
       happens-before ``Thread.start()`` (RC003 polices the exception).
RC002  Check-then-act on shared state outside the guarding lock:
       ``if self._closed: ... ; self._closed = True`` where no lock is
       held across both the test and the write. Also applied to module
       globals mutated under a ``global`` declaration (lazy-init caches).
RC003  Publication hazards: a mutable default argument on a threaded
       class's method, or a ``self.X`` assigned in ``__init__`` *after*
       the worker thread started when that worker touches ``X`` — the
       thread can observe a partially-constructed object.

Guard inference is deliberately syntactic: an attribute counts as guarded
by exactly the set of lock-kind names (per locklint's ``_AttrKinds``
classification) held via ``with`` at the access, carried through
``self.method()`` calls. Sync primitives themselves (locks, semaphores,
queues, events, thread handles) are exempt from the data rules — their
whole job is cross-thread access.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable

from .findings import Finding, ScopeIndex, SourceFile, dotted_name
from .locklint import _AttrKinds, _THREAD_CTORS

__all__ = ["run", "CHECKS"]

CHECKS = ("RC001", "RC002", "RC003")

CALLER_CTX = "<caller>"
INIT_CTX = "<init>"

# Method names that mutate their receiver in place: a call
# ``self.X.append(...)`` counts as a *write* to ``self.X``.
_MUTATOR_METHODS = {
    "append", "appendleft", "extend", "add", "insert", "setdefault",
    "pop", "popleft", "popitem", "remove", "discard", "clear", "update",
}

_MUTABLE_DEFAULTS = (ast.List, ast.Dict, ast.Set)
_MUTABLE_DEFAULT_CTORS = {"list", "dict", "set", "collections.deque", "deque"}


@dataclasses.dataclass(frozen=True)
class _Access:
    attr: str
    write: bool
    ctx: str  # entry method name, CALLER_CTX, or INIT_CTX
    method: str  # method the access physically lives in
    locks: frozenset[str]
    line: int


def _self_attr(node: ast.AST) -> str | None:
    """``self.X`` -> ``X`` for a direct attribute node, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _self_root(node: ast.AST) -> str | None:
    """Peel ``.attr`` / ``[...]`` / ``(...)`` layers down to a ``self.X``."""
    while True:
        name = _self_attr(node)
        if name is not None:
            return name
        if isinstance(node, ast.Attribute):
            node = node.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Call):
            node = node.func
        else:
            return None


class _MethodWalker(ast.NodeVisitor):
    """Record self-attribute accesses in one thread context.

    Follows ``self.method()`` calls into sibling methods, carrying the
    currently-held ``with``-lock set; the visited set is keyed on
    (method, held-locks) so differently-guarded call paths each count.
    """

    def __init__(self, methods: dict[str, ast.FunctionDef], kinds: _AttrKinds, ctx: str):
        self.methods = methods
        self.kinds = kinds
        self.sync = _sync_names(kinds)
        self.ctx = ctx
        self.held: list[str] = []
        self.accesses: list[_Access] = []
        self._visited: set[tuple[str, frozenset[str]]] = set()
        self._current = ""

    # -- entry ----------------------------------------------------------

    def walk(self, method: str) -> None:
        key = (method, frozenset(self.held))
        if key in self._visited:
            return
        self._visited.add(key)
        prev = self._current
        self._current = method
        for stmt in self.methods[method].body:
            self.visit(stmt)
        self._current = prev

    # -- recording ------------------------------------------------------

    def _record(self, attr: str, write: bool, line: int) -> None:
        if attr in self.sync:
            return
        self.accesses.append(
            _Access(
                attr=attr,
                write=write,
                ctx=self.ctx,
                method=self._current,
                locks=frozenset(self.held),
                line=line,
            )
        )

    def _record_target(self, tgt: ast.AST) -> None:
        root = _self_root(tgt)
        if root is not None:
            self._record(root, True, tgt.lineno)
        # Subscript/attribute targets still *read* their index expressions.
        if isinstance(tgt, ast.Subscript):
            self.visit(tgt.slice)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                self._record_target(elt)

    # -- structure ------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        held_here: list[str] = []
        for item in node.items:
            expr = item.context_expr
            name = dotted_name(expr)
            if name is None and isinstance(expr, ast.Call):
                name = dotted_name(expr.func)
            if name and self.kinds.is_lock(name):
                held_here.append(name)
        self.held.extend(held_here)
        for stmt in node.body:
            self.visit(stmt)
        for _ in held_here:
            self.held.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # Nested defs run later (possibly on another thread); don't fold
        # their accesses into this context.
        pass

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]
    visit_Lambda = visit_FunctionDef  # type: ignore[assignment]

    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            self._record_target(tgt)
        self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_target(node.target)
        # ``self.n += 1`` reads n too.
        root = _self_root(node.target)
        if root is not None:
            self._record(root, False, node.lineno)
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record_target(node.target)
            self.visit(node.value)

    def visit_Delete(self, node: ast.Delete) -> None:
        for tgt in node.targets:
            self._record_target(tgt)

    def visit_Call(self, node: ast.Call) -> None:
        # self.method() -> descend into the sibling method, locks carried.
        callee = _self_attr(node.func)
        if callee is not None and callee in self.methods:
            self.walk(callee)
        elif isinstance(node.func, ast.Attribute) and node.func.attr in _MUTATOR_METHODS:
            root = _self_root(node.func.value)
            if root is not None:
                self._record(root, True, node.lineno)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        name = _self_attr(node)
        if name is not None and isinstance(node.ctx, ast.Load):
            self._record(name, False, node.lineno)
        self.generic_visit(node)


def _sync_names(kinds: _AttrKinds) -> set[str]:
    out: set[str] = set()
    for bucket in (kinds.locks, kinds.sems, kinds.queues, kinds.threads, kinds.events):
        for dotted in bucket:
            out.add(dotted.split(".")[-1])
    return out


class _ClassReport:
    """Thread-context access sets for one threaded class."""

    def __init__(self, cls: ast.ClassDef, kinds: _AttrKinds) -> None:
        self.cls = cls
        self.kinds = kinds
        self.methods: dict[str, ast.FunctionDef] = {
            n.name: n
            for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        self.entries = self._thread_entries()
        self.accesses: list[_Access] = []
        if not self.entries:
            return
        reachable = self._reachable_from(self.entries)
        for entry in sorted(self.entries):
            w = _MethodWalker(self.methods, kinds, ctx=entry)
            w.walk(entry)
            self.accesses.extend(w.accesses)
        caller_roots = [
            name
            for name in self.methods
            if name not in reachable and name not in self.entries and name != "__init__"
        ]
        w = _MethodWalker(self.methods, kinds, ctx=CALLER_CTX)
        for root in sorted(caller_roots):
            w.walk(root)
        self.accesses.extend(w.accesses)
        if "__init__" in self.methods:
            w = _MethodWalker(self.methods, kinds, ctx=INIT_CTX)
            w.walk("__init__")
            self.accesses.extend(w.accesses)

    def _thread_entries(self) -> set[str]:
        entries: set[str] = set()
        for node in ast.walk(self.cls):
            if not (
                isinstance(node, ast.Call)
                and (dotted_name(node.func) or "") in _THREAD_CTORS
            ):
                continue
            for kw in node.keywords:
                if kw.arg != "target":
                    continue
                target = _self_attr(kw.value)
                if target and target in self.methods:
                    entries.add(target)
        return entries

    def _reachable_from(self, roots: set[str]) -> set[str]:
        calls: dict[str, set[str]] = {}
        for name, fn in self.methods.items():
            out: set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    callee = _self_attr(node.func)
                    if callee and callee in self.methods:
                        out.add(callee)
            calls[name] = out
        seen = set(roots)
        frontier = list(roots)
        while frontier:
            for nxt in calls.get(frontier.pop(), ()):
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(nxt)
        return seen


# ---------------------------------------------------------------- RC001


def _check_multi_context_writes(
    src: SourceFile, scopes: ScopeIndex, report: _ClassReport
) -> list[Finding]:
    findings: list[Finding] = []
    by_attr: dict[str, list[_Access]] = {}
    for acc in report.accesses:
        if acc.write and acc.ctx != INIT_CTX:
            by_attr.setdefault(acc.attr, []).append(acc)
    for attr, writes in sorted(by_attr.items()):
        ctxs = sorted({w.ctx for w in writes})
        if len(ctxs) < 2:
            continue
        common = frozenset.intersection(*(w.locks for w in writes))
        if common:
            continue
        first = min(writes, key=lambda w: w.line)
        findings.append(
            Finding(
                check="RC001",
                path=src.rel,
                line=first.line,
                scope=scopes.lookup(first.line),
                message=(
                    f"attribute 'self.{attr}' written in thread contexts "
                    f"{', '.join(repr(c) for c in ctxs)} with no common "
                    "guarding lock"
                ),
            )
        )
    return findings


# ---------------------------------------------------------------- RC002


class _CheckActVisitor(ast.NodeVisitor):
    """If-tests and writes per attribute, with held locks, inside one fn."""

    def __init__(self, kinds: _AttrKinds, names: set[str] | None) -> None:
        # names=None: track self.X attrs; else track these bare globals.
        self.kinds = kinds
        self.sync = _sync_names(kinds)
        self.names = names
        self.held: list[str] = []
        self.tests: dict[str, list[tuple[frozenset[str], int]]] = {}
        self.writes: dict[str, list[tuple[frozenset[str], int]]] = {}

    def _tracked(self, node: ast.AST) -> str | None:
        if self.names is None:
            name = _self_attr(node)
            if name is not None and name not in self.sync:
                return name
            return None
        if isinstance(node, ast.Name) and node.id in self.names:
            return node.id
        return None

    def visit_With(self, node: ast.With) -> None:
        held_here: list[str] = []
        for item in node.items:
            expr = item.context_expr
            name = dotted_name(expr)
            if name is None and isinstance(expr, ast.Call):
                name = dotted_name(expr.func)
            if name and self.kinds.is_lock(name):
                held_here.append(name)
        self.held.extend(held_here)
        for stmt in node.body:
            self.visit(stmt)
        for _ in held_here:
            self.held.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]
    visit_Lambda = visit_FunctionDef  # type: ignore[assignment]

    def visit_If(self, node: ast.If) -> None:
        held = frozenset(self.held)
        for sub in ast.walk(node.test):
            name = self._tracked(sub)
            if name is not None:
                self.tests.setdefault(name, []).append((held, node.lineno))
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    def _note_write(self, tgt: ast.AST, line: int) -> None:
        name = self._tracked(tgt)
        if name is not None:
            self.writes.setdefault(name, []).append((frozenset(self.held), line))

    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            self._note_write(tgt, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._note_write(node.target, node.lineno)
        self.generic_visit(node)


def _check_then_act_findings(
    visitor: _CheckActVisitor,
    src: SourceFile,
    scopes: ScopeIndex,
    subject: str,
    eligible: set[str] | None = None,
) -> list[Finding]:
    findings: list[Finding] = []
    for attr, writes in sorted(visitor.writes.items()):
        if eligible is not None and attr not in eligible:
            continue
        for test_locks, test_line in visitor.tests.get(attr, ()):
            acted = [
                (w_locks, w_line)
                for w_locks, w_line in writes
                if w_line > test_line and not (test_locks & w_locks)
            ]
            if not acted:
                continue
            w_line = min(line for _, line in acted)
            findings.append(
                Finding(
                    check="RC002",
                    path=src.rel,
                    line=w_line,
                    scope=scopes.lookup(w_line),
                    message=(
                        f"check-then-act on {subject} '{attr}': tested at "
                        f"line {test_line} and written at line {w_line} "
                        "with no lock held across both"
                    ),
                )
            )
            break  # one finding per attribute per function
    return findings


def _check_check_then_act(
    src: SourceFile, scopes: ScopeIndex, report: _ClassReport
) -> list[Finding]:
    # Shared = touched in >= 2 distinct non-__init__ methods of a class
    # that runs threads; single-method attrs are thread-confined enough
    # for this rule (RC001 still sees true multi-context writes).
    touched_in: dict[str, set[str]] = {}
    for acc in report.accesses:
        if acc.method != "__init__":
            touched_in.setdefault(acc.attr, set()).add(acc.method)
    shared = {attr for attr, methods in touched_in.items() if len(methods) >= 2}
    findings: list[Finding] = []
    for name, fn in sorted(report.methods.items()):
        if name == "__init__":
            continue
        v = _CheckActVisitor(report.kinds, names=None)
        for stmt in fn.body:
            v.visit(stmt)
        findings.extend(
            _check_then_act_findings(
                v, src, scopes, "shared attribute", eligible=shared
            )
        )
    return findings


def _check_global_check_then_act(
    src: SourceFile, scopes: ScopeIndex, kinds: _AttrKinds
) -> list[Finding]:
    findings: list[Finding] = []
    for fn in (
        n
        for n in ast.walk(src.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ):
        declared: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Global):
                declared.update(node.names)
        if not declared:
            continue
        v = _CheckActVisitor(kinds, names=declared)
        for stmt in fn.body:
            v.visit(stmt)
        findings.extend(
            _check_then_act_findings(v, src, scopes, "module global")
        )
    return findings


# ---------------------------------------------------------------- RC003


def _check_publication(
    src: SourceFile, scopes: ScopeIndex, report: _ClassReport
) -> list[Finding]:
    findings: list[Finding] = []
    sync = _sync_names(report.kinds)

    # (a) mutable default arguments on a threaded class's methods: one
    # shared object across every instance AND every thread.
    for name, fn in sorted(report.methods.items()):
        args = fn.args
        defaults = list(args.defaults) + [d for d in args.kw_defaults if d is not None]
        for d in defaults:
            mutable = isinstance(d, _MUTABLE_DEFAULTS) or (
                isinstance(d, ast.Call)
                and (dotted_name(d.func) or "") in _MUTABLE_DEFAULT_CTORS
            )
            if mutable:
                findings.append(
                    Finding(
                        check="RC003",
                        path=src.rel,
                        line=d.lineno,
                        scope=scopes.lookup(d.lineno),
                        message=(
                            f"mutable default argument on '{name}' of a "
                            "thread-running class: one object is shared by "
                            "every instance and every thread"
                        ),
                    )
                )

    # (b) attributes assigned in __init__ AFTER the worker thread started:
    # the worker can observe a partially-constructed object.
    init = report.methods.get("__init__")
    if init is None:
        return findings
    start_line = None
    for node in ast.walk(init):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "start"
        ):
            root = dotted_name(node.func.value)
            if root in report.kinds.threads:
                start_line = node.lineno if start_line is None else min(start_line, node.lineno)
    if start_line is None:
        return findings
    entry_attrs = {
        acc.attr for acc in report.accesses if acc.ctx in report.entries
    }
    for node in ast.walk(init):
        if not isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            continue
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        for tgt in targets:
            attr = _self_attr(tgt)
            if (
                attr
                and node.lineno > start_line
                and attr in entry_attrs
                and attr not in sync
            ):
                findings.append(
                    Finding(
                        check="RC003",
                        path=src.rel,
                        line=node.lineno,
                        scope=scopes.lookup(node.lineno),
                        message=(
                            f"'self.{attr}' assigned after the worker thread "
                            f"started (line {start_line}) but read by the "
                            "worker: publication races construction"
                        ),
                    )
                )
    return findings


# ---------------------------------------------------------------- runner


def run(sources: Iterable[SourceFile]) -> list[Finding]:
    findings: list[Finding] = []
    for src in sources:
        scopes = ScopeIndex(src.tree)
        kinds = _AttrKinds(src.tree)
        findings.extend(_check_global_check_then_act(src, scopes, kinds))
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            report = _ClassReport(node, kinds)
            if not report.entries:
                continue
            findings.extend(_check_multi_context_writes(src, scopes, report))
            findings.extend(_check_check_then_act(src, scopes, report))
            findings.extend(_check_publication(src, scopes, report))
    return findings
