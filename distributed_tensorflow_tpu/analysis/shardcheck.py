"""shardcheck: mesh-axis and serving-layout validation before any device exists.

Two halves:

* **Static (SC001)** — every literal axis name appearing in a
  ``PartitionSpec``/``P(...)`` constructor, a ``lax`` collective
  (``psum``/``pmean``/``all_to_all``/...), or a ``mesh.shape[...]`` /
  ``mesh.shape.get(...)`` lookup must be declared in ``AXIS_ORDER`` in
  ``parallel/mesh.py``. The axis vocabulary is read from the *analyzed
  tree's own* ``parallel/mesh.py`` AST, so this pass needs no imports and
  follows the code under analysis, not the installed package.

* **Config sweep (SC002, full mode only)** — re-run the tp/ep/pp
  divisibility arithmetic that ``serve/engine.py::_serve_config`` enforces
  at runtime, over the default CLI serving configs (every BERT preset from
  ``cli/train.py``) crossed with the mesh layouts exercised by
  ``tests/test_serve_mesh.py``, on the 8-device test topology. Each
  (preset, layout) cell must resolve to one of three *designed* outcomes:
  ``serves``, ``falls_back`` (plan_serve_mesh warn-not-crash), or
  ``rejects`` (clean ValueError at startup). Anything else — an unexpected
  exception type, or a layout the planner accepts but the engine then dies
  on — is a finding: it would surface as a raw XLA error on real hardware.
  Decode cells that serve are further crossed with the prefix-cache,
  speculation, and disaggregated role-split plans (``DISAGG_VARIANTS`` →
  ``parallel/mesh.py::plan_disagg_mesh``), each under the same
  plan-or-clean-ValueError contract. Every serving cell (BERT and
  decode) is also crossed with ``QUANT_VARIANTS`` — the weight/KV
  storage-dtype plans from ``_plan_quant`` — so an unsupported dtype or
  an int8 × pipeline combination rejects at startup instead of dying
  when the params quantize on metal.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterable

from .findings import Finding, ScopeIndex, SourceFile, dotted_name

__all__ = ["run", "run_config_sweep", "CHECKS", "declared_axes", "DEFAULT_LAYOUTS"]

CHECKS = ("SC001", "SC002")

_SPEC_CTORS = {"P", "PartitionSpec", "jax.sharding.PartitionSpec"}
_COLLECTIVES = {
    "lax.psum",
    "lax.pmean",
    "lax.pmax",
    "lax.pmin",
    "lax.axis_index",
    "lax.all_gather",
    "lax.all_to_all",
    "lax.ppermute",
    "lax.psum_scatter",
    "jax.lax.psum",
    "jax.lax.pmean",
    "jax.lax.pmax",
    "jax.lax.pmin",
    "jax.lax.axis_index",
    "jax.lax.all_gather",
    "jax.lax.all_to_all",
    "jax.lax.ppermute",
    "jax.lax.psum_scatter",
}

# Prefix-cache / chunked-prefill configurations crossed into every decode
# cell that serves: (prefix_cache_mb, block_tokens). The 0.0 row is the
# cache-disabled plan (must stay a no-op, never a reject) and the rest
# exercise the byte-budget -> page-count arithmetic per TP shard layout.
PREFIX_CACHE_VARIANTS: tuple[tuple[float, int], ...] = (
    (0.0, 16),
    (8.0, 16),
    (8.0, 32),
)

# Speculative-decoding configurations crossed into every decode cell that
# serves: (spec_tokens, min_match, max_new_tokens). The 0 row is the
# spec-off plan (must stay a no-op, never a reject); the oversized rows
# must reject with a clean ValueError at plan time (a draft wider than the
# generation budget or the position table would be a runtime shape error).
SPEC_VARIANTS: tuple[tuple[int, int, int], ...] = (
    (0, 2, 32),
    (4, 2, 32),
    (8, 3, 32),
    (32, 2, 32),   # spec_tokens == max_new_tokens: must reject
)

# Quantized-serving configurations crossed into EVERY serving cell (BERT
# one-shot and causal-LM decode): (weight_dtype, kv_dtype). The
# (None, None) row is the quant-off plan (must resolve to the config
# dtype, never reject); int8 rows exercise the per-channel weight /
# per-position KV storage plans across TP shardings; the fp8 row is an
# unsupported dtype that must reject with a clean ValueError at plan
# time. kv_dtype is ignored for BERT cells (no KV cache there).
QUANT_VARIANTS: tuple[tuple[str | None, str | None], ...] = (
    (None, None),
    ("int8", "int8"),
    ("int8", None),
    (None, "int8"),
    ("bfloat16", "bfloat16"),
    ("fp8", None),   # unsupported: must reject with a clean ValueError
)

# Disaggregated-serving role splits crossed into every decode cell that
# serves: (prefill_devices, prefill_tp, decode_tp) over the sweep's
# 8-device topology. plan_disagg_mesh holds the same plan-or-clean-
# ValueError contract as plan_serve_mesh: oversized asks shrink with a
# note, non-dividing role tp drops to the largest divisor, and only
# genuinely invalid inputs (the 0 row) may reject — anything else raised
# would be a raw startup crash on a real role split.
DISAGG_VARIANTS: tuple[tuple[int, int, int], ...] = (
    (-1, 1, 1),  # auto half split, no role tp
    (-1, 2, 2),  # tp on both roles
    (2, 2, 4),   # explicit prefill subset, asymmetric tp
    (8, 1, 1),   # prefill wants the whole slice: must shrink, never crash
    (-1, 3, 1),  # non-dividing prefill tp: must fall back to a divisor
    (0, 1, 1),   # invalid: must reject with a clean ValueError
)

# Priority-scheduling configurations swept once per run (the scheduler
# plan is layout-independent): (sched, preempt, preempt_margin_ms,
# default_priority). Every combination must either plan (BatcherConfig
# constructs) or reject with a clean ValueError at config time — a knob
# combo that only dies when the decode loop first preempts would strand
# live streams. The invalid rows pin the designed rejections: preemption
# without EDF (FIFO cannot order deadline waiters), an unknown policy,
# a negative margin, and a negative default class.
SCHED_VARIANTS: tuple[tuple[str, bool, float, int], ...] = (
    ("fifo", False, 20.0, 1),   # the defaults: must plan
    ("edf", False, 20.0, 0),    # ordering without preemption: must plan
    ("edf", True, 20.0, 1),     # the full feature: must plan
    ("edf", True, 0.0, 0),      # zero margin (preempt at the deadline)
    ("fifo", True, 20.0, 1),    # preempt needs edf: must reject
    ("lifo", False, 20.0, 1),   # unknown policy: must reject
    ("edf", True, -5.0, 1),     # negative margin: must reject
    ("edf", False, 20.0, -1),   # negative default class: must reject
)

# Mesh layouts exercised by tests/test_serve_mesh.py plus the CLI default
# and the documented fallback probes, as (tp, pp, ep) on 8 devices.
DEFAULT_LAYOUTS: tuple[tuple[int, int, int], ...] = (
    (1, 1, 1),  # cli/serve.py defaults (dp over all chips)
    (2, 1, 1),
    (4, 1, 1),  # test_serve_mesh TP parity layout
    (1, 2, 1),  # PP layout (dp4-pp2)
    (1, 1, 4),  # EP layout (dp2-ep4)
    (2, 2, 2),  # combined tp2-pp2-ep2
    (16, 1, 1),  # oversized: must fall back, never crash
    (3, 1, 1),  # non-dividing: must fall back, never crash
)


def declared_axes(sources: Iterable[SourceFile]) -> set[str]:
    """Extract AXIS_ORDER from the analyzed tree's parallel/mesh.py."""
    for src in sources:
        if not src.rel.endswith("parallel/mesh.py"):
            continue
        for node in ast.walk(src.tree):
            if (
                isinstance(node, ast.Assign)
                and any(
                    isinstance(t, ast.Name) and t.id == "AXIS_ORDER"
                    for t in node.targets
                )
                and isinstance(node.value, (ast.Tuple, ast.List))
            ):
                return {
                    elt.value
                    for elt in node.value.elts
                    if isinstance(elt, ast.Constant) and isinstance(elt.value, str)
                }
    return set()


def run(sources: Iterable[SourceFile]) -> list[Finding]:
    sources = list(sources)
    axes = declared_axes(sources)
    if not axes:
        return []  # nothing to validate against (fixture trees without mesh.py)
    findings: list[Finding] = []
    for src in sources:
        scopes = ScopeIndex(src.tree)
        for node in ast.walk(src.tree):
            for line, name in _literal_axis_uses(node):
                if name not in axes:
                    findings.append(
                        Finding(
                            check="SC001",
                            path=src.rel,
                            line=line,
                            scope=scopes.lookup(line),
                            message=(
                                f"axis name '{name}' is not declared in "
                                f"parallel/mesh.py AXIS_ORDER {sorted(axes)}"
                            ),
                        )
                    )
    return findings


def _literal_axis_uses(node: ast.AST) -> list[tuple[int, str]]:
    out: list[tuple[int, str]] = []
    if isinstance(node, ast.Call):
        callee = dotted_name(node.func) or ""
        if callee in _SPEC_CTORS:
            for arg in node.args:
                out.extend(_axis_literals(arg))
        elif callee in _COLLECTIVES and len(node.args) >= 2:
            out.extend(_axis_literals(node.args[1]))
        elif callee in _COLLECTIVES:
            for kw in node.keywords:
                if kw.arg in {"axis_name", "axis"}:
                    out.extend(_axis_literals(kw.value))
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "get"
            and (dotted_name(node.func.value) or "").endswith(".shape")
            and node.args
        ):
            out.extend(_axis_literals(node.args[0]))
    elif (
        isinstance(node, ast.Subscript)
        and (dotted_name(node.value) or "").endswith(".shape")
    ):
        out.extend(_axis_literals(node.slice))
    return out


def _axis_literals(expr: ast.expr) -> list[tuple[int, str]]:
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return [(expr.lineno, expr.value)]
    if isinstance(expr, (ast.Tuple, ast.List)):
        out: list[tuple[int, str]] = []
        for elt in expr.elts:
            out.extend(_axis_literals(elt))
        return out
    return []


# ---------------------------------------------------------------- SC002


def run_config_sweep(
    n_devices: int = 8,
    layouts: Iterable[tuple[int, int, int]] = DEFAULT_LAYOUTS,
) -> tuple[list[Finding], list[dict]]:
    """Cross BERT presets with serving layouts; classify every cell.

    Returns ``(findings, matrix)`` where matrix rows record the designed
    outcome per cell (for the JSON report). Imports the package lazily —
    this is the only part of shardcheck that needs jax importable.
    """
    from ..cli.train import PRESETS
    from ..models.bert import BertConfig
    from ..models.causal_lm import CausalLMConfig
    from ..parallel.mesh import plan_disagg_mesh
    from ..serve.engine import (
        BertInferenceEngine,
        CausalLMEngine,
        plan_serve_mesh,
    )

    findings: list[Finding] = []
    matrix: list[dict] = []
    # Scheduler knob sweep (serve/batcher.py): layout-independent, so it
    # runs once, not per cell. Each variant must construct a BatcherConfig
    # or reject with a clean ValueError; the batcher classes that cannot
    # honor a policy (DynamicBatcher reorders nothing, flush admission
    # preempts nothing) must reject the config at BUILD time, before any
    # scheduler thread exists.
    from ..serve.batcher import (
        BatcherConfig,
        ContinuousBatcher,
        DynamicBatcher,
    )

    class _NullEngine:  # attribute surface only; never dispatched
        slots = 1
        max_batch = 1

    # "outcome" keeps the cell uniform with the layout cells for the
    # sweep summary; the per-variant plans/rejects verdicts live inside.
    sched_cell: dict = {"sweep": "sched", "outcome": "sched_variants",
                        "variants": []}
    for sched, preempt, margin, default_pri in SCHED_VARIANTS:
        row: dict = {
            "sched": sched, "preempt": preempt,
            "preempt_margin_ms": margin, "default_priority": default_pri,
        }
        try:
            cfg = BatcherConfig(
                sched=sched, preempt=preempt, preempt_margin_ms=margin,
                default_priority=default_pri,
            )
        except ValueError as exc:
            row["rejects"] = str(exc)
            sched_cell["variants"].append(row)
            continue
        except Exception as exc:
            findings.append(
                Finding(
                    check="SC002",
                    path="distributed_tensorflow_tpu/serve/batcher.py",
                    line=0,
                    scope="BatcherConfig",
                    message=(
                        f"sched variant sched={sched} preempt={preempt} "
                        f"margin={margin} default_priority={default_pri} "
                        f"raised {type(exc).__name__} instead of a clean "
                        f"ValueError: {exc}"
                    ),
                )
            )
            row["raised"] = type(exc).__name__
            sched_cell["variants"].append(row)
            continue
        row["plans"] = True
        if cfg.sched != "fifo":
            # The flush batcher holds no slots: a non-FIFO policy must be
            # rejected before its flusher thread ever starts.
            try:
                DynamicBatcher(lambda p: [{} for _ in p], cfg)
                findings.append(
                    Finding(
                        check="SC002",
                        path="distributed_tensorflow_tpu/serve/batcher.py",
                        line=0,
                        scope="DynamicBatcher",
                        message=(
                            f"DynamicBatcher accepted sched={cfg.sched!r} "
                            f"— the flush batcher cannot reorder or "
                            f"preempt and must reject at build time"
                        ),
                    )
                )
                row["dynamic_accepts"] = True
            except ValueError as exc:
                row["dynamic_rejects"] = str(exc)
        if cfg.preempt:
            # Flush admission only ever fills an empty table: preemption
            # there must be a clean build-time rejection too.
            try:
                ContinuousBatcher(_NullEngine(), cfg, admission="flush")
                findings.append(
                    Finding(
                        check="SC002",
                        path="distributed_tensorflow_tpu/serve/batcher.py",
                        line=0,
                        scope="ContinuousBatcher",
                        message=(
                            "ContinuousBatcher accepted preempt=True with "
                            "flush admission — there is never an occupied "
                            "slot to preempt for a waiter"
                        ),
                    )
                )
                row["flush_accepts"] = True
            except ValueError as exc:
                row["flush_rejects"] = str(exc)
        sched_cell["variants"].append(row)
    matrix.append(sched_cell)
    # Every preset with a transformer serving path: BERT one-shot scoring
    # AND the causal-LM decode engines — a decode layout that only dies at
    # executable build time is exactly the raw-XLA-error class SC002 exists
    # to catch.
    presets = {
        name: (wl, BertConfig, BertInferenceEngine)
        for name, wl in PRESETS.items()
        if "bert" in name.lower()
    }
    presets.update({
        name: (wl, CausalLMConfig, CausalLMEngine)
        for name, wl in PRESETS.items()
        if name.lower().startswith("lm")
    })
    for name, (wl, config_cls, engine_cls) in presets.items():
        # Mirror cli/serve.py config reconstruction: config-class defaults
        # with the preset's geometry overrides. max_position/dtype don't
        # affect the divisibility arithmetic under test.
        overrides: dict = {}
        if wl.bert_layers:
            overrides["num_layers"] = wl.bert_layers
        if wl.bert_hidden:
            overrides.update(
                hidden_size=wl.bert_hidden, intermediate_size=4 * wl.bert_hidden
            )
        if wl.bert_vocab:
            overrides["vocab_size"] = wl.bert_vocab
        if getattr(wl, "moe_experts", 0) and config_cls is BertConfig:
            overrides["moe_experts"] = wl.moe_experts
        base_cfg = config_cls(**overrides)

        for tp, pp, ep in layouts:
            cell = {"preset": name, "tp": tp, "pp": pp, "ep": ep}
            try:
                spec, fell_back = plan_serve_mesh(
                    tp=tp, pp=pp, ep=ep, n_devices=n_devices
                )
            except Exception as exc:  # planner must never raise
                findings.append(
                    Finding(
                        check="SC002",
                        path="distributed_tensorflow_tpu/serve/engine.py",
                        line=0,
                        scope="plan_serve_mesh",
                        message=(
                            f"planner raised {type(exc).__name__} for layout "
                            f"tp={tp} pp={pp} ep={ep} on {n_devices} devices "
                            f"(must warn and fall back): {exc}"
                        ),
                    )
                )
                cell["outcome"] = f"planner-raised:{type(exc).__name__}"
                matrix.append(cell)
                continue
            if fell_back:
                cell["outcome"] = "falls_back"
                matrix.append(cell)
                continue
            cfg = base_cfg
            if pp > 1 and config_cls is BertConfig:
                # cli/serve.py sets pipeline_parallel from --pp at load time
                # (the decoder config has no pipeline field — its engine
                # rejects pp>1 outright, which is the outcome under test).
                cfg = BertConfig(**{**overrides, "pipeline_parallel": pp})
            try:
                engine_cls._serve_config(cfg, tp=tp, ep=ep, pp=pp)
                cell["outcome"] = "serves"
                # Quantized-serving plan (engine _plan_quant): every
                # weight/kv dtype combination on a serving cell must
                # normalize to a storage plan or reject with a clean
                # ValueError — a dtype that only dies when the params
                # quantize or the cache allocates would be a raw XLA
                # error on metal.
                cell["quant"] = qplans = []
                for wd, kd in QUANT_VARIANTS:
                    qrow: dict = {"weight_dtype": wd, "kv_dtype": kd}
                    try:
                        if engine_cls is CausalLMEngine:
                            w, k = engine_cls._plan_quant(
                                cfg, tp=tp, weight_dtype=wd, kv_dtype=kd
                            )
                            qrow.update(weights=w, kv=k)
                        else:
                            w = engine_cls._plan_quant(
                                cfg, tp=tp, ep=ep, pp=pp, weight_dtype=wd
                            )
                            qrow.update(weights=w)
                    except ValueError as exc:
                        qrow["rejects"] = str(exc)
                    except Exception as exc:
                        findings.append(
                            Finding(
                                check="SC002",
                                path=(
                                    "distributed_tensorflow_tpu/"
                                    "serve/engine.py"
                                ),
                                line=0,
                                scope=(
                                    f"{engine_cls.__name__}._plan_quant"
                                ),
                                message=(
                                    f"quant plan weight={wd} kv={kd} on "
                                    f"preset '{name}' layout tp={tp} "
                                    f"pp={pp} raised "
                                    f"{type(exc).__name__} instead of a "
                                    f"clean ValueError: {exc}"
                                ),
                            )
                        )
                        qrow["raised"] = type(exc).__name__
                    qplans.append(qrow)
                if engine_cls is CausalLMEngine:
                    # Cross the serving cell with the prefix-cache budget
                    # arithmetic (serve/kvpool.py + engine page pool): each
                    # variant must plan a page count or reject with a clean
                    # ValueError — a budget that only dies when the pool
                    # tensor is allocated would be a raw XLA OOM on metal.
                    cell["prefix_cache"] = plans = []
                    for mb, bt in PREFIX_CACHE_VARIANTS:
                        try:
                            n_blocks, bpb = engine_cls._plan_prefix_cache(
                                cfg, tp=tp, prefix_cache_mb=mb,
                                block_tokens=bt,
                            )
                            plans.append({
                                "mb": mb, "block_tokens": bt,
                                "blocks": n_blocks,
                                "bytes_per_block": bpb,
                            })
                        except ValueError as exc:
                            plans.append({
                                "mb": mb, "block_tokens": bt,
                                "rejects": str(exc),
                            })
                        except Exception as exc:
                            findings.append(
                                Finding(
                                    check="SC002",
                                    path=(
                                        "distributed_tensorflow_tpu/"
                                        "serve/engine.py"
                                    ),
                                    line=0,
                                    scope=(
                                        f"{engine_cls.__name__}"
                                        "._plan_prefix_cache"
                                    ),
                                    message=(
                                        f"prefix-cache plan mb={mb} "
                                        f"block_tokens={bt} on preset "
                                        f"'{name}' layout tp={tp} raised "
                                        f"{type(exc).__name__} instead of "
                                        f"a clean ValueError: {exc}"
                                    ),
                                )
                            )
                            plans.append({
                                "mb": mb, "block_tokens": bt,
                                "raised": type(exc).__name__,
                            })
                    # Same contract for the speculative-decoding plan
                    # (serve/spec.py + the verify grid cell): each variant
                    # plans a draft width or rejects with a clean
                    # ValueError at startup, never a runtime shape error.
                    cell["speculation"] = splans = []
                    for sk, mm, mnt in SPEC_VARIANTS:
                        try:
                            k = engine_cls._plan_spec(
                                cfg, tp=tp, spec_tokens=sk,
                                min_match=mm, max_new_tokens=mnt,
                            )
                            splans.append({
                                "spec_tokens": sk, "min_match": mm,
                                "max_new_tokens": mnt, "k": k,
                            })
                        except ValueError as exc:
                            splans.append({
                                "spec_tokens": sk, "min_match": mm,
                                "max_new_tokens": mnt,
                                "rejects": str(exc),
                            })
                        except Exception as exc:
                            findings.append(
                                Finding(
                                    check="SC002",
                                    path=(
                                        "distributed_tensorflow_tpu/"
                                        "serve/engine.py"
                                    ),
                                    line=0,
                                    scope=(
                                        f"{engine_cls.__name__}"
                                        "._plan_spec"
                                    ),
                                    message=(
                                        f"speculation plan k={sk} "
                                        f"min_match={mm} on preset "
                                        f"'{name}' layout tp={tp} raised "
                                        f"{type(exc).__name__} instead of "
                                        f"a clean ValueError: {exc}"
                                    ),
                                )
                            )
                            splans.append({
                                "spec_tokens": sk, "min_match": mm,
                                "max_new_tokens": mnt,
                                "raised": type(exc).__name__,
                            })
                    # And the disaggregated role split (parallel/mesh.py
                    # plan_disagg_mesh): every role-split variant on this
                    # topology must return a plan (fallbacks noted) or
                    # reject with a clean ValueError — a split that only
                    # dies when the role engines build would be a raw
                    # startup crash on a disaggregated fleet.
                    cell["disagg"] = dplans = []
                    for pd, ptp, dtp in DISAGG_VARIANTS:
                        try:
                            plan = plan_disagg_mesh(
                                n_devices, prefill_devices=pd,
                                prefill_tp=ptp, decode_tp=dtp,
                            )
                            dplans.append({
                                "prefill_devices": pd, "prefill_tp": ptp,
                                "decode_tp": dtp,
                                "prefill": len(plan.prefill_device_ids),
                                "decode": len(plan.decode_device_ids),
                                "fell_back": plan.fell_back,
                                "notes": len(plan.notes),
                            })
                        except ValueError as exc:
                            dplans.append({
                                "prefill_devices": pd, "prefill_tp": ptp,
                                "decode_tp": dtp, "rejects": str(exc),
                            })
                        except Exception as exc:
                            findings.append(
                                Finding(
                                    check="SC002",
                                    path=(
                                        "distributed_tensorflow_tpu/"
                                        "parallel/mesh.py"
                                    ),
                                    line=0,
                                    scope="plan_disagg_mesh",
                                    message=(
                                        f"disagg role split "
                                        f"prefill_devices={pd} "
                                        f"prefill_tp={ptp} decode_tp={dtp} "
                                        f"on {n_devices} devices raised "
                                        f"{type(exc).__name__} instead of "
                                        f"a plan or a clean ValueError: "
                                        f"{exc}"
                                    ),
                                )
                            )
                            dplans.append({
                                "prefill_devices": pd, "prefill_tp": ptp,
                                "decode_tp": dtp,
                                "raised": type(exc).__name__,
                            })
            except ValueError as exc:
                # Designed loud rejection (clean startup error, no XLA trace).
                cell["outcome"] = "rejects"
                cell["reason"] = str(exc)
            except Exception as exc:
                findings.append(
                    Finding(
                        check="SC002",
                        path="distributed_tensorflow_tpu/serve/engine.py",
                        line=0,
                        scope=f"{engine_cls.__name__}._serve_config",
                        message=(
                            f"layout tp={tp} pp={pp} ep={ep} on preset '{name}' "
                            f"raised {type(exc).__name__} instead of a clean "
                            f"ValueError: {exc}"
                        ),
                    )
                )
                cell["outcome"] = f"raised:{type(exc).__name__}"
            matrix.append(cell)
    return findings, matrix
