"""jaxlint: AST checks for JAX correctness pitfalls.

Rules
-----
JL001  PRNG key reuse — the same key variable fed to two consuming
       ``jax.random.*`` calls without an intervening split/fold_in, or
       consumed inside a loop without per-iteration derivation.
JL002  Host-side effect inside a traced function — ``print``/``time.*``/
       ``input``/``open``/``breakpoint`` calls, or mutation of closed-over
       state (``global``/``nonlocal`` writes, ``.append`` etc. on
       non-local names), in any function that is jitted/shard_mapped or
       used as a ``lax.scan``/``grad`` body in the same module.
JL003  Blocking transfer (``jax.device_get``, ``.block_until_ready()``,
       ``np.asarray`` on a traced value) inside a designated hot-path
       module — these modules pipeline dispatch and must only block at
       their one designated fetch point.
JL004  Python ``if``/``while`` on a tracer-derived value inside a traced
       function. Shape/dtype/structure inspection (``.shape``, ``len``,
       ``isinstance``, ``is None``) launders the taint — those branches
       are resolved at trace time and are fine.

Detection of "traced function" is module-local and name-based: functions
passed (by name) to ``jax.jit``/``shard_map``/``pmap``/``grad``/
``value_and_grad``/``lax.scan``/``lax.while_loop``/``lax.fori_loop``/
``checkpoint``, or decorated with jit/shard_map/partial(jit, ...).
"""

from __future__ import annotations

import ast
from typing import Iterable

from .findings import Finding, ScopeIndex, SourceFile, dotted_name

__all__ = ["run", "CHECKS", "HOT_MODULES"]

CHECKS = ("JL001", "JL002", "JL003", "JL004")

# Modules whose steady-state loop must never block on device transfers
# except at their designated fetch point (baselined explicitly).
HOT_MODULES = (
    "train/loop.py",
    "serve/engine.py",
    "serve/batcher.py",
    "data/prefetch.py",
)

# jax.random.* functions that DERIVE keys rather than consume randomness.
_KEY_DERIVERS = {
    "key",
    "PRNGKey",
    "split",
    "fold_in",
    "clone",
    "key_data",
    "wrap_key_data",
    "key_impl",
}

# Callables that trace their function argument(s).
_TRACING_CALLS = {
    "jax.jit",
    "jit",
    "jax.pmap",
    "pmap",
    "jax.shard_map",
    "shard_map",
    "jax.experimental.shard_map.shard_map",
    "jax.grad",
    "jax.value_and_grad",
    "jax.vmap",
    "jax.checkpoint",
    "jax.remat",
    "jax.lax.scan",
    "lax.scan",
    "jax.lax.while_loop",
    "lax.while_loop",
    "jax.lax.fori_loop",
    "lax.fori_loop",
    "jax.lax.cond",
    "lax.cond",
    "jax.eval_shape",
}

_JIT_DECORATORS = {"jit", "jax.jit", "pmap", "jax.pmap", "shard_map", "jax.shard_map"}

_HOST_EFFECT_CALLS = {
    "print",
    "input",
    "breakpoint",
    "open",
    "time.time",
    "time.perf_counter",
    "time.monotonic",
    "time.sleep",
    "time.process_time",
}

_MUTATING_METHODS = {"append", "extend", "add", "update", "insert", "setdefault", "pop"}

# Attribute/call forms that convert a tracer into a static Python value.
_LAUNDER_ATTRS = {"shape", "dtype", "ndim", "size", "aval", "sharding", "itemsize"}
_LAUNDER_CALLS = {"len", "isinstance", "type", "getattr", "hasattr", "id", "repr", "str"}


def run(sources: Iterable[SourceFile]) -> list[Finding]:
    findings: list[Finding] = []
    for src in sources:
        scopes = ScopeIndex(src.tree)
        findings.extend(_check_key_reuse(src, scopes))
        traced = _traced_functions(src.tree)
        for fn in traced:
            findings.extend(_check_host_effects(src, scopes, fn))
            findings.extend(_check_tracer_branch(src, scopes, fn))
        if any(src.rel.endswith(m) for m in HOT_MODULES):
            findings.extend(_check_blocking_transfers(src, scopes))
    return findings


# ---------------------------------------------------------------- JL001


def _is_key_deriver(name: str) -> bool:
    return name.rsplit(".", 1)[-1] in _KEY_DERIVERS


def _check_key_reuse(src: SourceFile, scopes: ScopeIndex) -> list[Finding]:
    findings: list[Finding] = []
    for fn in _all_functions(src.tree):
        findings.extend(_key_reuse_in_function(src, scopes, fn))
    return findings


def _key_reuse_in_function(
    src: SourceFile, scopes: ScopeIndex, fn: ast.FunctionDef | ast.AsyncFunctionDef
) -> list[Finding]:
    # Event stream: (line, col, kind, name). kind in {assign, consume}.
    events: list[tuple[int, int, str, str]] = []
    key_names: set[str] = set()
    loops: list[ast.For | ast.While] = []

    for node in ast.walk(fn):
        if isinstance(node, (ast.For, ast.While)) and node is not fn:
            loops.append(node)
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            callee = dotted_name(node.value.func) or ""
            if ("random" in callee and _is_key_deriver(callee)) or callee.endswith(
                "make_rng"
            ):
                for tgt in node.targets:
                    for name in _target_names(tgt):
                        key_names.add(name)
                        events.append((node.lineno, node.col_offset, "assign", name))
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                for name in _target_names(tgt):
                    events.append((node.lineno, node.col_offset, "assign", name))
        if isinstance(node, ast.Call):
            callee = dotted_name(node.func) or ""
            if (
                callee.startswith(("jax.random.", "random.", "jrandom.", "jr."))
                and not _is_key_deriver(callee)
                and node.args
                and isinstance(node.args[0], ast.Name)
            ):
                events.append(
                    (node.lineno, node.col_offset, "consume", node.args[0].id)
                )

    events.sort(key=lambda e: (e[0], e[1]))
    consumed_at: dict[str, int] = {}
    findings: list[Finding] = []
    for line, _col, kind, name in events:
        if kind == "assign":
            consumed_at.pop(name, None)
        elif name in key_names:
            if name in consumed_at:
                findings.append(
                    Finding(
                        check="JL001",
                        path=src.rel,
                        line=line,
                        scope=scopes.lookup(line),
                        message=(
                            f"PRNG key '{name}' already consumed at line "
                            f"{consumed_at[name]}; split or fold_in before reuse"
                        ),
                    )
                )
            consumed_at[name] = line

    # Loop-carried reuse: key consumed inside a loop body but never
    # re-derived inside that body — every iteration samples identically.
    for loop in loops:
        assigned: set[str] = set()
        for node in ast.walk(loop):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    assigned.update(_target_names(tgt))
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                assigned.update(_target_names(node.target))
        for node in ast.walk(loop):
            if isinstance(node, ast.Call):
                callee = dotted_name(node.func) or ""
                if (
                    callee.startswith(("jax.random.", "jrandom.", "jr."))
                    and not _is_key_deriver(callee)
                    and node.args
                    and isinstance(node.args[0], ast.Name)
                ):
                    name = node.args[0].id
                    if name in key_names and name not in assigned:
                        findings.append(
                            Finding(
                                check="JL001",
                                path=src.rel,
                                line=node.lineno,
                                scope=scopes.lookup(node.lineno),
                                message=(
                                    f"PRNG key '{name}' consumed in a loop without "
                                    "per-iteration split/fold_in"
                                ),
                            )
                        )
    return findings


# ---------------------------------------------------------------- traced-fn set


def _all_functions(tree: ast.AST) -> list[ast.FunctionDef | ast.AsyncFunctionDef]:
    return [
        n
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]


def _traced_functions(tree: ast.Module) -> list[ast.FunctionDef | ast.AsyncFunctionDef]:
    traced_names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            callee = dotted_name(node.func) or ""
            if callee in _TRACING_CALLS:
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    if isinstance(arg, ast.Name):
                        traced_names.add(arg.id)

    out = []
    for fn in _all_functions(tree):
        if fn.name in traced_names:
            out.append(fn)
            continue
        for dec in fn.decorator_list:
            d = dec
            if isinstance(d, ast.Call):  # @partial(jit, ...) / @jit(...)
                inner = dotted_name(d.func) or ""
                if inner in _JIT_DECORATORS:
                    out.append(fn)
                    break
                if inner in {"partial", "functools.partial"} and d.args:
                    first = dotted_name(d.args[0]) or ""
                    if first in _JIT_DECORATORS:
                        out.append(fn)
                        break
            elif (dotted_name(d) or "") in _JIT_DECORATORS:
                out.append(fn)
                break
    return out


# ---------------------------------------------------------------- JL002


def _check_host_effects(
    src: SourceFile, scopes: ScopeIndex, fn: ast.FunctionDef | ast.AsyncFunctionDef
) -> list[Finding]:
    findings: list[Finding] = []
    local_names = _local_names(fn)
    global_writes: set[str] = set()
    # Mutation-style calls only count when the result is discarded — a
    # statement-level `seen.append(x)` mutates; `new, st = tx.update(...)`
    # is a pure functional API that happens to be named "update".
    stmt_calls = {
        id(node.value)
        for node in ast.walk(fn)
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call)
    }
    for node in ast.walk(fn):
        if isinstance(node, (ast.Global, ast.Nonlocal)):
            global_writes.update(node.names)

    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            callee = dotted_name(node.func) or ""
            if callee in _HOST_EFFECT_CALLS:
                findings.append(
                    Finding(
                        check="JL002",
                        path=src.rel,
                        line=node.lineno,
                        scope=scopes.lookup(node.lineno),
                        message=(
                            f"host-side effect '{callee}()' inside traced "
                            f"function '{fn.name}' runs at trace time only"
                        ),
                    )
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATING_METHODS
                and id(node) in stmt_calls
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id not in local_names
            ):
                findings.append(
                    Finding(
                        check="JL002",
                        path=src.rel,
                        line=node.lineno,
                        scope=scopes.lookup(node.lineno),
                        message=(
                            f"mutation of closed-over '{node.func.value.id}."
                            f"{node.func.attr}()' inside traced function "
                            f"'{fn.name}' happens once at trace time"
                        ),
                    )
                )
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for tgt in targets:
                for name in _target_names(tgt):
                    if name in global_writes:
                        findings.append(
                            Finding(
                                check="JL002",
                                path=src.rel,
                                line=node.lineno,
                                scope=scopes.lookup(node.lineno),
                                message=(
                                    f"write to global/nonlocal '{name}' inside "
                                    f"traced function '{fn.name}'"
                                ),
                            )
                        )
    return findings


# ---------------------------------------------------------------- JL003


_BLOCKING_TRANSFER_CALLS = {"jax.device_get", "jax.block_until_ready"}


def _check_blocking_transfers(src: SourceFile, scopes: ScopeIndex) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = dotted_name(node.func) or ""
        hit = None
        if callee in _BLOCKING_TRANSFER_CALLS:
            hit = f"{callee}()"
        elif isinstance(node.func, ast.Attribute) and node.func.attr == "block_until_ready":
            hit = ".block_until_ready()"
        elif callee in {"np.asarray", "numpy.asarray", "np.array", "numpy.array"}:
            # Only flag when the argument *names* a device-side value —
            # np.asarray on request payloads (lists/JSON) is host-only and
            # exactly what the assemble phase is for.
            if node.args and _looks_device_side(node.args[0]):
                hit = f"{callee}() (implicit device→host copy)"
        if hit:
            findings.append(
                Finding(
                    check="JL003",
                    path=src.rel,
                    line=node.lineno,
                    scope=scopes.lookup(node.lineno),
                    message=(
                        f"blocking transfer {hit} in hot-path module; only the "
                        "designated fetch point may block"
                    ),
                )
            )
    return findings


_DEVICE_NAME_HINTS = ("device", "dev_", "_dev", "out_ref", "in_flight", "on_chip")


def _looks_device_side(arg: ast.expr) -> bool:
    name = dotted_name(arg) or ""
    low = name.lower()
    return any(h in low for h in _DEVICE_NAME_HINTS)


# ---------------------------------------------------------------- JL004


def _check_tracer_branch(
    src: SourceFile, scopes: ScopeIndex, fn: ast.FunctionDef | ast.AsyncFunctionDef
) -> list[Finding]:
    tainted: set[str] = {a.arg for a in fn.args.args + fn.args.kwonlyargs}
    if fn.args.vararg:
        tainted.add(fn.args.vararg.arg)
    tainted.discard("self")

    # One forward propagation pass in source order (good enough for the
    # straight-line style of traced step functions).
    for node in sorted(
        (n for n in ast.walk(fn) if isinstance(n, ast.Assign)),
        key=lambda n: (n.lineno, n.col_offset),
    ):
        rhs_tainted = _expr_tainted(node.value, tainted)
        for tgt in node.targets:
            for name in _target_names(tgt):
                if rhs_tainted:
                    tainted.add(name)
                else:
                    tainted.discard(name)

    findings: list[Finding] = []
    for node in ast.walk(fn):
        if not isinstance(node, (ast.If, ast.While)):
            continue
        if _test_is_static(node.test):
            continue
        if _expr_tainted(node.test, tainted):
            kind = "if" if isinstance(node, ast.If) else "while"
            findings.append(
                Finding(
                    check="JL004",
                    path=src.rel,
                    line=node.lineno,
                    scope=scopes.lookup(node.lineno),
                    message=(
                        f"Python '{kind}' on a tracer-derived value inside traced "
                        f"function '{fn.name}'; use lax.cond/jnp.where"
                    ),
                )
            )
    return findings


def _test_is_static(test: ast.expr) -> bool:
    """`is None` / isinstance / len / shape comparisons resolve at trace time."""
    if isinstance(test, ast.Compare) and any(
        isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
    ):
        return True
    for node in ast.walk(test):
        if isinstance(node, ast.Call):
            callee = dotted_name(node.func) or ""
            if callee in _LAUNDER_CALLS:
                return True
        if isinstance(node, ast.Attribute) and node.attr in _LAUNDER_ATTRS:
            return True
    return False


def _expr_tainted(expr: ast.expr, tainted: set[str]) -> bool:
    if _contains_launder(expr):
        return False
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id in tainted:
            return True
    return False


def _contains_launder(expr: ast.expr) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr in _LAUNDER_ATTRS:
            return True
        if isinstance(node, ast.Call):
            callee = dotted_name(node.func) or ""
            if callee in _LAUNDER_CALLS:
                return True
    return False


# ---------------------------------------------------------------- shared helpers


def _target_names(tgt: ast.expr) -> list[str]:
    if isinstance(tgt, ast.Name):
        return [tgt.id]
    if isinstance(tgt, (ast.Tuple, ast.List)):
        out: list[str] = []
        for elt in tgt.elts:
            out.extend(_target_names(elt))
        return out
    if isinstance(tgt, ast.Starred):
        return _target_names(tgt.value)
    return []


def _local_names(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> set[str]:
    names = {a.arg for a in fn.args.args + fn.args.kwonlyargs + fn.args.posonlyargs}
    if fn.args.vararg:
        names.add(fn.args.vararg.arg)
    if fn.args.kwarg:
        names.add(fn.args.kwarg.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                names.update(_target_names(tgt))
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            names.update(_target_names(node.target))
        elif isinstance(node, (ast.For,)):
            names.update(_target_names(node.target))
        elif isinstance(node, ast.comprehension):
            names.update(_target_names(node.target))
        elif isinstance(node, ast.With):
            for item in node.items:
                if item.optional_vars is not None:
                    names.update(_target_names(item.optional_vars))
    return names
