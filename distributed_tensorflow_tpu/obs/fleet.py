"""Fleet health: per-step timelines, straggler detection, host beacons.

The paper's premise is a cluster that keeps making progress while
individual roles degrade — this module is how a modern SPMD job *sees*
that degradation (ROADMAP item 3's visibility substrate):

- :class:`StepTimeline` — the train-loop recorder (``fit(timeline=...)``
  feeds it): per-step wall/host-wait/dispatch durations into windowed
  series (obs/timeseries.py) plus a bounded recent-step ring, with an
  in-line :class:`StragglerDetector` flagging anomalies as they happen.
- :class:`StragglerDetector` — self-relative anomaly detection: a step
  is *slow* when it exceeds ``ratio`` x the trailing median of the
  host's own recent steps; a *host-wait regression* is the analogous
  test on the feed-wait series (with an absolute floor so microsecond
  jitter on an idle feed never flags).  Trailing-median, not mean: one
  checkpoint save must not shift the baseline.
- :class:`HostBeacon` — the per-host health summary, written as one JSON
  file per host (atomic rename) into a shared directory.  Processes
  never talk to each other: the aggregation side —
  :func:`read_beacons` / :func:`fleet_summary` /
  :func:`detect_fleet_stragglers` — runs wherever the files are visible
  (the chief, a monitor, the test harness).  A host is a *fleet*
  straggler when its median step time exceeds ``ratio`` x the median of
  the OTHER hosts' medians — cross-host-relative, so a uniformly slow
  fleet (bigger model) flags nobody while one 5x host flags alone.

No threads anywhere: recording is done by the train loop's own thread,
beacon writes happen at the loop's log cadence, aggregation is pull.
"""

from __future__ import annotations

import json
import os
import statistics
import threading
import time
from collections import deque
from pathlib import Path

from distributed_tensorflow_tpu.obs.timeseries import (
    DEFAULT_STEP_BOUNDS,
    WindowedHistogram,
)


class StragglerDetector:
    """Self-relative slow-step / feed-regression detector.

    ``observe`` compares each step against the trailing median of the
    PRIOR ``window`` steps (the current step never dilutes its own
    baseline) and returns an anomaly record or ``None``.  Anomalies are
    also kept in a bounded ring (``anomalies``) for the beacon.
    """

    def __init__(
        self,
        window: int = 64,
        min_history: int = 8,
        step_ratio: float = 3.0,
        host_wait_ratio: float = 4.0,
        min_host_wait_s: float = 0.005,
        max_anomalies: int = 128,
    ):
        if window < min_history:
            raise ValueError("window must be >= min_history")
        self._lock = threading.Lock()
        self.window = window
        self.min_history = min_history
        self.step_ratio = step_ratio
        self.host_wait_ratio = host_wait_ratio
        self.min_host_wait_s = min_host_wait_s
        self._steps: deque[float] = deque(maxlen=window)
        self._waits: deque[float] = deque(maxlen=window)
        self.anomalies: deque[dict] = deque(maxlen=max_anomalies)

    def observe(
        self, step: int, step_s: float, host_wait_s: float = 0.0
    ) -> dict | None:
        with self._lock:
            anomaly = None
            if len(self._steps) >= self.min_history:
                med = statistics.median(self._steps)
                if med > 0 and step_s > self.step_ratio * med:
                    anomaly = {
                        "kind": "slow_step",
                        "step": step,
                        "step_s": step_s,
                        "trailing_median_s": med,
                        "ratio": step_s / med,
                    }
                elif (
                    host_wait_s > self.min_host_wait_s
                    and host_wait_s
                    > self.host_wait_ratio
                    * max(statistics.median(self._waits), self.min_host_wait_s)
                ):
                    anomaly = {
                        "kind": "host_wait_regression",
                        "step": step,
                        "host_wait_s": host_wait_s,
                        "trailing_median_s": statistics.median(self._waits),
                    }
            self._steps.append(step_s)
            self._waits.append(host_wait_s)
            if anomaly is not None:
                self.anomalies.append(anomaly)
            return anomaly

    def summary(self) -> dict:
        with self._lock:
            kinds: dict[str, int] = {}
            for a in self.anomalies:
                kinds[a["kind"]] = kinds.get(a["kind"], 0) + 1
            return {
                "anomaly_counts": kinds,
                "recent_anomalies": list(self.anomalies)[-8:],
            }


class StepTimeline:
    """Per-step phase recorder feeding windowed series + the detector.

    ``record_step`` is the single entry point the train loop calls once
    per step with the durations it already measures (host_wait) plus the
    step wall and dispatch times.  Reads (``summary``) are safe from any
    thread — the beacon writer and the recording loop may interleave.
    """

    def __init__(
        self,
        detector: StragglerDetector | None = None,
        history: int = 512,
        max_window_s: float = 300.0,
        clock=time.monotonic,
    ):
        self._lock = threading.Lock()
        self._clock = clock
        self.step_time = WindowedHistogram(
            bounds=DEFAULT_STEP_BOUNDS, max_window_s=max_window_s, clock=clock
        )
        self.host_wait = WindowedHistogram(
            bounds=DEFAULT_STEP_BOUNDS, max_window_s=max_window_s, clock=clock
        )
        self.dispatch = WindowedHistogram(
            bounds=DEFAULT_STEP_BOUNDS, max_window_s=max_window_s, clock=clock
        )
        self.detector = detector or StragglerDetector()
        self._recent: deque[tuple] = deque(maxlen=history)
        self._last_step = -1

    def record_step(
        self,
        step: int,
        step_s: float,
        host_wait_s: float = 0.0,
        dispatch_s: float = 0.0,
        now: float | None = None,
    ) -> dict | None:
        """Record one step; returns the detector's anomaly (if any)."""
        now = self._clock() if now is None else now
        self.step_time.observe(step_s, now)
        self.host_wait.observe(host_wait_s, now)
        self.dispatch.observe(dispatch_s, now)
        with self._lock:
            self._recent.append((step, step_s, host_wait_s, dispatch_s))
            self._last_step = max(self._last_step, step)
        return self.detector.observe(step, step_s, host_wait_s)

    @property
    def last_step(self) -> int:
        with self._lock:
            return self._last_step

    def summary(self, window_s: float = 60.0, now: float | None = None) -> dict:
        """The beacon body: windowed step/wait distributions + anomalies."""
        now = self._clock() if now is None else now
        step_w = self.step_time.window_summary(window_s, now)
        wait_w = self.host_wait.window_summary(window_s, now)
        return {
            "last_step": self.last_step,
            "window_s": window_s,
            "steps_per_sec": step_w["rate"],
            "step_s": {k: step_w[k] for k in ("count", "p50", "p90", "p99")},
            "host_wait_s": {
                k: wait_w[k] for k in ("count", "p50", "p90", "p99")
            },
            # Raw mergeable counts so the aggregator can compute fleet
            # quantiles without re-observing anything.
            "step_counts": self.step_time.window_counts(window_s, now),
            "step_bounds": list(self.step_time.bounds),
            **self.detector.summary(),
        }


class HostBeacon:
    """One host's health file in the shared beacon directory.

    ``write()`` snapshots the timeline summary and atomically replaces
    ``<dir>/host_<id>.json`` — readers never see a torn file.  Call it
    from a fit hook at the log cadence (cli/train.py --beacon-dir wires
    exactly that).
    """

    def __init__(
        self,
        beacon_dir: str | Path,
        host_id: int,
        timeline: StepTimeline,
        window_s: float = 60.0,
        extras=None,
    ):
        self.dir = Path(beacon_dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.host_id = int(host_id)
        self.timeline = timeline
        self.window_s = window_s
        # extras() -> dict, merged into every summary — e.g. a
        # FaultInjector's fired-event ledger (train/faultinject.py), so a
        # chaos run's injections travel the same signal path real
        # degradation would.
        self.extras = extras
        self.path = self.dir / f"host_{self.host_id}.json"

    def summary(self) -> dict:
        out = {
            "host": self.host_id,
            "wall_time": time.time(),
            **self.timeline.summary(self.window_s),
        }
        if self.extras is not None:
            out.update(self.extras())
        return out

    def write(self) -> Path:
        tmp = self.path.with_suffix(".json.tmp")
        tmp.write_text(json.dumps(self.summary()))
        os.replace(tmp, self.path)  # atomic on POSIX
        return self.path


def read_beacons(beacon_dir: str | Path) -> list[dict]:
    """All parseable host beacons in the directory, sorted by host id."""
    out = []
    for p in sorted(Path(beacon_dir).glob("host_*.json")):
        try:
            out.append(json.loads(p.read_text()))
        except (OSError, json.JSONDecodeError):
            continue  # mid-replace or vanished: next poll sees it
    return out


def detect_fleet_stragglers(
    beacons: list[dict], ratio: float = 2.0
) -> list[int]:
    """Host ids whose median step time exceeds ``ratio`` x the median of
    the OTHER hosts' medians.

    Cross-host-relative on purpose: a uniformly slow fleet (bigger model,
    colder cache) flags nobody; one seeded-5x host flags alone even in a
    2-host fleet (where a global median would be dragged halfway up by
    the straggler itself).
    """
    meds = {
        int(b["host"]): b["step_s"]["p50"]
        for b in beacons
        if b.get("step_s", {}).get("count", 0) > 0
    }
    if len(meds) < 2:
        return []
    flagged = []
    for host, med in meds.items():
        others = [m for h, m in meds.items() if h != host]
        baseline = statistics.median(others)
        if baseline > 0 and med > ratio * baseline:
            flagged.append(host)
    return sorted(flagged)


def fleet_summary(beacons: list[dict], ratio: float = 2.0) -> dict:
    """The aggregated fleet view: per-host digests + straggler verdict."""
    stragglers = detect_fleet_stragglers(beacons, ratio)
    hosts = []
    for b in sorted(beacons, key=lambda x: x.get("host", -1)):
        host = int(b.get("host", -1))
        hosts.append({
            "host": host,
            "last_step": b.get("last_step"),
            "median_step_s": b.get("step_s", {}).get("p50"),
            "p99_step_s": b.get("step_s", {}).get("p99"),
            "steps_per_sec": b.get("steps_per_sec"),
            "anomaly_counts": b.get("anomaly_counts", {}),
            "straggler": host in stragglers,
        })
    return {
        "n_hosts": len(hosts),
        "stragglers": stragglers,
        "straggler_ratio": ratio,
        "hosts": hosts,
    }


class FleetSupervisor:
    """Beacon consumer deciding restart-vs-re-mesh (the reaction half).

    Poll-based and threadless like everything else here: call
    :meth:`poll` from wherever the beacon files are visible (a monitor, a
    relaunch wrapper, the chaos test harness). Per poll, each expected
    host is classified by its beacon's ``wall_time`` freshness:

    - a host with no beacon, or one older than ``heartbeat_timeout_s``,
      is **lost** → ``action: "re_mesh"`` — the survivors should replan
      onto the remaining devices
      (``parallel.mesh.plan_elastic_mesh(surviving)``) and resume via
      ``train.resilience.run_resilient``;
    - no losses but a fleet straggler (cross-host-relative, see
      :func:`detect_fleet_stragglers`) → ``action: "restart"`` — same
      topology, restart the slow host before it drags the collective;
    - otherwise ``action: "none"``.

    ``expected_hosts`` is an int (hosts 0..n-1) or an iterable of ids;
    without it, every host EVER seen is expected — a beacon that appears
    and then goes stale still counts as lost. Newly-lost hosts are
    recorded to ``recorder`` as ``host_lost`` events (once per loss, not
    per poll).
    """

    def __init__(
        self,
        beacon_dir: str | Path,
        *,
        expected_hosts=None,
        heartbeat_timeout_s: float = 30.0,
        straggler_ratio: float = 2.0,
        clock=time.time,
        recorder=None,
    ):
        self.dir = Path(beacon_dir)
        if isinstance(expected_hosts, int):
            expected_hosts = range(expected_hosts)
        self.expected: set[int] | None = (
            {int(h) for h in expected_hosts} if expected_hosts is not None else None
        )
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.straggler_ratio = straggler_ratio
        self._clock = clock
        self._recorder = recorder
        self._seen: set[int] = set()
        self._reported_lost: set[int] = set()

    def poll(self, now: float | None = None) -> dict:
        """One classification pass over the beacon directory."""
        now = self._clock() if now is None else now
        by_host = {}
        for b in read_beacons(self.dir):
            try:
                by_host[int(b["host"])] = b
            except (KeyError, TypeError, ValueError):
                continue
        self._seen |= set(by_host)
        expected = self.expected if self.expected is not None else self._seen
        alive, lost = [], []
        for h in sorted(expected):
            b = by_host.get(h)
            age = now - b.get("wall_time", 0.0) if b is not None else None
            if b is None or age > self.heartbeat_timeout_s:
                lost.append(h)
            else:
                alive.append(h)
        stragglers = detect_fleet_stragglers(
            [by_host[h] for h in alive], self.straggler_ratio
        )
        if self._recorder is not None:
            for h in lost:
                if h not in self._reported_lost:
                    self._recorder.record(
                        "host_lost", host=h,
                        last_step=by_host.get(h, {}).get("last_step", -1),
                    )
        self._reported_lost = set(lost)
        action = "re_mesh" if lost else ("restart" if stragglers else "none")
        return {
            "action": action,
            "lost_hosts": lost,
            "alive_hosts": alive,
            "stragglers": stragglers,
            "n_expected": len(expected),
            "heartbeat_timeout_s": self.heartbeat_timeout_s,
        }


class ReplicaSupervisor:
    """Per-replica poll-history verdicts for the serving router.

    The serving analogue of :class:`FleetSupervisor`, with the restart
    budget of ``train.resilience`` (which this module must not import —
    it pulls in jax at module scope; the router stays stdlib-only).  The
    semantics are the same on purpose:

    - **progress-aware budget**: a replica that comes back *ready* after
      a restart resets its consecutive-restart count, exactly as a
      training restart that advances ``resume_step`` does — only
      back-to-back failures with no intervening ready burn the budget;
    - **exponential backoff**: restart *n* waits
      ``min(base * factor**(n-1), cap)`` seconds, matching
      ``ResilienceConfig.backoff_s``.

    Threadless and poll-based like everything else here: the router's
    poll loop feeds :meth:`record_poll` / :meth:`record_ready` /
    :meth:`record_restart` and reads :meth:`verdict`:

    - fewer than ``fail_threshold`` consecutive failed polls →
      ``"none"`` (one dropped poll on a busy box must not bounce a
      healthy replica);
    - threshold reached with restart budget remaining → ``"restart"``;
    - budget exhausted → ``"quarantine"`` — the replica is left down and
      the fleet routes around it (restarting a replica that dies
      instantly N times just feeds it traffic to drop).
    """

    def __init__(
        self,
        *,
        fail_threshold: int = 3,
        max_restarts: int = 3,
        backoff_base_s: float = 0.5,
        backoff_factor: float = 2.0,
        backoff_max_s: float = 30.0,
    ):
        if fail_threshold < 1:
            raise ValueError("fail_threshold must be >= 1")
        if max_restarts < 0:
            raise ValueError("max_restarts must be >= 0")
        self.fail_threshold = int(fail_threshold)
        self.max_restarts = int(max_restarts)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_factor = float(backoff_factor)
        self.backoff_max_s = float(backoff_max_s)
        self._consecutive_fails = 0
        self._consecutive_restarts = 0
        self._total_restarts = 0
        self._ready_since_restart = False

    def record_poll(self, ok: bool) -> None:
        """One health-poll outcome (True = got a well-formed response)."""
        self._consecutive_fails = 0 if ok else self._consecutive_fails + 1

    def record_ready(self) -> None:
        """The replica reached *ready*: progress. Resets the consecutive
        restart count so the budget only bounds back-to-back failures."""
        self._consecutive_restarts = 0
        self._ready_since_restart = True

    def record_restart(self) -> float:
        """Account one restart; returns the backoff to wait before it."""
        self._consecutive_restarts += 1
        self._total_restarts += 1
        self._consecutive_fails = 0
        self._ready_since_restart = False
        n = self._consecutive_restarts
        return min(
            self.backoff_base_s * self.backoff_factor ** max(n - 1, 0),
            self.backoff_max_s,
        )

    def verdict(self) -> str:
        """``"none"`` / ``"restart"`` / ``"quarantine"`` for this poll."""
        if self._consecutive_fails < self.fail_threshold:
            return "none"
        if self._consecutive_restarts >= self.max_restarts:
            return "quarantine"
        return "restart"

    def summary(self) -> dict:
        return {
            "consecutive_fails": self._consecutive_fails,
            "consecutive_restarts": self._consecutive_restarts,
            "total_restarts": self._total_restarts,
            "ready_since_restart": self._ready_since_restart,
            "max_restarts": self.max_restarts,
            "verdict": self.verdict(),
        }
