"""Fleet health: per-step timelines, straggler detection, host beacons.

The paper's premise is a cluster that keeps making progress while
individual roles degrade — this module is how a modern SPMD job *sees*
that degradation (ROADMAP item 3's visibility substrate):

- :class:`StepTimeline` — the train-loop recorder (``fit(timeline=...)``
  feeds it): per-step wall/host-wait/dispatch durations into windowed
  series (obs/timeseries.py) plus a bounded recent-step ring, with an
  in-line :class:`StragglerDetector` flagging anomalies as they happen.
- :class:`StragglerDetector` — self-relative anomaly detection: a step
  is *slow* when it exceeds ``ratio`` x the trailing median of the
  host's own recent steps; a *host-wait regression* is the analogous
  test on the feed-wait series (with an absolute floor so microsecond
  jitter on an idle feed never flags).  Trailing-median, not mean: one
  checkpoint save must not shift the baseline.
- :class:`HostBeacon` — the per-host health summary, written as one JSON
  file per host (atomic rename) into a shared directory.  Processes
  never talk to each other: the aggregation side —
  :func:`read_beacons` / :func:`fleet_summary` /
  :func:`detect_fleet_stragglers` — runs wherever the files are visible
  (the chief, a monitor, the test harness).  A host is a *fleet*
  straggler when its median step time exceeds ``ratio`` x the median of
  the OTHER hosts' medians — cross-host-relative, so a uniformly slow
  fleet (bigger model) flags nobody while one 5x host flags alone.

No threads anywhere: recording is done by the train loop's own thread,
beacon writes happen at the loop's log cadence, aggregation is pull.
"""

from __future__ import annotations

import json
import os
import statistics
import threading
import time
from collections import deque
from pathlib import Path

from distributed_tensorflow_tpu.obs.timeseries import (
    DEFAULT_STEP_BOUNDS,
    WindowedHistogram,
)


class StragglerDetector:
    """Self-relative slow-step / feed-regression detector.

    ``observe`` compares each step against the trailing median of the
    PRIOR ``window`` steps (the current step never dilutes its own
    baseline) and returns an anomaly record or ``None``.  Anomalies are
    also kept in a bounded ring (``anomalies``) for the beacon.
    """

    def __init__(
        self,
        window: int = 64,
        min_history: int = 8,
        step_ratio: float = 3.0,
        host_wait_ratio: float = 4.0,
        min_host_wait_s: float = 0.005,
        max_anomalies: int = 128,
    ):
        if window < min_history:
            raise ValueError("window must be >= min_history")
        self._lock = threading.Lock()
        self.window = window
        self.min_history = min_history
        self.step_ratio = step_ratio
        self.host_wait_ratio = host_wait_ratio
        self.min_host_wait_s = min_host_wait_s
        self._steps: deque[float] = deque(maxlen=window)
        self._waits: deque[float] = deque(maxlen=window)
        self.anomalies: deque[dict] = deque(maxlen=max_anomalies)

    def observe(
        self, step: int, step_s: float, host_wait_s: float = 0.0
    ) -> dict | None:
        with self._lock:
            anomaly = None
            if len(self._steps) >= self.min_history:
                med = statistics.median(self._steps)
                if med > 0 and step_s > self.step_ratio * med:
                    anomaly = {
                        "kind": "slow_step",
                        "step": step,
                        "step_s": step_s,
                        "trailing_median_s": med,
                        "ratio": step_s / med,
                    }
                elif (
                    host_wait_s > self.min_host_wait_s
                    and host_wait_s
                    > self.host_wait_ratio
                    * max(statistics.median(self._waits), self.min_host_wait_s)
                ):
                    anomaly = {
                        "kind": "host_wait_regression",
                        "step": step,
                        "host_wait_s": host_wait_s,
                        "trailing_median_s": statistics.median(self._waits),
                    }
            self._steps.append(step_s)
            self._waits.append(host_wait_s)
            if anomaly is not None:
                self.anomalies.append(anomaly)
            return anomaly

    def summary(self) -> dict:
        with self._lock:
            kinds: dict[str, int] = {}
            for a in self.anomalies:
                kinds[a["kind"]] = kinds.get(a["kind"], 0) + 1
            return {
                "anomaly_counts": kinds,
                "recent_anomalies": list(self.anomalies)[-8:],
            }


class StepTimeline:
    """Per-step phase recorder feeding windowed series + the detector.

    ``record_step`` is the single entry point the train loop calls once
    per step with the durations it already measures (host_wait) plus the
    step wall and dispatch times.  Reads (``summary``) are safe from any
    thread — the beacon writer and the recording loop may interleave.
    """

    def __init__(
        self,
        detector: StragglerDetector | None = None,
        history: int = 512,
        max_window_s: float = 300.0,
        clock=time.monotonic,
    ):
        self._lock = threading.Lock()
        self._clock = clock
        self.step_time = WindowedHistogram(
            bounds=DEFAULT_STEP_BOUNDS, max_window_s=max_window_s, clock=clock
        )
        self.host_wait = WindowedHistogram(
            bounds=DEFAULT_STEP_BOUNDS, max_window_s=max_window_s, clock=clock
        )
        self.dispatch = WindowedHistogram(
            bounds=DEFAULT_STEP_BOUNDS, max_window_s=max_window_s, clock=clock
        )
        self.detector = detector or StragglerDetector()
        self._recent: deque[tuple] = deque(maxlen=history)
        self._last_step = -1

    def record_step(
        self,
        step: int,
        step_s: float,
        host_wait_s: float = 0.0,
        dispatch_s: float = 0.0,
        now: float | None = None,
    ) -> dict | None:
        """Record one step; returns the detector's anomaly (if any)."""
        now = self._clock() if now is None else now
        self.step_time.observe(step_s, now)
        self.host_wait.observe(host_wait_s, now)
        self.dispatch.observe(dispatch_s, now)
        with self._lock:
            self._recent.append((step, step_s, host_wait_s, dispatch_s))
            self._last_step = max(self._last_step, step)
        return self.detector.observe(step, step_s, host_wait_s)

    @property
    def last_step(self) -> int:
        with self._lock:
            return self._last_step

    def summary(self, window_s: float = 60.0, now: float | None = None) -> dict:
        """The beacon body: windowed step/wait distributions + anomalies."""
        now = self._clock() if now is None else now
        step_w = self.step_time.window_summary(window_s, now)
        wait_w = self.host_wait.window_summary(window_s, now)
        return {
            "last_step": self.last_step,
            "window_s": window_s,
            "steps_per_sec": step_w["rate"],
            "step_s": {k: step_w[k] for k in ("count", "p50", "p90", "p99")},
            "host_wait_s": {
                k: wait_w[k] for k in ("count", "p50", "p90", "p99")
            },
            # Raw mergeable counts so the aggregator can compute fleet
            # quantiles without re-observing anything.
            "step_counts": self.step_time.window_counts(window_s, now),
            "step_bounds": list(self.step_time.bounds),
            **self.detector.summary(),
        }


class HostBeacon:
    """One host's health file in the shared beacon directory.

    ``write()`` snapshots the timeline summary and atomically replaces
    ``<dir>/host_<id>.json`` — readers never see a torn file.  Call it
    from a fit hook at the log cadence (cli/train.py --beacon-dir wires
    exactly that).
    """

    def __init__(
        self,
        beacon_dir: str | Path,
        host_id: int,
        timeline: StepTimeline,
        window_s: float = 60.0,
    ):
        self.dir = Path(beacon_dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.host_id = int(host_id)
        self.timeline = timeline
        self.window_s = window_s
        self.path = self.dir / f"host_{self.host_id}.json"

    def summary(self) -> dict:
        return {
            "host": self.host_id,
            "wall_time": time.time(),
            **self.timeline.summary(self.window_s),
        }

    def write(self) -> Path:
        tmp = self.path.with_suffix(".json.tmp")
        tmp.write_text(json.dumps(self.summary()))
        os.replace(tmp, self.path)  # atomic on POSIX
        return self.path


def read_beacons(beacon_dir: str | Path) -> list[dict]:
    """All parseable host beacons in the directory, sorted by host id."""
    out = []
    for p in sorted(Path(beacon_dir).glob("host_*.json")):
        try:
            out.append(json.loads(p.read_text()))
        except (OSError, json.JSONDecodeError):
            continue  # mid-replace or vanished: next poll sees it
    return out


def detect_fleet_stragglers(
    beacons: list[dict], ratio: float = 2.0
) -> list[int]:
    """Host ids whose median step time exceeds ``ratio`` x the median of
    the OTHER hosts' medians.

    Cross-host-relative on purpose: a uniformly slow fleet (bigger model,
    colder cache) flags nobody; one seeded-5x host flags alone even in a
    2-host fleet (where a global median would be dragged halfway up by
    the straggler itself).
    """
    meds = {
        int(b["host"]): b["step_s"]["p50"]
        for b in beacons
        if b.get("step_s", {}).get("count", 0) > 0
    }
    if len(meds) < 2:
        return []
    flagged = []
    for host, med in meds.items():
        others = [m for h, m in meds.items() if h != host]
        baseline = statistics.median(others)
        if baseline > 0 and med > ratio * baseline:
            flagged.append(host)
    return sorted(flagged)


def fleet_summary(beacons: list[dict], ratio: float = 2.0) -> dict:
    """The aggregated fleet view: per-host digests + straggler verdict."""
    stragglers = detect_fleet_stragglers(beacons, ratio)
    hosts = []
    for b in sorted(beacons, key=lambda x: x.get("host", -1)):
        host = int(b.get("host", -1))
        hosts.append({
            "host": host,
            "last_step": b.get("last_step"),
            "median_step_s": b.get("step_s", {}).get("p50"),
            "p99_step_s": b.get("step_s", {}).get("p99"),
            "steps_per_sec": b.get("steps_per_sec"),
            "anomaly_counts": b.get("anomaly_counts", {}),
            "straggler": host in stragglers,
        })
    return {
        "n_hosts": len(hosts),
        "stragglers": stragglers,
        "straggler_ratio": ratio,
        "hosts": hosts,
    }
