"""Profiling: xprof traces of the compiled step (SURVEY.md §5 tracing row).

The reference's per-``sess.run`` ``RunOptions(trace_level=FULL_TRACE)``
Chrome timeline becomes a ``jax.profiler`` trace window around N steps,
viewable with TensorBoard's profile plugin — including per-op TPU timing,
HBM usage, and the ICI collectives the step issues.
"""

from __future__ import annotations

import contextlib
from pathlib import Path

import jax


@contextlib.contextmanager
def trace_steps(logdir: str | Path):
    """Context manager: profile everything dispatched inside the window.

    Usage::

        with trace_steps("/tmp/xprof"):
            for _ in range(5):
                state, m = train_step(state, next(batches), rng)
            jax.block_until_ready(state.params)
    """
    Path(logdir).mkdir(parents=True, exist_ok=True)
    with jax.profiler.trace(str(logdir)):
        yield
