"""Profiling: xprof traces of the compiled step (SURVEY.md §5 tracing row).

The reference's per-``sess.run`` ``RunOptions(trace_level=FULL_TRACE)``
Chrome timeline becomes a ``jax.profiler`` trace window around N steps,
viewable with TensorBoard's profile plugin — including per-op TPU timing,
HBM usage, and the ICI collectives the step issues.

Only process 0 traces (same gate as the metric writers — one profile per
job, not one per host); other processes get a no-op window, so call sites
stay branch-free. Two capture shapes:

- ``trace_steps(logdir)``: everything dispatched inside the ``with`` block
  (the original whole-run capture, ``cli/train.py --profile-dir``).
- ``trace_steps(logdir, num_steps=N)``: an ARMED window — the profiler
  starts at the first dispatched step and stops after exactly N, blocking
  on the Nth step's outputs so the device tail lands in the trace
  (``cli/train.py --profile-steps``). The yielded window's
  ``before_step()``/``after_step(out)`` bracket each dispatch.
- :func:`profile_window`: a bounded wall-clock capture for a RUNNING
  process — what ``POST /profilez?ms=N`` serves. Serialized by a module
  lock (the jax profiler is a process-global singleton).
"""

from __future__ import annotations

import contextlib
import threading
import time
from pathlib import Path

import jax

# jax.profiler.start_trace/stop_trace drive one global profiler session;
# concurrent /profilez calls (ThreadingHTTPServer: thread per request) or a
# profilez hitting during a --profile-steps window must queue, not collide.
_PROFILER_LOCK = threading.Lock()


class _NullWindow:
    """No-op window: non-chief processes and the plain whole-block mode."""

    def before_step(self) -> None:
        pass

    def after_step(self, out=None) -> None:
        pass


class _StepWindow:
    """Armed N-step window: first ``before_step`` starts the trace, the
    Nth ``after_step`` blocks on its outputs and stops it."""

    def __init__(self, logdir: str, num_steps: int):
        if num_steps < 1:
            raise ValueError(f"num_steps must be >= 1, got {num_steps}")
        self._logdir = logdir
        self._num_steps = num_steps
        self._seen = 0
        self._active = False
        self._done = False

    def before_step(self) -> None:
        if self._done or self._active:
            return
        _PROFILER_LOCK.acquire()
        try:
            jax.profiler.start_trace(self._logdir)
        except BaseException:
            # A failed start (bad logdir, profiler already active elsewhere)
            # must not leave the module lock held forever.
            _PROFILER_LOCK.release()
            raise
        self._active = True

    def after_step(self, out=None) -> None:
        if not self._active:
            return
        self._seen += 1
        if self._seen >= self._num_steps:
            if out is not None:
                jax.block_until_ready(out)
            self.close()

    def close(self) -> None:
        if self._active:
            try:
                jax.profiler.stop_trace()
            finally:
                # Even if stop_trace dies the window is over: release the
                # module lock so later windows/profilez can still run.
                self._active = False
                self._done = True
                _PROFILER_LOCK.release()


@contextlib.contextmanager
def trace_steps(logdir: str | Path, num_steps: int | None = None):
    """Context manager: profile dispatched work, process 0 only.

    Usage (whole block)::

        with trace_steps("/tmp/xprof"):
            for _ in range(5):
                state, m = train_step(state, next(batches), rng)
            jax.block_until_ready(state.params)

    Usage (armed N-step window)::

        with trace_steps("/tmp/xprof", num_steps=3) as win:
            for _ in range(100):
                win.before_step()
                state, m = train_step(state, next(batches), rng)
                win.after_step((state, m))   # steps 1..3 land in the trace
    """
    if jax.process_index() != 0:
        yield _NullWindow()
        return
    Path(logdir).mkdir(parents=True, exist_ok=True)
    if num_steps is None:
        with _PROFILER_LOCK, jax.profiler.trace(str(logdir)):
            yield _NullWindow()
        return
    win = _StepWindow(str(logdir), num_steps)
    try:
        yield win
    finally:
        win.close()  # run shorter than N steps: stop cleanly anyway


def profile_window(logdir: str | Path, ms: float) -> dict:
    """Capture a bounded ``ms``-long profiler window NOW (live process).

    Blocks the calling thread for the window (the /profilez handler thread,
    not the serving hot path), clamped to [1 ms, 60 s]. Returns the
    capture summary the endpoint replies with.
    """
    ms = min(max(float(ms), 1.0), 60_000.0)
    logdir = Path(logdir)
    logdir.mkdir(parents=True, exist_ok=True)
    with _PROFILER_LOCK:
        t0 = time.perf_counter()
        jax.profiler.start_trace(str(logdir))
        try:
            time.sleep(ms / 1e3)
        finally:
            jax.profiler.stop_trace()
        wall_ms = (time.perf_counter() - t0) * 1e3
    return {"trace_dir": str(logdir), "requested_ms": ms, "wall_ms": wall_ms}
