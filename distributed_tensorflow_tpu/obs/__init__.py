"""Observability: metric writers and profiling.

Replaces the reference's ``tf.summary`` scalars + ``SummarySaverHook`` +
Chrome-timeline ``RunOptions`` tracing (SURVEY.md §5 metrics/tracing rows):
metrics are device-computed scalars fetched at the logging cadence (never
per step — no host sync in the hot loop), written to TensorBoard and/or
JSONL by process 0; profiling is ``jax.profiler`` traces viewable in
TensorBoard's profile plugin (xprof).
"""

from distributed_tensorflow_tpu.obs.export import (  # noqa: F401
    PROM_CONTENT_TYPE,
    prometheus_text,
)
from distributed_tensorflow_tpu.obs.flightrec import (  # noqa: F401
    NULL_RECORDER,
    FlightRecorder,
)
from distributed_tensorflow_tpu.obs.fleet import (  # noqa: F401
    HostBeacon,
    StepTimeline,
    StragglerDetector,
    detect_fleet_stragglers,
    fleet_summary,
    read_beacons,
)
from distributed_tensorflow_tpu.obs.health import (  # noqa: F401
    HealthTracker,
    http_status,
)
from distributed_tensorflow_tpu.obs.memory import (  # noqa: F401
    MemoryRegistry,
    default_registry,
    reset_default_registry,
    tree_nbytes,
)
from distributed_tensorflow_tpu.obs.metrics import (  # noqa: F401
    Counter,
    FeedMetrics,
    Gauge,
    Histogram,
    JsonlWriter,
    LabelledCounter,
    LabelledHistogram,
    ServeMetrics,
    TensorBoardWriter,
    make_metric_hook,
)
from distributed_tensorflow_tpu.obs.profile import (  # noqa: F401
    profile_window,
    trace_steps,
)
from distributed_tensorflow_tpu.obs.slo import (  # noqa: F401
    SloSpec,
    SloTracker,
)
from distributed_tensorflow_tpu.obs.sanitizer import (  # noqa: F401
    LockOrderSanitizer,
    RaceSanitizer,
    sanitize_locks,
    sanitize_races,
)
from distributed_tensorflow_tpu.obs.timeseries import (  # noqa: F401
    DEFAULT_WINDOWS_S,
    WindowedCounter,
    WindowedHistogram,
    WindowedHistogramFamily,
    bounds_with,
)
from distributed_tensorflow_tpu.obs.trace import (  # noqa: F401
    NULL_TRACER,
    Span,
    Tracer,
)
