"""Sliding-window metrics core: trailing rates and windowed quantiles.

The cumulative families in :mod:`obs.metrics` answer "how much since
boot" — useless for *is this server degrading right now*: a week-old p99
barely moves when the last minute goes bad.  This module adds the
time-aware half the SLO/fleet layer consumes:

- :class:`WindowedCounter` — an epoch-ring counter: events land in the
  bucket for their ~1s epoch, and ``rate(window_s)`` sums the trailing
  buckets.  Old epochs are overwritten lazily on the next write/read, so
  there is no aggregator thread and an idle counter costs nothing.
- :class:`WindowedHistogram` — the same epoch ring over a FIXED bucket
  layout (log-spaced bounds).  Windowed p50/p99 come from merging the
  trailing epochs' bucket counts and interpolating inside the containing
  bucket — no 8k-sample sort per scrape, and counts are mergeable across
  hosts (the fleet beacons ship raw bucket counts).  A cumulative
  counts array rides alongside for Prometheus ``_bucket{le=...}``
  exposition, whose counters must be monotone across scrapes.
- :class:`WindowedHistogramFamily` — labelled histograms (per-phase
  windowed twins of ``ServeMetrics.phase``).

Every read/write accepts an optional ``now`` (seconds, same clock as the
constructor's ``clock``) so tests drive synthetic traces deterministically;
production call sites omit it and get ``time.monotonic()``.

Accuracy contract: a window of ``W`` seconds at resolution ``R`` actually
covers between ``W - R`` and ``W`` seconds of events (the current epoch is
partial), so rates read up to ``R/W`` low; quantiles are exact to the
containing bucket and interpolated within it.  Thresholds that matter
(an SLO latency bound) should be passed as an explicit bucket bound —
:func:`bounds_with` — which makes attainment at that threshold exact.
"""

from __future__ import annotations

import bisect
import math
import threading
import time
from collections.abc import Sequence

# Default latency bucket bounds (seconds): log-spaced 0.5ms .. 60s, the
# serving range.  Values above the last bound land in the +Inf overflow
# bucket.  ~2x growth keeps windowed-quantile error under ~35% of the
# value, and 21 buckets * 301 epochs is ~50KB per histogram.
DEFAULT_LATENCY_BOUNDS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

# Step-time bounds for the train-side timeline (seconds): training steps
# run 1ms (smoke models) to minutes (full pods).
DEFAULT_STEP_BOUNDS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)

DEFAULT_WINDOWS_S = (10.0, 60.0, 300.0)


def bounds_with(threshold: float, base: tuple = DEFAULT_LATENCY_BOUNDS) -> tuple:
    """``base`` bounds with ``threshold`` inserted (sorted, deduplicated).

    Building a histogram with its SLO threshold as an explicit bucket
    boundary makes ``attainment(threshold)`` exact instead of
    interpolated — the serve_bench ``--quick`` SLO-math gate relies on it.
    """
    if threshold <= 0:
        return tuple(base)
    return tuple(sorted(set(base) | {float(threshold)}))


class WindowedCounter:
    """Thread-safe trailing-rate counter over an epoch ring.

    ``max_window_s / resolution_s`` buckets plus one for the current
    partial epoch; ``add`` is O(1) amortized (lazy zeroing of skipped
    epochs), ``sum``/``rate`` are O(window / resolution).
    """

    def __init__(
        self,
        max_window_s: float = 300.0,
        resolution_s: float = 1.0,
        clock=time.monotonic,
    ):
        self._lock = threading.Lock()
        self._res = float(resolution_s)
        self._n = int(math.ceil(max_window_s / resolution_s)) + 1
        self._buckets = [0.0] * self._n
        self._epoch: int | None = None  # absolute epoch of the newest bucket
        self._clock = clock
        self.total = 0.0

    def _advance(self, now: float) -> int:
        """Move the ring to ``now``'s epoch, zeroing skipped buckets.
        Caller holds the lock."""
        e = int(now / self._res)
        if self._epoch is None:
            self._epoch = e
        elif e > self._epoch:
            if e - self._epoch >= self._n:
                for i in range(self._n):
                    self._buckets[i] = 0.0
            else:
                for k in range(self._epoch + 1, e + 1):
                    self._buckets[k % self._n] = 0.0
            self._epoch = e
        return self._epoch

    def add(self, n: float = 1.0, now: float | None = None) -> None:
        now = self._clock() if now is None else now
        with self._lock:
            e = self._advance(now)
            self._buckets[e % self._n] += n
            self.total += n

    def sum(self, window_s: float, now: float | None = None) -> float:
        """Events in the trailing ``window_s`` (including the current
        partial epoch)."""
        now = self._clock() if now is None else now
        k = max(1, min(int(round(window_s / self._res)), self._n - 1))
        with self._lock:
            if self._epoch is None:
                return 0.0
            e = self._advance(now)
            return sum(self._buckets[(e - i) % self._n] for i in range(k))

    def rate(self, window_s: float, now: float | None = None) -> float:
        """Events/second over the trailing window."""
        return self.sum(window_s, now) / window_s if window_s > 0 else 0.0

    def reset(self) -> None:
        with self._lock:
            for i in range(self._n):
                self._buckets[i] = 0.0
            self._epoch = None
            self.total = 0.0


def merge_counts(*counts: list[int] | tuple[int, ...]) -> list[int]:
    """Elementwise sum of bucket-count arrays (same bounds assumed) — the
    cross-host merge the fleet beacons use."""
    if not counts:
        return []
    out = [0] * len(counts[0])
    for c in counts:
        if len(c) != len(out):
            raise ValueError(
                f"cannot merge counts of lengths {len(out)} and {len(c)}: "
                "bucket bounds differ"
            )
        for i, v in enumerate(c):
            out[i] += v
    return out


def quantile_from_counts(
    bounds: tuple, counts: list[int], p: float
) -> float:
    """p in [0,100] from bucket counts (len(bounds)+1, last = overflow),
    linearly interpolated inside the containing bucket.  Overflow-bucket
    quantiles clamp to the last finite bound."""
    total = sum(counts)
    if total == 0:
        return 0.0
    rank = p / 100.0 * total
    acc = 0.0
    for i, c in enumerate(counts):
        if c and acc + c >= rank:
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i] if i < len(bounds) else bounds[-1]
            if hi <= lo:
                return hi
            return lo + (rank - acc) / c * (hi - lo)
        acc += c
    return bounds[-1]


def attainment_from_counts(
    bounds: tuple, counts: list[int], threshold: float
) -> float:
    """Fraction of samples <= threshold (1.0 when empty).  Exact when the
    threshold is a bucket bound; interpolated within the containing bucket
    otherwise (overflow-bucket mass counts as above any finite threshold)."""
    total = sum(counts)
    if total == 0:
        return 1.0
    acc = 0.0
    for i, c in enumerate(counts):
        lo = bounds[i - 1] if i > 0 else 0.0
        hi = bounds[i] if i < len(bounds) else float("inf")
        if threshold >= hi:
            acc += c
        elif threshold > lo and math.isfinite(hi):
            acc += c * (threshold - lo) / (hi - lo)
    return acc / total


class WindowedHistogram:
    """Thread-safe bucketed histogram over an epoch ring.

    Bucket ``i`` counts samples in ``(bounds[i-1], bounds[i]]`` (bucket 0:
    ``<= bounds[0]``; the last bucket is the ``> bounds[-1]`` overflow), so
    cumulative-bucket exposition matches the Prometheus ``le`` convention.
    Cumulative (since boot/reset) counts, sum, count, and max are kept
    alongside the windowed ring.
    """

    def __init__(
        self,
        bounds: tuple = DEFAULT_LATENCY_BOUNDS,
        max_window_s: float = 300.0,
        resolution_s: float = 1.0,
        clock=time.monotonic,
    ):
        if list(bounds) != sorted(set(bounds)):
            raise ValueError("bounds must be strictly increasing")
        self._lock = threading.Lock()
        self.bounds = tuple(float(b) for b in bounds)
        self._nb = len(self.bounds) + 1  # + overflow
        self._res = float(resolution_s)
        self._n = int(math.ceil(max_window_s / resolution_s)) + 1
        self._ring = [[0] * self._nb for _ in range(self._n)]
        self._ring_sum = [0.0] * self._n
        self._epoch: int | None = None
        self._clock = clock
        self._cum = [0] * self._nb
        self.count = 0
        self.sum = 0.0
        self.max = 0.0

    def _advance(self, now: float) -> int:
        e = int(now / self._res)
        if self._epoch is None:
            self._epoch = e
        elif e > self._epoch:
            if e - self._epoch >= self._n:
                todo = range(self._n)
            else:
                todo = (k % self._n for k in range(self._epoch + 1, e + 1))
            for i in todo:
                row = self._ring[i]
                for j in range(self._nb):
                    row[j] = 0
                self._ring_sum[i] = 0.0
            self._epoch = e
        return self._epoch

    def observe(self, v: float, now: float | None = None) -> None:
        v = float(v)
        now = self._clock() if now is None else now
        i = bisect.bisect_left(self.bounds, v)  # v == bound -> that bucket
        with self._lock:
            e = self._advance(now)
            self._ring[e % self._n][i] += 1
            self._ring_sum[e % self._n] += v
            self._cum[i] += 1
            self.count += 1
            self.sum += v
            if v > self.max:
                self.max = v

    def observe_many(
        self, values: Sequence[float], now: float | None = None
    ) -> None:
        """Bulk ``observe`` under ONE lock acquisition — the batcher's
        delivery path records a whole batch's latencies/phases at once, so
        per-sample locking would multiply hot-path lock traffic (and the
        race sanitizer's per-acquisition cost) by the batch size."""
        if not values:
            return
        now = self._clock() if now is None else now
        with self._lock:
            e = self._advance(now)
            row = self._ring[e % self._n]
            for v in values:
                v = float(v)
                i = bisect.bisect_left(self.bounds, v)
                row[i] += 1
                self._ring_sum[e % self._n] += v
                self._cum[i] += 1
                self.count += 1
                self.sum += v
                if v > self.max:
                    self.max = v

    def _window_rows(self, window_s: float, e: int) -> range:
        k = max(1, min(int(round(window_s / self._res)), self._n - 1))
        return range(k)

    def window_counts(
        self, window_s: float | None = None, now: float | None = None
    ) -> list[int]:
        """Merged bucket counts over the trailing window (``None`` =
        cumulative since boot/reset)."""
        if window_s is None:
            with self._lock:
                return list(self._cum)
        now = self._clock() if now is None else now
        with self._lock:
            if self._epoch is None:
                return [0] * self._nb
            e = self._advance(now)
            out = [0] * self._nb
            for i in self._window_rows(window_s, e):
                row = self._ring[(e - i) % self._n]
                for j in range(self._nb):
                    out[j] += row[j]
            return out

    def window_count(
        self, window_s: float | None = None, now: float | None = None
    ) -> int:
        return sum(self.window_counts(window_s, now))

    def quantile(
        self, p: float, window_s: float | None = None,
        now: float | None = None,
    ) -> float:
        return quantile_from_counts(
            self.bounds, self.window_counts(window_s, now), p
        )

    def attainment(
        self, threshold: float, window_s: float | None = None,
        now: float | None = None,
    ) -> float:
        """Fraction of samples <= threshold in the window (1.0 if empty)."""
        return attainment_from_counts(
            self.bounds, self.window_counts(window_s, now), threshold
        )

    def cumulative(self) -> dict:
        """One consistent snapshot of the since-boot families — the
        Prometheus histogram exposition source (monotone across scrapes)."""
        with self._lock:
            return {
                "bounds": self.bounds,
                "counts": list(self._cum),
                "sum": self.sum,
                "count": self.count,
                "max": self.max,
            }

    def window_summary(
        self, window_s: float, now: float | None = None
    ) -> dict:
        counts = self.window_counts(window_s, now)
        n = sum(counts)
        return {
            "count": n,
            "rate": n / window_s if window_s > 0 else 0.0,
            "p50": quantile_from_counts(self.bounds, counts, 50),
            "p90": quantile_from_counts(self.bounds, counts, 90),
            "p99": quantile_from_counts(self.bounds, counts, 99),
        }

    def reset(self) -> None:
        with self._lock:
            for i in range(self._n):
                row = self._ring[i]
                for j in range(self._nb):
                    row[j] = 0
                self._ring_sum[i] = 0.0
            self._epoch = None
            self._cum = [0] * self._nb
            self.count = 0
            self.sum = 0.0
            self.max = 0.0


class WindowedHistogramFamily:
    """Thread-safe labelled family of :class:`WindowedHistogram` (the
    windowed twin of ``LabelledHistogram`` — per-phase serving series)."""

    def __init__(
        self,
        bounds: tuple = DEFAULT_LATENCY_BOUNDS,
        max_window_s: float = 300.0,
        resolution_s: float = 1.0,
        clock=time.monotonic,
    ):
        self._lock = threading.Lock()
        self._args = (bounds, max_window_s, resolution_s, clock)
        self._hists: dict = {}

    def observe(self, label, v: float, now: float | None = None) -> None:
        self._series(label).observe(v, now)

    def observe_many(
        self, label, values: Sequence[float], now: float | None = None
    ) -> None:
        """Bulk per-label observe (one series lock for the whole batch)."""
        self._series(label).observe_many(values, now)

    def _series(self, label) -> WindowedHistogram:
        with self._lock:
            h = self._hists.get(label)
            if h is None:
                h = self._hists[label] = WindowedHistogram(*self._args)
        return h

    def labels(self) -> list:
        with self._lock:
            return sorted(self._hists)

    def get(self, label) -> WindowedHistogram | None:
        with self._lock:
            return self._hists.get(label)

    def snapshot(
        self, window_s: float, now: float | None = None
    ) -> dict:
        with self._lock:
            hists = dict(self._hists)
        return {
            str(k): h.window_summary(window_s, now)
            for k, h in sorted(hists.items())
        }

    def reset(self) -> None:
        with self._lock:
            self._hists.clear()
