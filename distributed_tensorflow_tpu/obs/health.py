"""Readiness-aware health: the liveness/readiness contract behind /healthz.

Lifecycle (explicit, operator/stack-driven)::

    starting ──► ready ──► draining ──► closed
        └──────────────────────┴──────────► closed

plus one *derived* overlay: a ``ready`` server reports **degraded** while
it is saturated (queue at bound, or backpressure sheds in the trailing
window) or while its SLO burn-rate verdict is ``page``.  Degraded is
computed at read time, never stored — the server recovers to ``ready``
the moment the pressure clears, with no transition to forget.

The HTTP mapping (the contract the fleet router polls — see
docs/DEPLOY.md): ``ready`` → 200; every other state → 503 with
``{"status": "<state>", "reason": ...}`` so a probe can distinguish
"warming up" from "drain me" from "dead".

The tracker owns NO thread: it reads a ``status_fn`` (the batcher's
live counters), the windowed ``rejected_w`` family, and the SLO verdict
on demand.  Explicit transitions are validated — ``mark_ready`` on a
draining server is a programming error, not a silent un-drain.
``mark_closed`` is idempotent (close paths race benignly).
"""

from __future__ import annotations

import threading
import time

STATES = ("starting", "ready", "degraded", "draining", "closed")

# Explicit-state machine; "degraded" is derived and never stored.
_ALLOWED = {
    "starting": {"ready", "draining", "closed"},
    "ready": {"draining", "closed"},
    "draining": {"closed"},
    "closed": set(),
}

#: states that answer 200 on /healthz
SERVING_STATES = ("ready",)


def http_status(state: str) -> int:
    return 200 if state in SERVING_STATES else 503


class HealthTracker:
    """Readiness state for one serving process.

    ``status_fn() -> dict`` supplies the live stack view (the batcher's
    ``status()``): ``closed`` (bool), ``queue_depth``, ``max_queue``,
    ``in_flight``.  ``metrics`` supplies ``rejected_w`` (windowed
    backpressure counter); ``slo`` supplies ``verdict()``.  All three are
    optional — a tracker with none is a plain explicit state machine.
    """

    def __init__(
        self,
        *,
        status_fn=None,
        metrics=None,
        slo=None,
        saturation_window_s: float = 10.0,
        clock=time.monotonic,
        warmup_fn=None,
        warmup_target: float = 1.0,
        recorder=None,
    ):
        self._lock = threading.Lock()
        self._state = "starting"
        self._status_fn = status_fn
        self._metrics = metrics
        self._slo = slo
        self._saturation_window_s = float(saturation_window_s)
        self._clock = clock
        # Warmup-gated readiness: ``warmup_fn() -> float`` reports the AOT
        # grid's warm fraction; while the tracker is ``starting`` a probe
        # auto-promotes to ready once the fraction reaches the target (the
        # docs/DEPLOY.md router contract: starting until grid warm).
        self._warmup_fn = warmup_fn
        self._warmup_target = float(warmup_target)
        self._recorder = recorder

    # ------------------------------------------------- explicit lifecycle

    def _transition(self, to: str) -> None:
        with self._lock:
            if to not in _ALLOWED[self._state]:
                raise ValueError(
                    f"invalid health transition {self._state} -> {to}"
                )
            was, self._state = self._state, to
        if self._recorder is not None:
            self._recorder.record("health_transition", state=to, was=was)

    def mark_ready(self) -> None:
        self._transition("ready")

    def mark_draining(self) -> None:
        self._transition("draining")

    def mark_closed(self) -> None:
        with self._lock:
            was, self._state = self._state, "closed"  # always legal,
        if was != "closed" and self._recorder is not None:  # idempotent
            self._recorder.record("health_transition", state="closed",
                                  was=was)

    @property
    def lifecycle(self) -> str:
        """The stored explicit state (no derived overlay)."""
        with self._lock:
            return self._state

    # --------------------------------------------------- derived readiness

    def _saturation(self, status: dict, now: float) -> str | None:
        depth, bound = status.get("queue_depth"), status.get("max_queue")
        if depth is not None and bound and depth >= bound:
            return f"queue full ({depth}/{bound})"
        if self._metrics is not None:
            shed = self._metrics.rejected_w.sum(
                self._saturation_window_s, now
            )
            if shed > 0:
                return (
                    f"shed {shed:g} requests in the last "
                    f"{self._saturation_window_s:g}s"
                )
        return None

    def state(self, now: float | None = None) -> tuple[str, dict]:
        """(state, detail).  Detail carries the reason plus the live stack
        numbers a router/operator wants in the probe body."""
        now = self._clock() if now is None else now
        with self._lock:
            base = self._state
        status = dict(self._status_fn()) if self._status_fn else {}
        detail: dict = {**status}
        if status.get("closed") and base not in ("closed",):
            base = "closed"  # stack closed underneath us (e.g. bare
            # batcher.close()) — report it even without mark_closed()
        if base == "starting" and self._warmup_fn is not None:
            frac = float(self._warmup_fn())
            detail["warm_fraction"] = frac
            if frac >= self._warmup_target:
                # Grid warm: auto-promote at probe time (guarded — a racing
                # probe or an explicit mark_ready may have beaten us).
                with self._lock:
                    promote = self._state == "starting"
                    if promote:
                        self._state = "ready"
                if promote and self._recorder is not None:
                    self._recorder.record("health_transition",
                                          state="ready", was="starting")
                base = "ready"
            else:
                detail["reason"] = (
                    f"warming: grid {frac:.0%} compiled "
                    f"(target {self._warmup_target:.0%})"
                )
                return base, detail
        if base in ("closed", "draining", "starting"):
            return base, detail
        reason = self._saturation(status, now)
        if reason is not None:
            detail["reason"] = f"saturated: {reason}"
            return "degraded", detail
        if self._slo is not None:
            verdict = self._slo.verdict(now)
            detail["slo_verdict"] = verdict
            if verdict == "page":
                detail["reason"] = "slo burn rate at page level"
                return "degraded", detail
        return base, detail

    def probe(self, now: float | None = None) -> tuple[int, dict]:
        """(http_code, body) for /healthz."""
        state, detail = self.state(now)
        return http_status(state), {"status": state, **detail}
