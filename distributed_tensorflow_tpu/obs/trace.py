"""Span/event tracing: the rebuild of the reference's per-``sess.run``
Chrome timeline (``RunOptions(trace_level=FULL_TRACE)``), host side.

``jax.profiler`` (obs/profile.py) covers the device half offline; this
module covers the HOST half live: where a request or a training step
spends its wall time between the counters. Three pieces:

- :class:`Tracer` — thread-safe, ring-buffered span recording. Spans are
  either scoped (``with tracer.span("assemble"):``, nesting tracked per
  thread so children know their parent and inherit its correlation keys)
  or recorded after the fact from explicit timestamps
  (``tracer.record("device", t0, t1, request_id=...)`` — the shape the
  serving pipeline needs, where one request's phases are measured on
  three different threads).
- **Correlation keys**: every span may carry a ``request_id`` (serving)
  and/or a ``step`` (training), so a drained trace decomposes per
  request/step, not just per thread.
- **Chrome trace-event export** (:meth:`Tracer.chrome_events` /
  :meth:`Tracer.export`): the JSON the ``chrome://tracing`` / Perfetto UI
  loads — ``ph: "X"`` complete events with microsecond ``ts``/``dur``,
  ``ph: "i"`` instants, real ``pid``/``tid``.

Overhead contract (the "always-on-capable" requirement): a DISABLED
tracer is a branch and a return at every call site — ``span()`` hands
back a shared no-op context manager, ``record``/``instant`` return on
the first line, nothing allocates. An ENABLED tracer costs one small
object + one deque append per span; the buffer is bounded
(``buffer_size``), so a serving process tracing forever holds a fixed
window of recent spans, never an unbounded log.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from pathlib import Path


class Span:
    """One completed (or open) span. ``t0``/``t1`` are ``time.monotonic``
    seconds; the exporter rebases them onto the tracer's origin."""

    __slots__ = (
        "name", "cat", "t0", "t1", "tid", "span_id", "parent_id",
        "request_id", "step", "args", "ph",
    )

    def __init__(self, name, cat, t0, t1, tid, span_id, parent_id,
                 request_id, step, args, ph="X"):
        self.name = name
        self.cat = cat
        self.t0 = t0
        self.t1 = t1
        self.tid = tid
        self.span_id = span_id
        self.parent_id = parent_id
        self.request_id = request_id
        self.step = step
        self.args = args
        self.ph = ph

    @property
    def duration_s(self) -> float:
        return (self.t1 or self.t0) - self.t0


class _NullSpan:
    """Shared no-op context manager: what a disabled tracer's ``span()``
    returns. One instance for the whole process — entering it allocates
    nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **args) -> None:
        pass


NULL_SPAN = _NullSpan()


class _ScopedSpan:
    """Context manager for an open span; pops the thread-local stack and
    commits to the ring buffer on exit."""

    __slots__ = ("_tracer", "_span")

    def __init__(self, tracer, span):
        self._tracer = tracer
        self._span = span

    def set(self, **args) -> None:
        """Attach args to the open span (e.g. the chosen tier, row count)."""
        if self._span.args is None:
            self._span.args = {}
        self._span.args.update(args)

    def __enter__(self):
        self._tracer._stack().append(self._span)
        return self

    def __exit__(self, *exc):
        span = self._span
        span.t1 = time.monotonic()
        stack = self._tracer._stack()
        if stack and stack[-1] is span:
            stack.pop()
        self._tracer._commit(span)
        return False


class Tracer:
    """Thread-safe ring-buffered span recorder with Chrome JSON export.

    ``enabled=False`` (or ``buffer_size=0``) builds a no-op tracer: every
    method returns immediately, ``span()`` returns the shared
    :data:`NULL_SPAN`. Call sites therefore never need their own
    ``if tracing:`` branches.
    """

    def __init__(self, buffer_size: int = 4096, enabled: bool = True):
        self.enabled = bool(enabled) and buffer_size > 0
        self.buffer_size = int(buffer_size)
        self._lock = threading.Lock()
        self._buf: list[Span] = []
        self._head = 0  # ring write position once the buffer is full
        self._dropped = 0
        self._ids = itertools.count(1)
        self._tls = threading.local()
        # Export origin: monotonic epoch paired with wall clock so two
        # traces from one process line up in the viewer.
        self._t_origin = time.monotonic()
        self._wall_origin = time.time()

    # ------------------------------------------------------------ recording

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _commit(self, span: Span) -> None:
        with self._lock:
            if len(self._buf) < self.buffer_size:
                self._buf.append(span)
            else:
                self._buf[self._head] = span
                self._head = (self._head + 1) % self.buffer_size
                self._dropped += 1

    def span(self, name: str, cat: str = "", *, request_id=None,
             step=None, **args):
        """Open a scoped span (``with tracer.span(...)``). Nested spans
        record their parent and inherit its ``request_id``/``step`` unless
        given their own."""
        if not self.enabled:
            return NULL_SPAN
        stack = self._stack()
        parent = stack[-1] if stack else None
        if parent is not None:
            if request_id is None:
                request_id = parent.request_id
            if step is None:
                step = parent.step
        return _ScopedSpan(self, Span(
            name, cat, time.monotonic(), None, threading.get_ident(),
            next(self._ids), parent.span_id if parent else None,
            request_id, step, args or None,
        ))

    def record(self, name: str, t0: float, t1: float, *, cat: str = "",
               request_id=None, step=None, tid=None, args=None) -> None:
        """Commit a span from explicit ``time.monotonic`` timestamps —
        for phases measured across threads (the serving pipeline), where a
        ``with`` block can't scope the interval."""
        if not self.enabled:
            return
        self._commit(Span(
            name, cat, t0, t1, tid or threading.get_ident(),
            next(self._ids), None, request_id, step, args,
        ))

    def instant(self, name: str, cat: str = "", *, request_id=None,
                step=None, **args) -> None:
        """Record a point event (``ph: "i"``) — checkpoint writes, errors."""
        if not self.enabled:
            return
        now = time.monotonic()
        self._commit(Span(
            name, cat, now, now, threading.get_ident(), next(self._ids),
            None, request_id, step, args or None, ph="i",
        ))

    # ------------------------------------------------------------- reading

    def _snapshot_buf(self) -> list[Span]:
        with self._lock:
            # Oldest-first: the ring's tail is at _head once it wrapped.
            return self._buf[self._head:] + self._buf[:self._head]

    def drain(self, max_spans: int | None = None) -> list[Span]:
        """Pop spans (oldest first). ``max_spans`` keeps only the NEWEST N
        — a bounded ``/tracez`` pull wants the recent window, and the rest
        is discarded either way."""
        with self._lock:
            spans = self._buf[self._head:] + self._buf[:self._head]
            self._buf = []
            self._head = 0
        if max_spans is not None and max_spans >= 0:
            spans = spans[len(spans) - min(len(spans), max_spans):]
        return spans

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def summary(self) -> dict:
        """Per-span-name aggregate over the CURRENT buffer (no drain):
        ``{name: {count, mean_ms, max_ms}}`` — the /statusz digest."""
        agg: dict[str, list] = {}
        for s in self._snapshot_buf():
            a = agg.setdefault(s.name, [0, 0.0, 0.0])
            d = s.duration_s
            a[0] += 1
            a[1] += d
            a[2] = max(a[2], d)
        return {
            name: {
                "count": n,
                "mean_ms": 1e3 * total / n,
                "max_ms": 1e3 * mx,
            }
            for name, (n, total, mx) in sorted(agg.items())
        }

    def status(self) -> dict:
        with self._lock:
            buffered, dropped = len(self._buf), self._dropped
        return {
            "enabled": self.enabled,
            "buffer_size": self.buffer_size,
            "buffered_spans": buffered,
            "dropped_spans": dropped,
        }

    # ------------------------------------------------------------- export

    def chrome_events(self, spans: list[Span] | None = None) -> list[dict]:
        """Spans -> Chrome trace-event dicts (``ts``/``dur`` in µs since
        the tracer's origin). ``spans=None`` exports a copy of the current
        buffer without draining it."""
        if spans is None:
            spans = self._snapshot_buf()
        pid = os.getpid()
        events = []
        for s in spans:
            args = dict(s.args) if s.args else {}
            if s.request_id is not None:
                args["request_id"] = s.request_id
            if s.step is not None:
                args["step"] = s.step
            ev = {
                "name": s.name,
                "cat": s.cat or "host",
                "ph": s.ph,
                "ts": (s.t0 - self._t_origin) * 1e6,
                "pid": pid,
                "tid": s.tid,
                "args": args,
            }
            if s.ph == "X":
                ev["dur"] = max(0.0, ((s.t1 or s.t0) - s.t0) * 1e6)
            else:
                ev["s"] = "t"  # thread-scoped instant
            events.append(ev)
        return events

    def chrome_json(self, spans: list[Span] | None = None) -> dict:
        return {
            "traceEvents": self.chrome_events(spans),
            "displayTimeUnit": "ms",
            "otherData": {"wall_origin": self._wall_origin},
        }

    def export(self, path: str | Path, *, drain: bool = False) -> Path:
        """Write the buffer as Chrome trace-event JSON (Perfetto /
        ``chrome://tracing`` loadable). ``drain`` empties the buffer."""
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        spans = self.drain() if drain else None
        with path.open("w") as fh:
            json.dump(self.chrome_json(spans), fh)
        return path


#: Process-wide disabled tracer: the default for every instrumented call
#: site, so ``tracer or NULL_TRACER`` makes tracing opt-in with zero
#: conditional clutter (and near-zero cost) when it is off.
NULL_TRACER = Tracer(buffer_size=0, enabled=False)
