"""Black-box flight recorder: the last N structured events, dumped on
trigger.

The SLO layer answers "are we burning budget" and the tracer answers
"where did THIS request spend its time" — neither answers the postmortem
question "what happened in the seconds before the page / the engine
failure / the 500". This module does: a preallocated bounded ring of
tiny structured events (request admit/complete/reject, slot alloc/free,
prefix-cache hit/eviction, health transitions, SLO verdict changes,
engine dispatch failures, checkpoint restores — the taxonomy in
docs/OBS.md), fed from the batcher/engine/kvpool/health/slo hot paths,
plus a ``dump()`` that atomically snapshots the ring together with the
metrics snapshot, the tracer span summary, and the memz/compilez digests
into one timestamped JSON file under ``--dump-dir``.

Overhead contract (mirrors :class:`~..obs.trace.Tracer`): a DISABLED
recorder is one attribute check and a return at every call site —
:data:`NULL_RECORDER` is the process-wide default, so instrumented code
never needs its own ``if recording:`` branches. An ENABLED recorder
costs one tuple build and one ring write under a small dedicated lock;
the ring is PREALLOCATED (``capacity`` slots, filled with ``None``) so
steady-state recording allocates nothing but the event tuples, and
overflow overwrites the oldest event while counting the drop — the
serve_bench ``--quick`` gate pins the whole thing at <=2%% throughput
overhead.

Dump triggers are RATE-LIMITED (``min_dump_interval_s``) so a flapping
SLO verdict cannot fill the disk: automatic triggers inside the window
count as ``dumps_suppressed``; a manual ``POST /debugz/dump`` passes
``force=True`` and always writes. Writes are atomic (tmp + rename) so a
reader never sees a torn file.
"""

from __future__ import annotations

import json
import os
import threading
import time
from pathlib import Path

__all__ = ["FlightEvent", "FlightRecorder", "NULL_RECORDER"]

#: canonical event kinds (docs/OBS.md "Flight-recorder event taxonomy");
#: ``record`` accepts any string — this tuple is the documented contract,
#: not a validation gate (a new call site must not crash an old binary).
EVENT_KINDS = (
    "request_admit",
    "request_complete",
    "request_reject",
    "slot_alloc",
    "slot_free",
    "prefix_hit",
    "prefix_evict",
    "spec_backoff",     # speculation backoff engaged/disengaged for a slot
    "health_transition",
    "slo_verdict",
    "engine_failure",
    "server_error",
    "ckpt_restore",
    # -- training resilience (train/faultinject.py, train/resilience.py,
    #    train/loop.py non-finite guard, obs/fleet.py FleetSupervisor) --
    "fault_injected",    # a scheduled FaultPlan event fired (kind, step)
    "nonfinite_loss",    # NaN/Inf step loss seen by the loop guard
    "ckpt_save_error",   # periodic save attempt failed (absorbed)
    "train_restart",     # transient failure -> restore + re-enter loop
    "train_fatal",       # fatal classification: dumping and re-raising
    "preempt_exit",      # SIGTERM/SIGINT -> final sync checkpoint + exit
    "host_lost",         # FleetSupervisor: a host's beacon went stale
    # -- serving fleet (serve/router.py, obs/fleet.py ReplicaSupervisor) --
    "router_spawn",      # router spawned/adopted a replica process
    "replica_lost",      # health-poll timeout / refusal / process exit
    "replica_restart",   # ReplicaSupervisor verdict -> replica relaunched
    "hot_swap",          # rolling checkpoint swap step (drain/restart/done)
    # -- disaggregated serving (serve/disagg.py KV-page transfer) --
    "kv_transfer_start",   # page-chain transfer admitted (role, bytes)
    "kv_transfer_done",    # chain adopted by the decode role (bytes, s)
    "kv_transfer_reject",  # budget shed / wire refusal (cause)
    # -- live stream migration (serve/batcher.py, serve/disagg.py) --
    "stream_export",        # live stream checkpointed off its slot/queue
    "stream_adopt",         # migrated stream resumed here (pages yes/no)
    "stream_migrate_reject",  # wire/geometry/state/budget refusal (cause)
    # -- priority-preemptive scheduling (serve/batcher.py) --
    "slot_preempt",         # victim parked (reason paged/pageless) or the
                            # park aborted (aborted=True: pool full /
                            # un-bucketable resume; victim finishes)
    "slot_resume",          # parked victim re-admitted (resume_tokens
                            # replay; rounds = parks survived)
    "dump",
)


class FlightEvent:
    """One ring entry: wall-clock stamp, kind, optional request id, and a
    small detail dict. ``__slots__`` keeps the steady-state footprint at
    one small object per event."""

    __slots__ = ("t", "kind", "request_id", "detail")

    def __init__(self, t, kind, request_id, detail):
        self.t = t
        self.kind = kind
        self.request_id = request_id
        self.detail = detail

    def as_dict(self) -> dict:
        out = {"t": self.t, "kind": self.kind}
        if self.request_id is not None:
            out["request_id"] = self.request_id
        if self.detail:
            out.update(self.detail)
        return out


class FlightRecorder:
    """Lock-light bounded event ring with triggered JSON dumps.

    ``capacity=0`` or ``enabled=False`` builds a no-op recorder: every
    method returns on its first line (:data:`NULL_RECORDER` is the shared
    instance call sites default to). ``dump_dir=None`` keeps the ring and
    the snapshot machinery but skips the file write — ``dump()`` still
    returns the payload, which is what the in-process tests and the
    serve_bench round-trip gate consume.

    ``attach`` wires the dump's sidecar sections: zero-arg callables for
    the metrics snapshot, the ``/memz`` digest, the ``/compilez`` digest,
    and the tracer span summary. Missing sections dump as ``None`` — a
    partial wiring still produces a valid file with all four keys.
    """

    # Shared mutable ring state; every access is ordered by self._lock.
    _RACETRACE_ATTRS = ("_buf", "_head", "_n", "_dropped")

    def __init__(
        self,
        capacity: int = 2048,
        *,
        enabled: bool = True,
        dump_dir: str | Path | None = None,
        min_dump_interval_s: float = 30.0,
        clock=time.time,
    ):
        self.enabled = bool(enabled) and capacity > 0
        self.capacity = int(capacity)
        self.dump_dir = Path(dump_dir) if dump_dir else None
        self.min_dump_interval_s = float(min_dump_interval_s)
        self._clock = clock
        self._lock = threading.Lock()
        # Preallocated ring: _head is the next write slot once full.
        self._buf: list[FlightEvent | None] = [None] * self.capacity
        self._head = 0
        self._n = 0
        self._dropped = 0
        self._dump_lock = threading.Lock()
        self._last_dump_t: float | None = None
        self._dumps_written = 0
        self._dumps_suppressed = 0
        self._dump_seq = 0
        self._metrics_fn = None
        self._memz_fn = None
        self._compilez_fn = None
        self._tracer_fn = None

    # ---------------------------------------------------------- recording

    def record(self, kind: str, request_id=None, **detail) -> None:
        """Append one event (cheap no-op when disabled). ``detail`` values
        must be JSON-serializable — call sites pass ints/floats/strings."""
        if not self.enabled:
            return
        ev = FlightEvent(self._clock(), kind, request_id, detail or None)
        with self._lock:
            if self._n < self.capacity:
                self._buf[self._n] = ev
                self._n += 1
            else:
                self._buf[self._head] = ev
                self._head = (self._head + 1) % self.capacity
                self._dropped += 1

    def events(self) -> list[dict]:
        """Snapshot the ring oldest-first (no drain — a dump must not blind
        the next one)."""
        with self._lock:
            if self._n < self.capacity:
                evs = self._buf[: self._n]
            else:
                evs = self._buf[self._head:] + self._buf[: self._head]
            evs = list(evs)
        return [e.as_dict() for e in evs if e is not None]

    def status(self) -> dict:
        with self._lock:
            buffered, dropped = self._n, self._dropped
        with self._dump_lock:
            written = self._dumps_written
            suppressed = self._dumps_suppressed
            last = self._last_dump_t
        return {
            "enabled": self.enabled,
            "capacity": self.capacity,
            "buffered_events": buffered,
            "dropped_events": dropped,
            "dumps_written": written,
            "dumps_suppressed": suppressed,
            "last_dump_t": last,
            "dump_dir": str(self.dump_dir) if self.dump_dir else None,
        }

    # ------------------------------------------------------------ dumping

    def attach(
        self,
        *,
        metrics_fn=None,
        memz_fn=None,
        compilez_fn=None,
        tracer_fn=None,
    ) -> None:
        """Wire the dump's sidecar sections (Client does this once)."""
        if metrics_fn is not None:
            self._metrics_fn = metrics_fn
        if memz_fn is not None:
            self._memz_fn = memz_fn
        if compilez_fn is not None:
            self._compilez_fn = compilez_fn
        if tracer_fn is not None:
            self._tracer_fn = tracer_fn

    @staticmethod
    def _section(fn):
        if fn is None:
            return None
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — a broken section must not
            return {"error": f"{type(e).__name__}: {e}"}  # lose the dump

    def snapshot_payload(self, reason: str) -> dict:
        """The dump body: ring events + the four sidecar sections. Always
        carries every key so a reader's parser never branches on wiring."""
        return {
            "reason": reason,
            "wall_time": self._clock(),
            "recorder": self.status(),
            "events": self.events(),
            "metrics": self._section(self._metrics_fn),
            "memz": self._section(self._memz_fn),
            "compilez": self._section(self._compilez_fn),
            "tracer": self._section(self._tracer_fn),
        }

    def dump(self, reason: str, *, force: bool = False):
        """Write one dump (rate-limited unless ``force``). Returns the
        written :class:`~pathlib.Path`, the payload dict when no
        ``dump_dir`` is configured, or ``None`` when suppressed/disabled.
        """
        if not self.enabled:
            return None
        now = self._clock()
        with self._dump_lock:
            if not force and self._last_dump_t is not None and (
                now - self._last_dump_t < self.min_dump_interval_s
            ):
                self._dumps_suppressed += 1
                return None
            self._last_dump_t = now
            self._dumps_written += 1
            self._dump_seq += 1
            seq = self._dump_seq
        self.record("dump", reason=reason)
        payload = self.snapshot_payload(reason)
        if self.dump_dir is None:
            return payload
        self.dump_dir.mkdir(parents=True, exist_ok=True)
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime(now))
        path = self.dump_dir / f"flightrec-{stamp}-{seq:04d}-{reason}.json"
        tmp = path.with_suffix(".json.tmp")
        with tmp.open("w") as fh:
            json.dump(payload, fh, default=str)
        os.replace(tmp, path)  # atomic: readers never see a torn file
        return path

    def trigger(self, reason: str):
        """Automatic-trigger entry point (SLO page, engine failure,
        unhandled 500): a rate-limited ``dump``."""
        return self.dump(reason, force=False)


#: Process-wide disabled recorder: the default for every instrumented
#: call site, so ``recorder or NULL_RECORDER`` keeps recording opt-in
#: with near-zero cost when it is off.
NULL_RECORDER = FlightRecorder(capacity=0, enabled=False)
