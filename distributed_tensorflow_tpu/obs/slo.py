"""Declared SLOs and multi-window error-budget burn rates.

An SLO here is a *declared* objective over the windowed families in
:class:`~distributed_tensorflow_tpu.obs.metrics.ServeMetrics`:

- **latency**: ``target`` fraction of requests complete within
  ``threshold_ms`` (e.g. 99% under 50ms).  Good/bad fractions come from
  ``metrics.latency_w.attainment(threshold)`` — the windowed bucketed
  histogram, with the threshold inserted as an explicit bucket bound so
  attainment is exact, not interpolated.
- **availability**: ``target`` fraction of accepted requests produce a
  result (backpressure sheds, engine failures, and closed-server
  rejections are the bad events; ``validation`` errors are the client's
  fault and do not burn budget).

The alerting math is the standard error-budget burn rate
(Google SRE workbook ch.5): over a window,

    burn = bad_fraction / (1 - target)

so burn 1.0 means "exactly consuming budget at the sustainable rate" and
burn 10 means "10x too fast".  Verdicts are multi-window so a single
slow request can't page and a slow-motion leak still warns:

- ``page``  — burn >= ``page_burn`` in BOTH the short and mid windows
  (fast-burn confirmation: the short window reacts, the mid window
  proves it isn't one bad second);
- ``warn``  — burn >= ``warn_burn`` in the mid OR long window;
- ``ok``    — otherwise (including "no traffic in window").

Windows default to (10s, 60s, 300s) — scaled-down analogues of the
classic (5m, 1h, 6h) tuned to a serving process you watch live, and the
exact series :class:`WindowedCounter`/:class:`WindowedHistogram` retain.

:class:`SloTracker` is pull-based: verdicts are computed at read time
from the windowed series — no aggregator thread, nothing to join.
"""

from __future__ import annotations

import dataclasses
import time

VERDICTS = ("ok", "warn", "page")


@dataclasses.dataclass(frozen=True)
class SloSpec:
    """Declared objectives (0 disables a dimension).

    ``latency_threshold_ms``/``latency_target``: latency SLO — target
    fraction of requests under the threshold.  ``availability_target``:
    availability SLO.  ``windows_s`` must be ascending (short, mid, long).
    """

    latency_threshold_ms: float = 0.0
    latency_target: float = 0.99
    availability_target: float = 0.0
    windows_s: tuple = (10.0, 60.0, 300.0)
    warn_burn: float = 1.0
    page_burn: float = 10.0

    def __post_init__(self):
        if self.latency_threshold_ms < 0:
            raise ValueError("latency_threshold_ms must be >= 0")
        for t, nm in ((self.latency_target, "latency_target"),
                      (self.availability_target, "availability_target")):
            if t and not (0.0 < t < 1.0):
                raise ValueError(f"{nm} must be in (0, 1), got {t}")
        if len(self.windows_s) < 2:
            raise ValueError("need at least (short, mid) windows")
        if list(self.windows_s) != sorted(self.windows_s):
            raise ValueError("windows_s must be ascending")

    @property
    def enabled(self) -> bool:
        return bool(
            (self.latency_threshold_ms and self.latency_target)
            or self.availability_target
        )


def burn_rate(bad_fraction: float, target: float) -> float:
    """Error-budget burn multiple: 1.0 = consuming budget exactly at the
    sustainable rate."""
    budget = 1.0 - target
    if budget <= 0:
        return float("inf") if bad_fraction > 0 else 0.0
    return bad_fraction / budget


def _verdict(spec: SloSpec, burns: dict[float, float]) -> str:
    ws = spec.windows_s
    if burns[ws[0]] >= spec.page_burn and burns[ws[1]] >= spec.page_burn:
        return "page"
    if any(burns[w] >= spec.warn_burn for w in ws[1:]):
        return "warn"
    return "ok"


def worst(verdicts) -> str:
    vs = list(verdicts)
    return max(vs, key=VERDICTS.index) if vs else "ok"


class SloTracker:
    """Compute attainment/burn/verdicts from a ``ServeMetrics``'s windowed
    families at read time.

    ``metrics`` needs ``latency_w`` (WindowedHistogram, seconds),
    ``ok_w``/``bad_w`` (WindowedCounters) — the serving bundle wires them;
    anything else can duck-type the same three attributes.
    """

    def __init__(self, metrics, spec: SloSpec | None = None,
                 clock=time.monotonic, recorder=None):
        self.metrics = metrics
        self.spec = spec or SloSpec()
        self._clock = clock
        # Flight-recorder hookup: verdict() is pull-based (probes/exports
        # call it), so verdict CHANGES are detected here — each one logs an
        # slo_verdict event and a flip to "page" trips a rate-limited dump.
        self._recorder = recorder
        self._last_verdict = "ok"

    # ------------------------------------------------------------ queries

    def latency_attainment(
        self, window_s: float | None = None, now: float | None = None
    ) -> float:
        t_s = self.spec.latency_threshold_ms / 1e3
        return self.metrics.latency_w.attainment(t_s, window_s, now)

    def availability(
        self, window_s: float, now: float | None = None
    ) -> float:
        ok = self.metrics.ok_w.sum(window_s, now)
        bad = self.metrics.bad_w.sum(window_s, now)
        total = ok + bad
        return ok / total if total else 1.0

    def _latency_burns(self, now: float) -> dict[float, float]:
        return {
            w: burn_rate(
                1.0 - self.latency_attainment(w, now), self.spec.latency_target
            )
            for w in self.spec.windows_s
        }

    def _availability_burns(self, now: float) -> dict[float, float]:
        return {
            w: burn_rate(
                1.0 - self.availability(w, now), self.spec.availability_target
            )
            for w in self.spec.windows_s
        }

    # ------------------------------------------------------------- report

    def report(self, now: float | None = None) -> dict:
        """The ``/sloz`` body: per-SLO windowed attainment + burn +
        verdict, and the overall (worst) verdict."""
        now = self._clock() if now is None else now
        spec = self.spec
        slos = []
        if spec.latency_threshold_ms and spec.latency_target:
            burns = self._latency_burns(now)
            slos.append({
                "name": f"latency_p{round(spec.latency_target * 100):g}",
                "kind": "latency",
                "threshold_ms": spec.latency_threshold_ms,
                "target": spec.latency_target,
                "windows": {
                    f"{w:g}s": {
                        "attainment": self.latency_attainment(w, now),
                        "burn_rate": burns[w],
                        "count": self.metrics.latency_w.window_count(w, now),
                    }
                    for w in spec.windows_s
                },
                "verdict": _verdict(spec, burns),
            })
        if spec.availability_target:
            burns = self._availability_burns(now)
            slos.append({
                "name": "availability",
                "kind": "availability",
                "target": spec.availability_target,
                "windows": {
                    f"{w:g}s": {
                        "attainment": self.availability(w, now),
                        "burn_rate": burns[w],
                        "count": (
                            self.metrics.ok_w.sum(w, now)
                            + self.metrics.bad_w.sum(w, now)
                        ),
                    }
                    for w in spec.windows_s
                },
                "verdict": _verdict(spec, burns),
            })
        return {
            "spec": dataclasses.asdict(spec),
            "slos": slos,
            "verdict": worst(s["verdict"] for s in slos),
        }

    def verdict(self, now: float | None = None) -> str:
        """Overall verdict only (the health tracker's burn-rate input)."""
        now = self._clock() if now is None else now
        spec = self.spec
        vs = []
        if spec.latency_threshold_ms and spec.latency_target:
            vs.append(_verdict(spec, self._latency_burns(now)))
        if spec.availability_target:
            vs.append(_verdict(spec, self._availability_burns(now)))
        v = worst(vs)
        if self._recorder is not None and v != self._last_verdict:
            was, self._last_verdict = self._last_verdict, v
            self._recorder.record("slo_verdict", verdict=v, was=was)
            if v == "page":
                self._recorder.trigger("slo_page")
        return v
