"""Metric writers and serving instruments.

Training side: TensorBoard scalars and append-only JSONL. Only process 0
writes (the reference gated summaries on the chief the same way,
SURVEY.md §5); other hosts get no-op hooks, so call sites stay branch-free.

Serving side (serve/): thread-safe :class:`Counter` / :class:`Gauge` /
:class:`Histogram` primitives and the :class:`ServeMetrics` bundle — the
per-request latency histogram (p50/p99), queue-depth and batch-occupancy
gauges the inference engine exposes at ``GET /metrics``.
"""

from __future__ import annotations

import json
import threading
import time
from collections.abc import Sequence
from pathlib import Path

import jax

from distributed_tensorflow_tpu.obs.timeseries import (
    DEFAULT_LATENCY_BOUNDS,
    DEFAULT_WINDOWS_S,
    WindowedCounter,
    WindowedHistogram,
    WindowedHistogramFamily,
)


class JsonlWriter:
    """One JSON object per log event: ``{"step": n, "wall": t, ...metrics}``."""

    def __init__(self, path: str | Path):
        self._path = Path(path)
        self._path.parent.mkdir(parents=True, exist_ok=True)
        self._f = self._path.open("a")

    def write(self, step: int, metrics: dict) -> None:
        rec = {"step": step, "wall": time.time(), **metrics}
        self._f.write(json.dumps(rec) + "\n")
        self._f.flush()

    def close(self) -> None:
        self._f.close()


class TensorBoardWriter:
    """Scalar writer over flax's TensorBoard summary backend."""

    def __init__(self, logdir: str | Path):
        from flax.metrics import tensorboard

        self._sw = tensorboard.SummaryWriter(str(logdir))

    def write(self, step: int, metrics: dict) -> None:
        for k, v in metrics.items():
            self._sw.scalar(k, v, step)
        self._sw.flush()

    def close(self) -> None:
        self._sw.close()


class Counter:
    """Thread-safe monotonically-increasing counter."""

    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._n += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._n


class Gauge:
    """Thread-safe last-value gauge (queue depth, in-flight batch size)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._v = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._v = float(v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._v


class Histogram:
    """Thread-safe value histogram with percentile summaries.

    Keeps exact count/sum/max over the full stream plus a bounded ring of
    recent samples for the percentile estimates — serving runs are
    unbounded, so the sample buffer must not grow with traffic.
    """

    def __init__(self, max_samples: int = 8192):
        self._lock = threading.Lock()
        self._buf: list[float] = []
        self._max_samples = max_samples
        self._i = 0
        self.count = 0
        self.total = 0.0
        self.max = 0.0

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.total += v
            self.max = max(self.max, v)
            if len(self._buf) < self._max_samples:
                self._buf.append(v)
            else:
                self._buf[self._i] = v
                self._i = (self._i + 1) % self._max_samples

    def reset(self) -> None:
        """Zero the stream (per-measurement-window use, e.g. serve_bench)."""
        with self._lock:
            self._buf.clear()
            self._i = 0
            self.count = 0
            self.total = 0.0
            self.max = 0.0

    @staticmethod
    def _pct(s: list[float], p: float) -> float:
        """p in [0, 100] over an already-sorted sample list."""
        if not s:
            return 0.0
        k = min(len(s) - 1, max(0, int(round(p / 100.0 * (len(s) - 1)))))
        return s[k]

    def percentile(self, p: float) -> float:
        """p in [0, 100] over the retained sample window (0.0 when empty)."""
        with self._lock:
            s = sorted(self._buf)
        return self._pct(s, p)

    def summary(self) -> dict:
        # ONE lock acquisition and ONE sort: count/percentiles come from
        # the same instant, so a /metrics scrape never mixes a newer count
        # with older percentiles (and doesn't sort the buffer three times).
        with self._lock:
            count, total, mx = self.count, self.total, self.max
            s = sorted(self._buf)
        return {
            "count": count,
            "mean": total / count if count else 0.0,
            "p50": self._pct(s, 50),
            "p90": self._pct(s, 90),
            "p99": self._pct(s, 99),
            "max": mx,
        }


class LabelledGauge:
    """Thread-safe gauge family keyed by label (per-dtype KV bytes per
    token). Labels are created on first ``set``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._vals: dict = {}

    def set(self, label, v: float) -> None:
        with self._lock:
            self._vals[label] = float(v)

    def snapshot(self) -> dict:
        with self._lock:
            return {str(k): v for k, v in sorted(self._vals.items())}

    def reset(self) -> None:
        with self._lock:
            self._vals.clear()


class LabelledCounter:
    """Thread-safe counter family keyed by label (per-tier / per-bucket
    hit counts). Labels are created on first ``inc``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._vals: dict = {}

    def inc(self, label, n: int = 1) -> None:
        with self._lock:
            self._vals[label] = self._vals.get(label, 0) + n

    def snapshot(self) -> dict:
        with self._lock:
            return {str(k): v for k, v in sorted(self._vals.items())}

    def reset(self) -> None:
        with self._lock:
            self._vals.clear()


class LabelledHistogram:
    """Thread-safe histogram family keyed by label (per-tier occupancy)."""

    def __init__(self, max_samples: int = 2048):
        self._lock = threading.Lock()
        self._max_samples = max_samples
        self._hists: dict = {}

    def observe(self, label, v: float) -> None:
        with self._lock:
            h = self._hists.get(label)
            if h is None:
                h = self._hists[label] = Histogram(self._max_samples)
        h.observe(v)

    def snapshot(self) -> dict:
        with self._lock:
            hists = dict(self._hists)
        return {str(k): h.summary() for k, h in sorted(hists.items())}

    def reset(self) -> None:
        with self._lock:
            self._hists.clear()


class FeedMetrics:
    """Feed-path observability bundle (data/prefetch.py wires the feeder
    side; ``train.fit`` wires the consumer side and surfaces a summary at
    its log cadence).

    Two sides write into it:

    - **feeder** (the prefetch thread, or the inline path when prefetch is
      off): ``assembly`` histogram (seconds per batch of host assembly +
      host→device transfer), ``batches_assembled`` counter, ``queue_depth``
      gauge.
    - **consumer** (the training loop / bench harness): ``observe_wait``
      with the seconds it blocked waiting for a batch. In steady state with
      prefetch on, host wait ≈ 0 — assembly is hidden behind device
      compute; host wait ≈ assembly means the run is feed-bound.

    ``window()`` pops the per-log-window summary (mean host wait since the
    last call + current queue depth), so a feed-bound run is diagnosable
    from the step log instead of inferred.
    """

    def __init__(self):
        self.host_wait = Histogram()       # s/step the consumer blocked on feed
        self.assembly = Histogram()        # s/batch of assembly + device put
        self.queue_depth = Gauge()         # prefetch queue occupancy
        self.batches_assembled = Counter()
        # Time-aware twin of host_wait (obs/timeseries.py): trailing-window
        # wait distribution, so a feed regression is visible while it
        # happens (the fleet straggler detector reads it via StepTimeline).
        self.host_wait_w = WindowedHistogram()
        self._lock = threading.Lock()
        self._win_wait = 0.0
        self._win_steps = 0

    def observe_wait(self, seconds: float) -> None:
        """Consumer-side: record one blocking wait for a batch."""
        self.host_wait.observe(seconds)
        self.host_wait_w.observe(seconds)
        with self._lock:
            self._win_wait += float(seconds)
            self._win_steps += 1

    def window(self) -> dict:
        """Pop the log-cadence summary (resets the window accumulators)."""
        with self._lock:
            wait, steps = self._win_wait, self._win_steps
            self._win_wait, self._win_steps = 0.0, 0
        return {
            "host_wait_ms": (1e3 * wait / steps) if steps else 0.0,
            "feed_queue_depth": self.queue_depth.value,
        }

    def snapshot(self) -> dict:
        """Full-stream summary (feed_bench / tests)."""
        return {
            "host_wait_ms": {
                k: (v * 1e3 if k != "count" else v)
                for k, v in self.host_wait.summary().items()
            },
            "assembly_ms": {
                k: (v * 1e3 if k != "count" else v)
                for k, v in self.assembly.summary().items()
            },
            "queue_depth": self.queue_depth.value,
            "batches_assembled": self.batches_assembled.value,
        }


class ServeMetrics:
    """The serving subsystem's observability bundle (serve/batcher.py wires
    it; serve/server.py exposes it as JSON at ``GET /metrics`` and as
    Prometheus text at ``GET /metrics?format=prom`` via obs/export.py).

    Two generations of families live side by side:

    - **cumulative** (since boot): the original Counter/Gauge/Histogram
      instruments — stable JSON keys, Prometheus counter/histogram
      exposition;
    - **windowed** (obs/timeseries.py): trailing-rate counters and
      bucketed windowed histograms feeding the SLO burn-rate math and the
      readiness probe.  ``windowed=False`` skips them (one bool check on
      the hot path) — the A/B knob for the overhead measurement in
      docs/PERF.md.

    ``latency_bounds`` overrides the windowed latency bucket layout; pass
    ``obs.timeseries.bounds_with(slo_threshold_s)`` so SLO attainment at
    the threshold is exact (cli/serve.py and serve_bench do).
    """

    #: trailing windows surfaced in snapshots (short, mid, long)
    WINDOWS_S = DEFAULT_WINDOWS_S

    def __init__(self, windowed: bool = True, latency_bounds: tuple | None = None):
        self.windowed = windowed
        self.latency = Histogram()          # seconds, submit -> reply
        self.batch_occupancy = Histogram()  # rows per flushed batch
        self.queue_depth = Gauge()
        self.in_flight = Gauge()            # dispatched-not-yet-fetched batches
        self.requests = Counter()
        self.rejected = Counter()           # backpressure rejections
        self.batches = Counter()
        self.errors = Counter()             # batches that raised
        self.padded_rows = Counter()        # wasted executable rows (tier - occupancy)
        self.tier_hits = LabelledCounter()      # dispatches per batch tier
        self.bucket_hits = LabelledCounter()    # dispatches per sequence bucket
        self.tier_occupancy = LabelledHistogram()  # rows per dispatch, by tier
        # Layout-labelled twins of the dispatch instruments, keyed
        # "<layout>/<tier|bucket>" (layout = parallel.mesh.layout_label, e.g.
        # "dp2-tp4") — ADDITIVE alongside the unlabelled ones so single-mesh
        # deployments keep their stable /metrics keys while multi-layout
        # fleets can attribute hits per mesh layout.
        self.layout_tier_hits = LabelledCounter()
        self.layout_bucket_hits = LabelledCounter()
        # Per-request phase breakdown (seconds), keyed by phase name
        # (queue_wait/batch_assemble/dispatch/device/fetch on the pipelined
        # path) — the histogram form of the per-request `Future.phases`
        # dict, so serve_bench p99 is attributable to a pipeline stage.
        self.phase = LabelledHistogram()
        # Per-layout phase histograms, keyed "<layout>/<phase>" — written by
        # observe_phase alongside the plain phase family, so mesh layouts'
        # device-time distributions are separable (a TP engine's "device"
        # phase includes its psums; the DP engine's does not).
        self.layout_phase = LabelledHistogram()
        # Requests that never produced a result, by cause: "backpressure"
        # (queue full), "validation" (RequestError at submit),
        # "engine_failure" (batch raised mid-flight), "closed".
        self.rejected_by_cause = LabelledCounter()
        # ------------------------------------------------- decode families
        # Per-token observability for the continuous-batching decode path
        # (serve/batcher.ContinuousBatcher). Per-token latency itself rides
        # the phase family as "decode_step" (one sample per fetched token);
        # these are the aggregates that family can't carry.
        self.tokens = Counter()        # generated tokens delivered
        self.decode_steps = Counter()  # decode-step executions (all slots)
        self.slots_active = Gauge()    # occupied KV-cache slots
        self.ttft = Histogram()        # seconds, submit -> first token
        self.itl = Histogram()         # seconds between consecutive tokens
        # Prefix-cache (serve/kvpool.py) families: admissions that
        # consulted the trie, the subset that matched a cached head, the
        # prompt tokens those matches skipped (suffix-only prefill), and
        # the bytes of KV pages the pool currently holds.
        self.prefix_lookups = Counter()
        self.prefix_hits = Counter()
        self.prefix_tokens_saved = Counter()
        self.kv_pool_bytes = Gauge()
        # Quantized serving (models/quant.py): slot-cache bytes one cached
        # token occupies, keyed by the engine's KV storage dtype — the
        # capacity story behind int8 KV ("serve_kv_bytes_per_token" in
        # prom; DEPLOY.md's sizing math divides the HBM budget by this).
        self.kv_bytes_per_token = LabelledGauge()
        # Speculative-decoding (serve/spec.py) families: drafted candidate
        # tokens, the subset the verify step accepted, and verify steps
        # that rejected at least one draft. acceptance = accepted/drafted;
        # the windowed twins below carry the trailing-rate form.
        self.draft_tokens = Counter()
        self.accepted_tokens = Counter()
        self.spec_rejects = Counter()
        # Disaggregated-serving (serve/disagg.py) families, keyed by role
        # ("prefill"/"decode" — the side that sourced/adopted the chain):
        # KV-page bytes moved between engine pools and the wall-clock
        # seconds each transfer took (export + transport + adoption).
        self.kv_transfer_bytes = LabelledCounter()
        self.kv_transfer_seconds = LabelledHistogram()
        # Live stream migration (serve/disagg.py StreamReceiver +
        # migrate_streams), keyed by outcome: "adopted"/"rejected" on the
        # receiving replica, "migrated"/"readopted" on the exporting one.
        self.stream_migrations = LabelledCounter()
        # Priority-preemptive scheduling (serve/batcher.py), keyed by how
        # the park went: "paged" (KV lanes published into parked pool
        # pages), "pageless" (resume_tokens replay only), or the abort
        # reasons "park_full"/"bucket_overflow" (victim kept its slot and
        # finished). serve_preemptions_total in prom.
        self.preemptions = LabelledCounter()
        # Queued requests per priority class (label = class number as a
        # string; 0 is the most urgent). serve_sched_queue_depth in prom.
        self.sched_queue_depth = LabelledGauge()
        # ------------------------------------------------ windowed families
        # (obs/timeseries.py) — the SLO/health layer's inputs.  bad_w
        # counts requests that burned availability budget (backpressure +
        # engine failure + closed; NOT validation — that's the client's
        # error); ok_w counts delivered results.  rejected_w is the
        # backpressure-only series the saturation probe reads.
        bounds = latency_bounds or DEFAULT_LATENCY_BOUNDS
        self.latency_w = WindowedHistogram(bounds=bounds)
        self.phase_w = WindowedHistogramFamily(bounds=bounds)
        self.requests_w = WindowedCounter()   # accepted submissions
        self.ok_w = WindowedCounter()         # delivered results
        self.bad_w = WindowedCounter()        # budget-burning failures
        self.rejected_w = WindowedCounter()   # backpressure sheds only
        self.tokens_w = WindowedCounter()     # generated tokens (tokens/s)
        self.drafted_w = WindowedCounter()    # speculative drafts proposed
        self.accepted_w = WindowedCounter()   # speculative drafts accepted

    def observe_phase(self, name: str, seconds: float, layout: str = "") -> None:
        """Record one per-request phase sample, double-keyed by the engine's
        mesh layout when one is known (serve/batcher.py passes it through)."""
        self.phase.observe(name, seconds)
        if self.windowed:
            self.phase_w.observe(name, seconds)
        if layout:
            self.layout_phase.observe(f"{layout}/{name}", seconds)

    def observe_phase_batch(
        self,
        name: str,
        values: Sequence[float],
        layout: str = "",
        now: float | None = None,
    ) -> None:
        """One flush's worth of samples for a single phase. The windowed
        twin takes its lock ONCE for the whole batch (``observe_many``) —
        per-sample locking would scale hot-path lock traffic with the
        batch size (and trip the racetrace-overhead bound in tests)."""
        for v in values:
            self.phase.observe(name, v)
            if layout:
                self.layout_phase.observe(f"{layout}/{name}", v)
        if self.windowed:
            self.phase_w.observe_many(name, values, now)

    def windowed_snapshot(self) -> dict:
        """Per-window trailing rates + latency quantiles (ms), keyed
        "10s"/"60s"/"300s" — the time-aware section of ``snapshot()``."""
        out = {}
        for w in self.WINDOWS_S:
            lat = self.latency_w.window_summary(w)
            drafted = self.drafted_w.sum(w)
            out[f"{w:g}s"] = {
                "request_rate": self.requests_w.rate(w),
                "ok_rate": self.ok_w.rate(w),
                "rejected_rate": self.rejected_w.rate(w),
                "failure_rate": self.bad_w.rate(w),
                "token_rate": self.tokens_w.rate(w),
                # Trailing draft-acceptance rate (accepted/drafted over the
                # window); 0.0 when speculation is off or idle.
                "spec_acceptance": (
                    self.accepted_w.sum(w) / drafted if drafted else 0.0
                ),
                "latency_ms": {
                    "count": lat["count"],
                    "p50": lat["p50"] * 1e3,
                    "p90": lat["p90"] * 1e3,
                    "p99": lat["p99"] * 1e3,
                },
            }
        return out

    def snapshot(self) -> dict:
        lat = self.latency.summary()
        return {
            "requests": self.requests.value,
            "rejected": self.rejected.value,
            "batches": self.batches.value,
            "errors": self.errors.value,
            "queue_depth": self.queue_depth.value,
            "in_flight": self.in_flight.value,
            "padded_rows": self.padded_rows.value,
            "latency_ms": {
                k: (v * 1e3 if k != "count" else v) for k, v in lat.items()
            },
            "batch_occupancy": self.batch_occupancy.summary(),
            "tier_hits": self.tier_hits.snapshot(),
            "bucket_hits": self.bucket_hits.snapshot(),
            "tier_occupancy": self.tier_occupancy.snapshot(),
            "layout_tier_hits": self.layout_tier_hits.snapshot(),
            "layout_bucket_hits": self.layout_bucket_hits.snapshot(),
            "rejected_by_cause": self.rejected_by_cause.snapshot(),
            "tokens": self.tokens.value,
            "decode_steps": self.decode_steps.value,
            "slots_active": self.slots_active.value,
            "prefix_lookups": self.prefix_lookups.value,
            "prefix_hits": self.prefix_hits.value,
            "prefix_tokens_saved": self.prefix_tokens_saved.value,
            "kv_pool_bytes": self.kv_pool_bytes.value,
            "kv_bytes_per_token": self.kv_bytes_per_token.snapshot(),
            "draft_tokens": self.draft_tokens.value,
            "accepted_tokens": self.accepted_tokens.value,
            "spec_rejects": self.spec_rejects.value,
            "kv_transfer_bytes": self.kv_transfer_bytes.snapshot(),
            "kv_transfer_seconds": self.kv_transfer_seconds.snapshot(),
            "stream_migrations": self.stream_migrations.snapshot(),
            "preemptions": self.preemptions.snapshot(),
            "sched_queue_depth": self.sched_queue_depth.snapshot(),
            "ttft_ms": {
                k: (v * 1e3 if k != "count" else v)
                for k, v in self.ttft.summary().items()
            },
            "itl_ms": {
                k: (v * 1e3 if k != "count" else v)
                for k, v in self.itl.summary().items()
            },
            "phase_ms": {
                phase: {
                    k: (v * 1e3 if k != "count" else v)
                    for k, v in summ.items()
                }
                for phase, summ in self.phase.snapshot().items()
            },
            "layout_phase_ms": {
                key: {
                    k: (v * 1e3 if k != "count" else v)
                    for k, v in summ.items()
                }
                for key, summ in self.layout_phase.snapshot().items()
            },
            **(
                {"windowed": self.windowed_snapshot()} if self.windowed else {}
            ),
        }


def make_metric_hook(
    logdir: str | Path | None = None,
    jsonl: str | Path | None = None,
):
    """Build a ``fit()`` hook writing to TensorBoard and/or JSONL.

    Process 0 only; returns a no-op hook elsewhere. The hook signature is
    the loop's: ``hook(step, state, metrics)``. Empty strings count as
    unset — a default-constructed CLI arg must never create an event file
    in the current directory.
    """
    logdir = logdir or None
    jsonl = jsonl or None
    if jax.process_index() != 0 or (logdir is None and jsonl is None):
        return lambda step, state, metrics: None
    writers = []
    if logdir is not None:
        writers.append(TensorBoardWriter(logdir))
    if jsonl is not None:
        writers.append(JsonlWriter(jsonl))

    def hook(step: int, state, metrics: dict) -> None:
        del state
        for w in writers:
            w.write(step, metrics)

    hook.writers = writers  # exposed so callers/tests can close them
    return hook
