"""Metric writers and serving instruments.

Training side: TensorBoard scalars and append-only JSONL. Only process 0
writes (the reference gated summaries on the chief the same way,
SURVEY.md §5); other hosts get no-op hooks, so call sites stay branch-free.

Serving side (serve/): thread-safe :class:`Counter` / :class:`Gauge` /
:class:`Histogram` primitives and the :class:`ServeMetrics` bundle — the
per-request latency histogram (p50/p99), queue-depth and batch-occupancy
gauges the inference engine exposes at ``GET /metrics``.
"""

from __future__ import annotations

import json
import threading
import time
from pathlib import Path

import jax


class JsonlWriter:
    """One JSON object per log event: ``{"step": n, "wall": t, ...metrics}``."""

    def __init__(self, path: str | Path):
        self._path = Path(path)
        self._path.parent.mkdir(parents=True, exist_ok=True)
        self._f = self._path.open("a")

    def write(self, step: int, metrics: dict) -> None:
        rec = {"step": step, "wall": time.time(), **metrics}
        self._f.write(json.dumps(rec) + "\n")
        self._f.flush()

    def close(self) -> None:
        self._f.close()


class TensorBoardWriter:
    """Scalar writer over flax's TensorBoard summary backend."""

    def __init__(self, logdir: str | Path):
        from flax.metrics import tensorboard

        self._sw = tensorboard.SummaryWriter(str(logdir))

    def write(self, step: int, metrics: dict) -> None:
        for k, v in metrics.items():
            self._sw.scalar(k, v, step)
        self._sw.flush()

    def close(self) -> None:
        self._sw.close()


class Counter:
    """Thread-safe monotonically-increasing counter."""

    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._n += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._n


class Gauge:
    """Thread-safe last-value gauge (queue depth, in-flight batch size)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._v = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._v = float(v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._v


class Histogram:
    """Thread-safe value histogram with percentile summaries.

    Keeps exact count/sum/max over the full stream plus a bounded ring of
    recent samples for the percentile estimates — serving runs are
    unbounded, so the sample buffer must not grow with traffic.
    """

    def __init__(self, max_samples: int = 8192):
        self._lock = threading.Lock()
        self._buf: list[float] = []
        self._max_samples = max_samples
        self._i = 0
        self.count = 0
        self.total = 0.0
        self.max = 0.0

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.total += v
            self.max = max(self.max, v)
            if len(self._buf) < self._max_samples:
                self._buf.append(v)
            else:
                self._buf[self._i] = v
                self._i = (self._i + 1) % self._max_samples

    def reset(self) -> None:
        """Zero the stream (per-measurement-window use, e.g. serve_bench)."""
        with self._lock:
            self._buf.clear()
            self._i = 0
            self.count = 0
            self.total = 0.0
            self.max = 0.0

    def percentile(self, p: float) -> float:
        """p in [0, 100] over the retained sample window (0.0 when empty)."""
        with self._lock:
            if not self._buf:
                return 0.0
            s = sorted(self._buf)
        k = min(len(s) - 1, max(0, int(round(p / 100.0 * (len(s) - 1)))))
        return s[k]

    def summary(self) -> dict:
        with self._lock:
            count, total, mx = self.count, self.total, self.max
        return {
            "count": count,
            "mean": total / count if count else 0.0,
            "p50": self.percentile(50),
            "p90": self.percentile(90),
            "p99": self.percentile(99),
            "max": mx,
        }


class LabelledCounter:
    """Thread-safe counter family keyed by label (per-tier / per-bucket
    hit counts). Labels are created on first ``inc``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._vals: dict = {}

    def inc(self, label, n: int = 1) -> None:
        with self._lock:
            self._vals[label] = self._vals.get(label, 0) + n

    def snapshot(self) -> dict:
        with self._lock:
            return {str(k): v for k, v in sorted(self._vals.items())}

    def reset(self) -> None:
        with self._lock:
            self._vals.clear()


class LabelledHistogram:
    """Thread-safe histogram family keyed by label (per-tier occupancy)."""

    def __init__(self, max_samples: int = 2048):
        self._lock = threading.Lock()
        self._max_samples = max_samples
        self._hists: dict = {}

    def observe(self, label, v: float) -> None:
        with self._lock:
            h = self._hists.get(label)
            if h is None:
                h = self._hists[label] = Histogram(self._max_samples)
        h.observe(v)

    def snapshot(self) -> dict:
        with self._lock:
            hists = dict(self._hists)
        return {str(k): h.summary() for k, h in sorted(hists.items())}

    def reset(self) -> None:
        with self._lock:
            self._hists.clear()


class FeedMetrics:
    """Feed-path observability bundle (data/prefetch.py wires the feeder
    side; ``train.fit`` wires the consumer side and surfaces a summary at
    its log cadence).

    Two sides write into it:

    - **feeder** (the prefetch thread, or the inline path when prefetch is
      off): ``assembly`` histogram (seconds per batch of host assembly +
      host→device transfer), ``batches_assembled`` counter, ``queue_depth``
      gauge.
    - **consumer** (the training loop / bench harness): ``observe_wait``
      with the seconds it blocked waiting for a batch. In steady state with
      prefetch on, host wait ≈ 0 — assembly is hidden behind device
      compute; host wait ≈ assembly means the run is feed-bound.

    ``window()`` pops the per-log-window summary (mean host wait since the
    last call + current queue depth), so a feed-bound run is diagnosable
    from the step log instead of inferred.
    """

    def __init__(self):
        self.host_wait = Histogram()       # s/step the consumer blocked on feed
        self.assembly = Histogram()        # s/batch of assembly + device put
        self.queue_depth = Gauge()         # prefetch queue occupancy
        self.batches_assembled = Counter()
        self._lock = threading.Lock()
        self._win_wait = 0.0
        self._win_steps = 0

    def observe_wait(self, seconds: float) -> None:
        """Consumer-side: record one blocking wait for a batch."""
        self.host_wait.observe(seconds)
        with self._lock:
            self._win_wait += float(seconds)
            self._win_steps += 1

    def window(self) -> dict:
        """Pop the log-cadence summary (resets the window accumulators)."""
        with self._lock:
            wait, steps = self._win_wait, self._win_steps
            self._win_wait, self._win_steps = 0.0, 0
        return {
            "host_wait_ms": (1e3 * wait / steps) if steps else 0.0,
            "feed_queue_depth": self.queue_depth.value,
        }

    def snapshot(self) -> dict:
        """Full-stream summary (feed_bench / tests)."""
        return {
            "host_wait_ms": {
                k: (v * 1e3 if k != "count" else v)
                for k, v in self.host_wait.summary().items()
            },
            "assembly_ms": {
                k: (v * 1e3 if k != "count" else v)
                for k, v in self.assembly.summary().items()
            },
            "queue_depth": self.queue_depth.value,
            "batches_assembled": self.batches_assembled.value,
        }


class ServeMetrics:
    """The serving subsystem's observability bundle (serve/batcher.py wires
    it; serve/server.py exposes it as JSON at ``GET /metrics``)."""

    def __init__(self):
        self.latency = Histogram()          # seconds, submit -> reply
        self.batch_occupancy = Histogram()  # rows per flushed batch
        self.queue_depth = Gauge()
        self.in_flight = Gauge()            # dispatched-not-yet-fetched batches
        self.requests = Counter()
        self.rejected = Counter()           # backpressure rejections
        self.batches = Counter()
        self.errors = Counter()             # batches that raised
        self.padded_rows = Counter()        # wasted executable rows (tier - occupancy)
        self.tier_hits = LabelledCounter()      # dispatches per batch tier
        self.bucket_hits = LabelledCounter()    # dispatches per sequence bucket
        self.tier_occupancy = LabelledHistogram()  # rows per dispatch, by tier
        # Layout-labelled twins of the dispatch instruments, keyed
        # "<layout>/<tier|bucket>" (layout = parallel.mesh.layout_label, e.g.
        # "dp2-tp4") — ADDITIVE alongside the unlabelled ones so single-mesh
        # deployments keep their stable /metrics keys while multi-layout
        # fleets can attribute hits per mesh layout.
        self.layout_tier_hits = LabelledCounter()
        self.layout_bucket_hits = LabelledCounter()
        # Per-request phase breakdown (seconds), keyed by phase name
        # (queue_wait/batch_assemble/dispatch/device/fetch on the pipelined
        # path) — the histogram form of the per-request `Future.phases`
        # dict, so serve_bench p99 is attributable to a pipeline stage.
        self.phase = LabelledHistogram()
        # Per-layout phase histograms, keyed "<layout>/<phase>" — written by
        # observe_phase alongside the plain phase family, so mesh layouts'
        # device-time distributions are separable (a TP engine's "device"
        # phase includes its psums; the DP engine's does not).
        self.layout_phase = LabelledHistogram()
        # Requests that never produced a result, by cause: "backpressure"
        # (queue full), "validation" (RequestError at submit),
        # "engine_failure" (batch raised mid-flight), "closed".
        self.rejected_by_cause = LabelledCounter()

    def observe_phase(self, name: str, seconds: float, layout: str = "") -> None:
        """Record one per-request phase sample, double-keyed by the engine's
        mesh layout when one is known (serve/batcher.py passes it through)."""
        self.phase.observe(name, seconds)
        if layout:
            self.layout_phase.observe(f"{layout}/{name}", seconds)

    def snapshot(self) -> dict:
        lat = self.latency.summary()
        return {
            "requests": self.requests.value,
            "rejected": self.rejected.value,
            "batches": self.batches.value,
            "errors": self.errors.value,
            "queue_depth": self.queue_depth.value,
            "in_flight": self.in_flight.value,
            "padded_rows": self.padded_rows.value,
            "latency_ms": {
                k: (v * 1e3 if k != "count" else v) for k, v in lat.items()
            },
            "batch_occupancy": self.batch_occupancy.summary(),
            "tier_hits": self.tier_hits.snapshot(),
            "bucket_hits": self.bucket_hits.snapshot(),
            "tier_occupancy": self.tier_occupancy.snapshot(),
            "layout_tier_hits": self.layout_tier_hits.snapshot(),
            "layout_bucket_hits": self.layout_bucket_hits.snapshot(),
            "rejected_by_cause": self.rejected_by_cause.snapshot(),
            "phase_ms": {
                phase: {
                    k: (v * 1e3 if k != "count" else v)
                    for k, v in summ.items()
                }
                for phase, summ in self.phase.snapshot().items()
            },
            "layout_phase_ms": {
                key: {
                    k: (v * 1e3 if k != "count" else v)
                    for k, v in summ.items()
                }
                for key, summ in self.layout_phase.snapshot().items()
            },
        }


def make_metric_hook(
    logdir: str | Path | None = None,
    jsonl: str | Path | None = None,
):
    """Build a ``fit()`` hook writing to TensorBoard and/or JSONL.

    Process 0 only; returns a no-op hook elsewhere. The hook signature is
    the loop's: ``hook(step, state, metrics)``. Empty strings count as
    unset — a default-constructed CLI arg must never create an event file
    in the current directory.
    """
    logdir = logdir or None
    jsonl = jsonl or None
    if jax.process_index() != 0 or (logdir is None and jsonl is None):
        return lambda step, state, metrics: None
    writers = []
    if logdir is not None:
        writers.append(TensorBoardWriter(logdir))
    if jsonl is not None:
        writers.append(JsonlWriter(jsonl))

    def hook(step: int, state, metrics: dict) -> None:
        del state
        for w in writers:
            w.write(step, metrics)

    hook.writers = writers  # exposed so callers/tests can close them
    return hook
