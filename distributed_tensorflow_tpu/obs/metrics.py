"""Metric writers: TensorBoard scalars and append-only JSONL.

Only process 0 writes (the reference gated summaries on the chief the same
way, SURVEY.md §5); other hosts get no-op hooks, so call sites stay
branch-free.
"""

from __future__ import annotations

import json
import time
from pathlib import Path

import jax


class JsonlWriter:
    """One JSON object per log event: ``{"step": n, "wall": t, ...metrics}``."""

    def __init__(self, path: str | Path):
        self._path = Path(path)
        self._path.parent.mkdir(parents=True, exist_ok=True)
        self._f = self._path.open("a")

    def write(self, step: int, metrics: dict) -> None:
        rec = {"step": step, "wall": time.time(), **metrics}
        self._f.write(json.dumps(rec) + "\n")
        self._f.flush()

    def close(self) -> None:
        self._f.close()


class TensorBoardWriter:
    """Scalar writer over flax's TensorBoard summary backend."""

    def __init__(self, logdir: str | Path):
        from flax.metrics import tensorboard

        self._sw = tensorboard.SummaryWriter(str(logdir))

    def write(self, step: int, metrics: dict) -> None:
        for k, v in metrics.items():
            self._sw.scalar(k, v, step)
        self._sw.flush()

    def close(self) -> None:
        self._sw.close()


def make_metric_hook(
    logdir: str | Path | None = None,
    jsonl: str | Path | None = None,
):
    """Build a ``fit()`` hook writing to TensorBoard and/or JSONL.

    Process 0 only; returns a no-op hook elsewhere. The hook signature is
    the loop's: ``hook(step, state, metrics)``. Empty strings count as
    unset — a default-constructed CLI arg must never create an event file
    in the current directory.
    """
    logdir = logdir or None
    jsonl = jsonl or None
    if jax.process_index() != 0 or (logdir is None and jsonl is None):
        return lambda step, state, metrics: None
    writers = []
    if logdir is not None:
        writers.append(TensorBoardWriter(logdir))
    if jsonl is not None:
        writers.append(JsonlWriter(jsonl))

    def hook(step: int, state, metrics: dict) -> None:
        del state
        for w in writers:
            w.write(step, metrics)

    hook.writers = writers  # exposed so callers/tests can close them
    return hook
