"""Prometheus text-format exposition (format 0.0.4) for the obs layer.

``GET /metrics?format=prom`` renders every ``ServeMetrics`` family — the
cumulative counters/gauges, native Prometheus histograms cut from the
windowed-bucket cumulative counts (monotone across scrapes by
construction), quantile gauges from the sample-ring summaries, the
trailing-window rate/quantile gauges, and (when configured) the SLO
attainment/burn-rate/verdict and readiness-state families.

The renderer is deliberately dumb: build :class:`Family` rows, then
:func:`render` emits ``# HELP`` / ``# TYPE`` / sample lines with label
escaping per the exposition spec (``\\`` -> ``\\\\``, ``"`` -> ``\\"``,
newline -> ``\\n``).  Tests parse every emitted line back
(tests/test_serve_health.py) — if it doesn't round-trip, it doesn't ship.
"""

from __future__ import annotations

import math

_VERDICT_VALUE = {"ok": 0, "warn": 1, "page": 2}


def escape_label_value(v: str) -> str:
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if math.isnan(f):
        return "NaN"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class Family:
    """One exposition family: a TYPE/HELP header plus sample lines.

    ``samples`` rows are ``(suffix, labels, value)`` — suffix is appended
    to the family name (``_bucket``/``_sum``/``_count`` for histograms,
    empty otherwise).
    """

    def __init__(self, name: str, mtype: str, help_: str):
        self.name = name
        self.mtype = mtype
        self.help = help_
        self.samples: list[tuple[str, dict, float]] = []

    def add(self, value, labels: dict | None = None, suffix: str = "") -> "Family":
        self.samples.append((suffix, labels or {}, value))
        return self


def render(families: list[Family]) -> str:
    lines = []
    for fam in families:
        if not fam.samples:
            continue
        lines.append(f"# HELP {fam.name} {fam.help}")
        lines.append(f"# TYPE {fam.name} {fam.mtype}")
        for suffix, labels, value in fam.samples:
            if labels:
                lbl = ",".join(
                    f'{k}="{escape_label_value(v)}"'
                    for k, v in sorted(labels.items())
                )
                lines.append(f"{fam.name}{suffix}{{{lbl}}} {_fmt(value)}")
            else:
                lines.append(f"{fam.name}{suffix} {_fmt(value)}")
    return "\n".join(lines) + "\n"


def histogram_family(
    name: str, help_: str, cumulatives: dict[tuple, dict]
) -> Family:
    """Native Prometheus histogram from ``WindowedHistogram.cumulative()``
    snapshots, one labelset per entry.  Bucket lines are CUMULATIVE counts
    (``le`` convention) ending at ``+Inf == _count`` — monotone across
    scrapes because the source counts are since-boot."""
    fam = Family(name, "histogram", help_)
    for labels_items, cum in cumulatives.items():
        labels = dict(labels_items)
        acc = 0
        for bound, count in zip(cum["bounds"], cum["counts"]):
            acc += count
            fam.add(acc, {**labels, "le": _fmt(bound)}, "_bucket")
        fam.add(cum["count"], {**labels, "le": "+Inf"}, "_bucket")
        fam.add(cum["sum"], labels, "_sum")
        fam.add(cum["count"], labels, "_count")
    return fam


def _summary_quantiles(name: str, help_: str, summaries: dict[tuple, dict],
                       scale: float = 1.0) -> Family:
    """Quantile gauges from a sample-ring ``Histogram.summary()`` dict
    (p50/p90/p99 + max) — the legacy estimator, kept alongside the
    bucketed histograms for continuity with the JSON snapshot."""
    fam = Family(name, "gauge", help_)
    for labels_items, summ in summaries.items():
        labels = dict(labels_items)
        for q, key in (("0.5", "p50"), ("0.9", "p90"), ("0.99", "p99")):
            fam.add(summ[key] * scale, {**labels, "quantile": q})
    return fam


def _split_layout_labels(snapshot: dict, value_key: str) -> list[tuple[dict, float]]:
    """``"<layout>/<x>" -> value`` labelled-counter snapshots into
    ``{layout=..., <value_key>=...}`` label pairs."""
    out = []
    for key, v in snapshot.items():
        layout, _, rest = key.partition("/")
        out.append(({"layout": layout, value_key: rest}, v))
    return out


def _kv_bytes_per_token_family(m) -> Family:
    """Per-dtype slot-cache bytes per cached token (models/quant.py int8
    KV halves-and-then-some this; the label keeps fp32 and int8 engines
    distinguishable on one dashboard)."""
    fam = Family("serve_kv_bytes_per_token", "gauge",
                 "slot-cache bytes one cached token occupies, by KV dtype")
    for dtype, v in m.kv_bytes_per_token.snapshot().items():
        fam.add(v, {"dtype": dtype})
    return fam


def serve_families(
    metrics, slo=None, health=None, memory=None, grid=None
) -> list[Family]:
    """Every ``ServeMetrics`` family (plus SLO + health + memory + compile
    grid when given) as exposition rows. ``memory`` is a
    :class:`~.memory.MemoryRegistry`; ``grid`` is an engine
    ``grid_status()`` digest dict."""
    m = metrics
    fams = [
        Family("serve_requests_total", "counter",
               "requests accepted into the batcher queue")
        .add(m.requests.value),
        Family("serve_rejected_total", "counter",
               "requests shed by backpressure").add(m.rejected.value),
        Family("serve_batches_total", "counter",
               "batches flushed to the engine").add(m.batches.value),
        Family("serve_errors_total", "counter",
               "batches that raised in the engine").add(m.errors.value),
        Family("serve_padded_rows_total", "counter",
               "executable rows burned on padding").add(m.padded_rows.value),
        Family("serve_queue_depth", "gauge",
               "requests waiting in the batcher").add(m.queue_depth.value),
        Family("serve_in_flight", "gauge",
               "batches dispatched but not yet fetched").add(m.in_flight.value),
        # Decode (continuous-batching) families — zero-valued but present
        # on scoring-only replicas, so dashboards need no per-mode wiring.
        Family("serve_tokens_total", "counter",
               "generated tokens delivered").add(m.tokens.value),
        Family("serve_decode_steps_total", "counter",
               "decode-step executions over the slot table")
        .add(m.decode_steps.value),
        Family("serve_slots_active", "gauge",
               "occupied KV-cache slots").add(m.slots_active.value),
        Family("serve_prefix_lookups_total", "counter",
               "admissions that consulted the prefix-cache trie")
        .add(m.prefix_lookups.value),
        Family("serve_prefix_hits_total", "counter",
               "admissions that matched a cached prompt prefix")
        .add(m.prefix_hits.value),
        Family("serve_prefix_tokens_saved_total", "counter",
               "prompt tokens skipped via cached KV pages")
        .add(m.prefix_tokens_saved.value),
        Family("serve_kv_pool_bytes", "gauge",
               "KV bytes held by the prefix-cache block pool")
        .add(m.kv_pool_bytes.value),
        _kv_bytes_per_token_family(m),
        # Speculative-decoding families (serve/spec.py).
        Family("serve_spec_draft_tokens_total", "counter",
               "speculative draft tokens proposed")
        .add(m.draft_tokens.value),
        Family("serve_spec_accepted_tokens_total", "counter",
               "speculative draft tokens accepted by verify")
        .add(m.accepted_tokens.value),
        Family("serve_spec_rejects_total", "counter",
               "verify steps that rejected at least one draft")
        .add(m.spec_rejects.value),
        Family("serve_spec_acceptance_ratio", "gauge",
               "lifetime draft-acceptance ratio (accepted/drafted)")
        .add(
            m.accepted_tokens.value / m.draft_tokens.value
            if m.draft_tokens.value else 0.0
        ),
    ]

    by_cause = Family("serve_rejected_by_cause_total", "counter",
                      "requests that never produced a result, by cause")
    for cause, v in m.rejected_by_cause.snapshot().items():
        by_cause.add(v, {"cause": cause})
    fams.append(by_cause)

    tier_hits = Family("serve_tier_hits_total", "counter",
                       "dispatches per batch tier")
    for tier, v in m.tier_hits.snapshot().items():
        tier_hits.add(v, {"tier": tier})
    fams.append(tier_hits)

    bucket_hits = Family("serve_bucket_hits_total", "counter",
                         "dispatches per sequence bucket")
    for bucket, v in m.bucket_hits.snapshot().items():
        bucket_hits.add(v, {"bucket": bucket})
    fams.append(bucket_hits)

    layout_tiers = Family("serve_layout_tier_hits_total", "counter",
                          "dispatches per mesh layout and batch tier")
    for labels, v in _split_layout_labels(m.layout_tier_hits.snapshot(), "tier"):
        layout_tiers.add(v, labels)
    fams.append(layout_tiers)

    layout_buckets = Family("serve_layout_bucket_hits_total", "counter",
                            "dispatches per mesh layout and sequence bucket")
    for labels, v in _split_layout_labels(
        m.layout_bucket_hits.snapshot(), "bucket"
    ):
        layout_buckets.add(v, labels)
    fams.append(layout_buckets)

    # Disaggregated-serving KV-page transfer families (serve/disagg.py),
    # role-labelled ("prefill" = chain exported, "decode" = chain adopted).
    kv_bytes = Family("serve_kv_transfer_bytes_total", "counter",
                      "KV-page bytes moved between engine roles")
    for role, v in m.kv_transfer_bytes.snapshot().items():
        kv_bytes.add(v, {"role": role})
    fams.append(kv_bytes)
    kv_secs = m.kv_transfer_seconds.snapshot()
    if kv_secs:
        fams.append(_summary_quantiles(
            "serve_kv_transfer_seconds",
            "per-transfer wall time quantiles by engine role",
            {(("role", role),): summ for role, summ in kv_secs.items()},
        ))

    # Live stream-migration outcomes (serve/disagg.py): "adopted" and
    # "rejected" count on the receiving replica, "migrated" and
    # "readopted" on the exporting one.
    migrations = Family("serve_stream_migrations_total", "counter",
                        "live decode-stream migrations by outcome")
    for outcome, v in m.stream_migrations.snapshot().items():
        migrations.add(v, {"outcome": outcome})
    fams.append(migrations)

    # Priority-preemptive scheduling (serve/batcher.py): parks by how they
    # went ("paged"/"pageless"/aborts) and live queue depth per priority
    # class (label "0" is the most urgent).
    preempts = Family("serve_preemptions_total", "counter",
                      "slot preemptions (parks + aborted parks) by reason")
    for reason, v in m.preemptions.snapshot().items():
        preempts.add(v, {"reason": reason})
    fams.append(preempts)
    sched_depth = Family("serve_sched_queue_depth", "gauge",
                         "queued requests per priority class")
    for cls, v in m.sched_queue_depth.snapshot().items():
        sched_depth.add(v, {"class": cls})
    fams.append(sched_depth)

    # Sample-ring quantile gauges (legacy estimator; ms families in the
    # JSON snapshot stay seconds here — exposition is SI).
    fams.append(_summary_quantiles(
        "serve_latency_quantile_seconds",
        "submit->reply latency quantiles (sample-ring estimator)",
        {(): m.latency.summary()},
    ))
    fams.append(_summary_quantiles(
        "serve_batch_occupancy_rows",
        "rows per flushed batch, quantiles",
        {(): m.batch_occupancy.summary()},
    ))
    fams.append(_summary_quantiles(
        "serve_tier_occupancy_rows",
        "rows per dispatch by batch tier, quantiles",
        {
            (("tier", tier),): summ
            for tier, summ in m.tier_occupancy.snapshot().items()
        },
    ))
    phase_summaries = {
        (("phase", name),): summ for name, summ in m.phase.snapshot().items()
    }
    fams.append(_summary_quantiles(
        "serve_phase_quantile_seconds",
        "per-request phase latency quantiles (sample-ring estimator)",
        phase_summaries,
    ))
    # Per-token latency quantiles (decode path; per-token samples also ride
    # the phase family as "decode_step").
    if m.ttft.summary()["count"]:
        fams.append(_summary_quantiles(
            "serve_ttft_quantile_seconds",
            "submit->first-token latency quantiles",
            {(): m.ttft.summary()},
        ))
    if m.itl.summary()["count"]:
        fams.append(_summary_quantiles(
            "serve_itl_quantile_seconds",
            "inter-token latency quantiles",
            {(): m.itl.summary()},
        ))

    if getattr(m, "windowed", False):
        # Native histograms from the windowed families' cumulative counts.
        fams.append(histogram_family(
            "serve_latency_seconds",
            "submit->reply latency (bucketed, cumulative since boot)",
            {(): m.latency_w.cumulative()},
        ))
        fams.append(histogram_family(
            "serve_phase_seconds",
            "per-request phase latency (bucketed, cumulative since boot)",
            {
                (("phase", str(label)),): m.phase_w.get(label).cumulative()
                for label in m.phase_w.labels()
            },
        ))
        # Trailing-window rate + quantile gauges.
        rates = Family("serve_window_rate", "gauge",
                       "trailing-window request rates by series (per second)")
        lat_q = Family("serve_window_latency_seconds", "gauge",
                       "trailing-window latency quantiles")
        for w in m.WINDOWS_S:
            wl = f"{w:g}s"
            for series, c in (
                ("requests", m.requests_w), ("ok", m.ok_w),
                ("rejected", m.rejected_w), ("failed", m.bad_w),
                ("tokens", m.tokens_w), ("spec_drafted", m.drafted_w),
                ("spec_accepted", m.accepted_w),
            ):
                rates.add(c.rate(w), {"window": wl, "series": series})
            summ = m.latency_w.window_summary(w)
            for q, key in (("0.5", "p50"), ("0.9", "p90"), ("0.99", "p99")):
                lat_q.add(summ[key], {"window": wl, "quantile": q})
        fams.extend([rates, lat_q])

    if slo is not None:
        rep = slo.report()
        att = Family("serve_slo_attainment", "gauge",
                     "fraction of good events per SLO and window")
        burn = Family("serve_slo_burn_rate", "gauge",
                      "error-budget burn multiple per SLO and window")
        verd = Family("serve_slo_verdict", "gauge",
                      "per-SLO verdict (0=ok 1=warn 2=page)")
        for s in rep["slos"]:
            for wl, row in s["windows"].items():
                att.add(row["attainment"], {"slo": s["name"], "window": wl})
                burn.add(row["burn_rate"], {"slo": s["name"], "window": wl})
            verd.add(_VERDICT_VALUE[s["verdict"]], {"slo": s["name"]})
        fams.extend([att, burn, verd])

    if health is not None:
        from distributed_tensorflow_tpu.obs.health import (
            SERVING_STATES,
            STATES,
        )

        state, _ = health.state()
        hs = Family("serve_health_state", "gauge",
                    "readiness state (one-hot)")
        for s in STATES:
            hs.add(1 if s == state else 0, {"state": s})
        fams.append(hs)
        fams.append(
            Family("serve_ready", "gauge",
                   "1 when /healthz answers 200")
            .add(1 if state in SERVING_STATES else 0)
        )

    if memory is not None:
        snap = memory.snapshot()
        dtypes = snap.get("component_dtypes", {})
        hbm = Family("hbm_reserved_bytes", "gauge",
                     "accounted device-memory reservation per component")
        for comp, nbytes in snap["components"].items():
            lbl = {"component": comp}
            # Quantized serving: components that declared a storage dtype
            # carry it, so "how much of HBM is int8" is one PromQL sum.
            if comp in dtypes:
                lbl["dtype"] = dtypes[comp]
            hbm.add(nbytes, lbl)
        fams.append(hbm)
        released = Family("hbm_released_bytes_total", "counter",
                          "device bytes released per component since boot")
        for comp, nbytes in snap["released"].items():
            released.add(nbytes, {"component": comp})
        fams.append(released)
        saved = Family(
            "hbm_bytes_saved_vs_fp32", "gauge",
            "bytes saved vs an fp32 baseline per quantized component",
        )
        for comp, nbytes in snap.get("bytes_saved_vs_fp32", {}).items():
            saved.add(nbytes, {"component": comp})
        fams.append(saved)
        in_use = Family("hbm_device_bytes_in_use", "gauge",
                        "backend-reported bytes_in_use per local device")
        limit = Family("hbm_device_bytes_limit", "gauge",
                       "backend-reported byte limit per local device")
        for row in snap["devices"]:
            if row.get("reported"):
                lbl = {"device": str(row["device"]),
                       "platform": row["platform"]}
                in_use.add(row["bytes_in_use"], lbl)
                limit.add(row["bytes_limit"], lbl)
        fams.extend([in_use, limit])

    if grid is not None:
        cells = Family("serve_compile_cells", "gauge",
                       "AOT grid cells by compile state")
        cells.add(grid["cells_compiled"], {"state": "compiled"})
        cells.add(grid["cells_failed"], {"state": "failed"})
        cells.add(
            max(grid["cells_total"] - grid["cells_compiled"]
                - grid["cells_failed"], 0),
            {"state": "pending"},
        )
        fams.append(cells)
        fams.append(
            Family("serve_compile_seconds_total", "counter",
                   "cumulative AOT grid compile wall time")
            .add(grid["compile_seconds_total"])
        )
        fams.append(
            Family("serve_grid_warm_fraction", "gauge",
                   "fraction of planned AOT grid cells compiled")
            .add(grid["warm_fraction"])
        )
    return fams


def prometheus_text(metrics, slo=None, health=None, memory=None,
                    grid=None) -> str:
    """The ``GET /metrics?format=prom`` body."""
    return render(serve_families(
        metrics, slo=slo, health=health, memory=memory, grid=grid
    ))


#: content type for the exposition reply
PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
