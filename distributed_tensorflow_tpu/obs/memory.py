"""Device-memory accounting: who is occupying HBM, by name.

The serving and training stacks allocate a handful of large, long-lived
device residencies — model params, AdamW slots, the decode engine's
slot-table KV cache, the prefix-cache page pool, the stale-mode gradient
ring, host staging buffers — and the contention between them is exactly
what ``ckpt.restore_serving_state(release_opt_state=True)`` exists to
manage. This module makes that contention *visible*: components register
named byte reservations at allocation time (sizes computed from array
shapes, so the accounting costs a dict write, never a device sync), and
the registry reconciles the accounted total against
``jax.local_devices()[i].memory_stats()`` where the backend reports it.

Degradation contract: ``memory_stats()`` is a TPU/GPU feature — CPU
backends return ``None`` or raise. The registry treats every per-device
failure as "unreported" and falls back to accounted-only totals, so
``GET /memz`` answers on every backend and the 10%%-reconciliation check
in ISSUE acceptance only applies where the runtime actually reports.

Threading: one small lock orders every method; no call ever re-enters a
caller's lock (engines call ``register`` while holding their own buffer
locks — the registry must never call back out).
"""

from __future__ import annotations

import threading

__all__ = [
    "MemoryRegistry",
    "default_registry",
    "reset_default_registry",
    "tree_nbytes",
]


def tree_nbytes(tree) -> int:
    """Total bytes of every array leaf in ``tree`` (jax or numpy — anything
    with ``.nbytes``). Shape-derived: never materializes or syncs."""
    import jax

    total = 0
    for leaf in jax.tree.leaves(tree):
        total += int(getattr(leaf, "nbytes", 0) or 0)
    return total


class MemoryRegistry:
    """Named byte reservations + device reconciliation.

    ``register`` SETS a component's reservation (idempotent re-registration
    — a rebuilt engine overwrites its dead predecessor's entry instead of
    double counting); ``add`` grows one (staging buffers accrete);
    ``release`` removes one, accumulating the freed bytes into a
    ``released`` ledger so a restore that drops the AdamW slots leaves an
    auditable trail in ``/memz`` rather than just a smaller number.

    ``devices_fn`` defaults to ``jax.local_devices`` and exists so tests
    can reconcile against stub devices without a real backend.
    """

    # Shared mutable state; every access is ordered by self._lock (the
    # sanitize_races soak can watch these when a test wraps an instance).
    _RACETRACE_ATTRS = ("_reserved", "_released", "_dtypes", "_fp32")

    def __init__(self, devices_fn=None):
        self._lock = threading.Lock()
        self._reserved: dict[str, int] = {}
        self._released: dict[str, int] = {}
        # Quantized-serving ledger (PR 19): the storage dtype a component
        # declared and what its payload WOULD cost at fp32, so /memz can
        # answer "what did int8 buy me" per component without re-deriving
        # shapes.
        self._dtypes: dict[str, str] = {}
        self._fp32: dict[str, int] = {}
        self._devices_fn = devices_fn

    # -------------------------------------------------------- bookkeeping

    def register(self, component: str, nbytes: int, *,
                 dtype: str | None = None,
                 fp32_nbytes: int | None = None) -> None:
        """Set ``component``'s reservation to ``nbytes`` (absolute).

        ``dtype`` / ``fp32_nbytes`` are optional quantization metadata:
        the storage dtype and the fp32-equivalent byte count of the same
        payload (``/memz`` reports ``fp32_nbytes - nbytes`` as
        ``bytes_saved_vs_fp32``)."""
        with self._lock:
            key = str(component)
            self._reserved[key] = int(nbytes)
            if dtype is not None:
                self._dtypes[key] = str(dtype)
            else:
                self._dtypes.pop(key, None)
            if fp32_nbytes is not None:
                self._fp32[key] = int(fp32_nbytes)
            else:
                self._fp32.pop(key, None)

    def add(self, component: str, nbytes: int) -> None:
        """Grow ``component``'s reservation by ``nbytes``."""
        with self._lock:
            key = str(component)
            self._reserved[key] = self._reserved.get(key, 0) + int(nbytes)

    def register_tree(self, component: str, tree, *,
                      dtype: str | None = None,
                      fp32_nbytes: int | None = None) -> int:
        """``register`` with bytes summed from an array pytree; returns the
        byte count so callers can log it."""
        n = tree_nbytes(tree)
        self.register(component, n, dtype=dtype, fp32_nbytes=fp32_nbytes)
        return n

    def release(self, component: str, nbytes: int | None = None) -> int:
        """Drop ``component``'s reservation (or ``nbytes`` of it) and
        record the freed bytes in the ``released`` ledger. Returns the
        bytes actually released (0 for an unknown component)."""
        with self._lock:
            key = str(component)
            held = self._reserved.get(key, 0)
            freed = held if nbytes is None else min(int(nbytes), held)
            if freed <= 0 and held == 0:
                return 0
            remaining = held - freed
            if remaining > 0:
                self._reserved[key] = remaining
            else:
                self._reserved.pop(key, None)
            self._released[key] = self._released.get(key, 0) + freed
            return freed

    def components(self) -> dict[str, int]:
        with self._lock:
            return dict(sorted(self._reserved.items()))

    def accounted_bytes(self) -> int:
        with self._lock:
            return sum(self._reserved.values())

    # ----------------------------------------------------- reconciliation

    def _devices(self):
        if self._devices_fn is not None:
            return self._devices_fn()
        import jax

        return jax.local_devices()

    def device_stats(self) -> list[dict]:
        """One row per local device: ``memory_stats()`` where the backend
        reports it, ``reported: False`` where it doesn't (CPU). Failures
        degrade per device — one bad device never hides the others."""
        rows = []
        try:
            devices = self._devices()
        except Exception:  # noqa: BLE001 — no backend at all: no rows
            return rows
        for i, d in enumerate(devices):
            row = {
                "device": i,
                "platform": getattr(d, "platform", "unknown"),
                "reported": False,
            }
            stats_fn = getattr(d, "memory_stats", None)
            if callable(stats_fn):
                try:
                    stats = stats_fn()
                except Exception:  # noqa: BLE001 — backend quirk != outage
                    stats = None
                if stats:
                    row["reported"] = True
                    row["bytes_in_use"] = int(stats.get("bytes_in_use", 0))
                    limit = stats.get(
                        "bytes_limit", stats.get("bytes_reservable_limit", 0)
                    )
                    row["bytes_limit"] = int(limit or 0)
            rows.append(row)
        return rows

    def reconcile(self) -> dict:
        """Accounted vs backend-reported totals + a headroom estimate.

        ``ratio`` is accounted/reported (None when nothing reports — the
        CPU fallback); ``headroom_bytes`` is limit - in_use summed over
        reporting devices, or None."""
        devices = self.device_stats()
        reporting = [d for d in devices if d["reported"]]
        accounted = self.accounted_bytes()
        out = {
            "accounted_bytes": accounted,
            "devices_reporting": len(reporting),
            "devices_total": len(devices),
            "reported_bytes_in_use": None,
            "headroom_bytes": None,
            "ratio": None,
        }
        if reporting:
            in_use = sum(d["bytes_in_use"] for d in reporting)
            limit = sum(d["bytes_limit"] for d in reporting)
            out["reported_bytes_in_use"] = in_use
            if limit:
                out["headroom_bytes"] = max(limit - in_use, 0)
            if in_use:
                out["ratio"] = accounted / in_use
        return out

    def snapshot(self) -> dict:
        """The ``GET /memz`` body: per-component reservations, the freed
        ledger, quantization metadata (storage dtype + bytes saved vs an
        fp32 baseline, per component and total), per-device stats, and the
        reconciliation digest."""
        with self._lock:
            reserved = dict(sorted(self._reserved.items()))
            released = dict(sorted(self._released.items()))
            dtypes = dict(sorted(self._dtypes.items()))
            saved = {
                key: self._fp32[key] - self._reserved.get(key, 0)
                for key in sorted(self._fp32)
            }
        return {
            "components": reserved,
            "released": released,
            "component_dtypes": dtypes,
            "bytes_saved_vs_fp32": saved,
            "bytes_saved_vs_fp32_total": sum(saved.values()),
            "devices": self.device_stats(),
            **self.reconcile(),
        }


_DEFAULT_LOCK = threading.Lock()
_DEFAULT: MemoryRegistry | None = None


def default_registry() -> MemoryRegistry:
    """Process-wide registry: engines/ckpt/train register here unless a
    caller supplies their own, so one serving process's ``/memz`` sees
    every footprint without plumbing a handle through each layer."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = MemoryRegistry()
        return _DEFAULT


def reset_default_registry() -> None:
    """Swap in a fresh default (tests isolate their accounting with it)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        _DEFAULT = MemoryRegistry()
