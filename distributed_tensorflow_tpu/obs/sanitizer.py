"""locktrace + racetrace: opt-in runtime concurrency sanitizers.

``sanitize_locks()`` monkeypatches ``threading.Lock`` and
``threading.Condition`` so every lock created inside the context is a
``TrackedLock`` that records, per acquisition, which locks the acquiring
thread already held. Those (held → acquired) edges form a directed
acquisition-order graph; a cycle in it means two threads can take the same
locks in opposite orders — a potential deadlock, reported even if the
interleaving never actually deadlocked during the test run.

Nodes are *creation sites* (``file:lineno`` of the ``Lock()`` call), not
instances, so the pattern generalizes across pool/queue instances created
from the same line. Self-edges (site → same site) are ignored: nested
acquisition of two instances from one constructor line (e.g. two queues)
is ordered by the caller, not by this graph.

Only locks constructed *while the patch is installed* are tracked —
pre-existing module locks and stdlib internals (logging, importlib) keep
their native types, so the sanitizer cannot perturb code outside the
system under test. ``queue.Queue`` and ``threading.Event`` objects built
inside the window *are* tracked (their internal mutex/Condition route
through the patched constructors), which is exactly what the batcher /
prefetch soak tests want.

``sanitize_races()`` layers a happens-before race detector on top of the
same machinery: per-thread vector clocks advanced by tracked-lock
release→acquire edges (plus ``Thread.start``/``join`` edges), and
``__setattr__``/``__getattribute__`` instrumentation on a declared
attribute set (a class's ``_RACETRACE_ATTRS`` tuple, or an explicit
``watch=`` mapping). Two accesses to the same attribute from different
threads with neither ordered before the other — and at least one a write —
is a data race, reported with both stacks and the creation sites of the
locks that *would* have ordered them. See :class:`RaceSanitizer`.
"""

from __future__ import annotations

import dataclasses
import sys
import threading
import traceback
from contextlib import contextmanager

__all__ = [
    "LockOrderSanitizer",
    "RaceSanitizer",
    "sanitize_locks",
    "sanitize_races",
]

_REAL_LOCK = threading.Lock
_REAL_CONDITION = threading.Condition


def _creation_site(skip_prefixes: tuple[str, ...]) -> str:
    """file:lineno of the frame that called Lock()/Condition()."""
    for frame in reversed(traceback.extract_stack(limit=12)[:-2]):
        fname = frame.filename
        if any(p in fname for p in skip_prefixes):
            continue
        return f"{fname.rsplit('/', 1)[-1]}:{frame.lineno}"
    return "<unknown>"


class LockOrderSanitizer:
    """Acquisition graph + cycle detection over tracked locks."""

    def __init__(self) -> None:
        self._graph_lock = _REAL_LOCK()
        # site -> set of sites acquired while holding it, with one example
        # stack edge label for the report.
        self._edges: dict[str, set[str]] = {}
        self._held = threading.local()
        self.acquisitions = 0

    # -- called by TrackedLock ------------------------------------------

    def _stack(self) -> list[str]:
        if not hasattr(self._held, "stack"):
            self._held.stack = []
        return self._held.stack

    def note_acquired(self, site: str, lock=None) -> None:
        stack = self._stack()
        if stack:
            holder = stack[-1]
            if holder != site:
                with self._graph_lock:
                    self._edges.setdefault(holder, set()).add(site)
        with self._graph_lock:
            self.acquisitions += 1
        stack.append(site)

    def note_released(self, site: str, lock=None) -> None:
        stack = self._stack()
        # Locks may be released out of LIFO order (Condition.wait releases
        # the underlying lock mid-stack); remove the most recent entry.
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == site:
                del stack[i]
                return

    # -- reporting ------------------------------------------------------

    def edges(self) -> dict[str, set[str]]:
        with self._graph_lock:
            return {k: set(v) for k, v in self._edges.items()}

    def cycles(self) -> list[list[str]]:
        """All elementary acquisition-order cycles (DFS, deduplicated)."""
        graph = self.edges()
        cycles: list[list[str]] = []
        seen: set[tuple[str, ...]] = set()

        def dfs(node: str, path: list[str], on_path: set[str]) -> None:
            for nxt in sorted(graph.get(node, ())):
                if nxt in on_path:
                    cyc = path[path.index(nxt):] + [nxt]
                    # Canonicalize rotation so each cycle reports once.
                    core = cyc[:-1]
                    k = core.index(min(core))
                    canon = tuple(core[k:] + core[:k])
                    if canon not in seen:
                        seen.add(canon)
                        cycles.append(list(canon) + [canon[0]])
                elif nxt not in path:
                    dfs(nxt, path + [nxt], on_path | {nxt})

        for start in sorted(graph):
            dfs(start, [start], {start})
        return cycles

    def report(self) -> str:
        lines = [f"lock-order sanitizer: {self.acquisitions} acquisitions"]
        for src in sorted(self._edges):
            for dst in sorted(self._edges[src]):
                lines.append(f"  {src} -> {dst}")
        cycles = self.cycles()
        if cycles:
            lines.append("POTENTIAL DEADLOCK CYCLES:")
            for cyc in cycles:
                lines.append("  " + " -> ".join(cyc))
        else:
            lines.append("no acquisition-order cycles")
        return "\n".join(lines)

    def assert_no_cycles(self) -> None:
        cycles = self.cycles()
        if cycles:
            raise AssertionError(
                "lock acquisition-order cycle(s) detected:\n" + self.report()
            )


class TrackedLock:
    """Drop-in ``threading.Lock`` recording acquisition order."""

    def __init__(self, sanitizer: LockOrderSanitizer, site: str) -> None:
        self._lock = _REAL_LOCK()
        self._san = sanitizer
        self._site = site

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._san.note_acquired(self._site, self)
        return got

    def release(self) -> None:
        # The release edge is published BEFORE the real release: once
        # another thread can win the lock, its acquire edge must already
        # see everything this thread did while holding it.
        self._san.note_released(self._site, self)
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<TrackedLock {self._site} {self._lock!r}>"


# ------------------------------------------------------------- racetrace


def _access_stack(limit: int = 16) -> tuple[tuple, ...]:
    """Raw caller stack, newest frame first.

    This runs on every watched attribute access, so it must be cheap: a
    bare frame walk collecting ``(code_object, lineno)`` pairs — no
    ``traceback`` FrameSummary objects, no linecache source lookups, not
    even the ``co_filename``/``co_name`` attribute fetches (code objects
    outlive their frames, so those resolve lazily in ``_format_stack``
    when a race is actually rendered).
    """
    frame = sys._getframe(1)
    out = []
    while frame is not None and len(out) < limit:
        out.append((frame.f_code, frame.f_lineno))
        frame = frame.f_back
    return tuple(out)


def _format_stack(raw: tuple[tuple, ...]) -> list[str]:
    """Render a raw stack oldest-first, sanitizer/threading frames elided."""
    kept = []
    for code, lineno in raw:  # newest first
        base = code.co_filename.rsplit("/", 1)[-1]
        if base in ("sanitizer.py", "threading.py"):
            continue
        kept.append(f"{base}:{lineno} in {code.co_name}")
    kept = kept[:10]
    kept.reverse()
    return kept


class _MemAccess:
    """One recorded access to a watched attribute.

    A plain __slots__ class, not a dataclass: one is built per watched
    access and frozen-dataclass ``__init__`` (object.__setattr__ per
    field) is measurable on that path.
    """

    __slots__ = ("tid", "thread_name", "clock", "op", "stack", "held")

    def __init__(self, tid, thread_name, clock, op, stack, held):
        self.tid = tid
        self.thread_name = thread_name
        self.clock = clock  # accessor's own vector-clock component
        self.op = op  # "read" | "write"
        self.stack = stack  # raw (code, lineno) frames, newest first
        self.held = held  # tracked-lock creation sites held at access


@dataclasses.dataclass(frozen=True)
class Race:
    """A pair of conflicting accesses with no happens-before edge."""

    cls: str
    attr: str
    first: _MemAccess
    second: _MemAccess
    candidate_locks: tuple[str, ...]  # lock sites seen guarding this attr

    def render(self) -> str:
        lines = [f"data race on {self.cls}.{self.attr} "
                 f"({self.first.op}/{self.second.op}):"]
        for acc in (self.first, self.second):
            held = f"holding [{', '.join(acc.held)}]" if acc.held else "holding no tracked lock"
            lines.append(
                f"  {acc.op} by thread '{acc.thread_name}' (ident {acc.tid}), {held}:"
            )
            for frame in _format_stack(acc.stack):
                lines.append(f"    {frame}")
        if self.candidate_locks:
            lines.append(
                "  lock(s) that would have ordered them (created at): "
                + ", ".join(self.candidate_locks)
            )
        else:
            lines.append(
                "  no tracked lock has ever guarded this attribute"
            )
        return "\n".join(lines)


class _AttrState:
    """Last write + last read-per-thread for one (object, attribute)."""

    __slots__ = ("cls", "write", "reads")

    def __init__(self, cls: str) -> None:
        self.cls = cls
        self.write: _MemAccess | None = None
        self.reads: dict[int, _MemAccess] = {}


class RaceSanitizer(LockOrderSanitizer):
    """Happens-before (vector clock) data-race detector.

    Extends the lock-order sanitizer: tracked-lock release→acquire pairs,
    ``Thread.start`` and completed ``Thread.join`` are the happens-before
    edges. Accesses to watched attributes are checked against the last
    write (and, for writes, the last read of every other thread); a
    conflicting pair with neither side ordered before the other is a race.

    Limitations (by design, documented in docs/ANALYSIS.md): only locks
    *created inside the window* carry edges — construct the system under
    test inside ``sanitize_races``; ``Thread`` subclasses overriding
    ``run()`` don't get start-edge bootstrapping (use ``target=``); thread
    idents may be reused by the OS after a join (fresh clocks are issued
    on every patched ``run()``, so this only affects unpatched threads).
    """

    def __init__(self) -> None:
        super().__init__()
        self._vc_mu = _REAL_LOCK()
        self._vcs: dict[int, dict[int, int]] = {}  # tid -> vector clock
        self._lock_vcs: dict[int, dict[int, int]] = {}  # id(lock) -> clock
        self._start_snaps: dict[int, dict[int, int]] = {}  # id(thread)
        self._final_vcs: dict[int, dict[int, int]] = {}  # id(thread)
        self._attrs: dict[tuple[int, str], _AttrState] = {}
        self._tid_names: dict[int, str] = {}  # current_thread() is hot
        self._guards: dict[tuple[str, str], set[str]] = {}  # (cls, attr)
        self._race_keys: set[tuple] = set()
        self.races: list[Race] = []
        self.accesses = 0

    # -- vector clocks (all helpers expect self._vc_mu held) -------------

    def _vc(self, tid: int) -> dict[int, int]:
        vc = self._vcs.get(tid)
        if vc is None:
            # A thread first seen mid-flight: its own component starts at
            # 1 so it is never confused with the "never observed" epoch 0.
            vc = self._vcs[tid] = {tid: 1}
        return vc

    @staticmethod
    def _join_into(dst: dict[int, int], src: dict[int, int] | None) -> None:
        if src:
            for tid, clock in src.items():
                if clock > dst.get(tid, 0):
                    dst[tid] = clock

    # -- happens-before edges -------------------------------------------

    def note_acquired(self, site: str, lock=None) -> None:
        super().note_acquired(site, lock)
        if lock is not None:
            tid = threading.get_ident()
            with self._vc_mu:
                self._join_into(self._vc(tid), self._lock_vcs.get(id(lock)))

    def note_released(self, site: str, lock=None) -> None:
        super().note_released(site, lock)
        if lock is not None:
            tid = threading.get_ident()
            with self._vc_mu:
                vc = self._vc(tid)
                self._lock_vcs[id(lock)] = dict(vc)
                vc[tid] = vc.get(tid, 1) + 1

    def note_thread_start(self, thread: threading.Thread) -> None:
        tid = threading.get_ident()
        with self._vc_mu:
            vc = self._vc(tid)
            self._start_snaps[id(thread)] = dict(vc)
            vc[tid] = vc.get(tid, 1) + 1

    def note_thread_run(self, thread: threading.Thread) -> None:
        tid = threading.get_ident()
        with self._vc_mu:
            vc = {tid: 1}
            self._join_into(vc, self._start_snaps.pop(id(thread), None))
            self._vcs[tid] = vc

    def note_thread_done(self, thread: threading.Thread) -> None:
        tid = threading.get_ident()
        with self._vc_mu:
            self._final_vcs[id(thread)] = dict(self._vc(tid))

    def note_thread_joined(self, thread: threading.Thread) -> None:
        tid = threading.get_ident()
        with self._vc_mu:
            self._join_into(self._vc(tid), self._final_vcs.get(id(thread)))

    # -- access checking -------------------------------------------------

    def on_access(self, obj, attr: str, op: str) -> None:
        tid = threading.get_ident()
        stack = _access_stack()
        held = tuple(self._stack())
        cls = type(obj).__name__
        name = self._tid_names.get(tid)
        if name is None:
            name = self._tid_names[tid] = threading.current_thread().name
        with self._vc_mu:
            self.accesses += 1
            vc = self._vc(tid)
            me = _MemAccess(tid, name, vc.get(tid, 1), op, stack, held)
            state = self._attrs.get((id(obj), attr))
            if state is None:
                state = self._attrs[(id(obj), attr)] = _AttrState(cls)
            guards = self._guards.setdefault((cls, attr), set())
            if held:
                guards.update(held)

            # prev is ordered before me iff my clock has absorbed it.
            conflicts = []
            w = state.write
            if w is not None and w.tid != tid and w.clock > vc.get(w.tid, 0):
                conflicts.append(w)
            if op == "write" and state.reads:
                for r in state.reads.values():
                    if r.tid != tid and r.clock > vc.get(r.tid, 0):
                        conflicts.append(r)
            for prev in conflicts:
                key = (cls, attr, prev.op, op, prev.stack, me.stack)
                if key not in self._race_keys:
                    self._race_keys.add(key)
                    self.races.append(
                        Race(
                            cls=cls,
                            attr=attr,
                            first=prev,
                            second=me,
                            candidate_locks=tuple(sorted(guards)),
                        )
                    )
            if op == "write":
                state.write = me
                state.reads = {}
            else:
                state.reads[tid] = me

    # -- reporting -------------------------------------------------------

    def race_report(self) -> str:
        lines = [
            f"race sanitizer: {self.accesses} watched accesses, "
            f"{len(self.races)} race(s)"
        ]
        for race in self.races:
            lines.append(race.render())
        return "\n".join(lines)

    def assert_race_free(self) -> None:
        if self.races:
            raise AssertionError(
                "data race(s) detected:\n" + self.race_report()
            )

    def assert_clean(self) -> None:
        self.assert_no_cycles()
        self.assert_race_free()


def _instrument_class(cls: type, attrs: frozenset, san: RaceSanitizer):
    """Wrap a class's attribute access for the watched set; returns undo."""
    own_set = cls.__dict__.get("__setattr__")
    own_get = cls.__dict__.get("__getattribute__")
    base_set = cls.__setattr__
    base_get = cls.__getattribute__

    def __setattr__(self, name, value):
        if name in attrs:
            san.on_access(self, name, "write")
        base_set(self, name, value)

    def __getattribute__(self, name):
        if name in attrs:
            san.on_access(self, name, "read")
        return base_get(self, name)

    cls.__setattr__ = __setattr__  # type: ignore[method-assign]
    cls.__getattribute__ = __getattribute__  # type: ignore[method-assign]

    def undo():
        if own_set is None:
            del cls.__setattr__
        else:
            cls.__setattr__ = own_set  # type: ignore[method-assign]
        if own_get is None:
            del cls.__getattribute__
        else:
            cls.__getattribute__ = own_get  # type: ignore[method-assign]

    return undo


@contextmanager
def sanitize_races(
    modules=(),
    watch: dict | None = None,
    skip_prefixes: tuple[str, ...] = ("threading.py", "sanitizer.py", "queue.py"),
):
    """Track lock order AND data races; yields a :class:`RaceSanitizer`.

    ``modules``: iterable of modules — every class in them declaring a
    ``_RACETRACE_ATTRS`` tuple gets its declared attributes instrumented.
    ``watch``: explicit ``{cls: (attr, ...)}`` additions (tests, ad-hoc).

    As with ``sanitize_locks``, only locks created inside the window carry
    happens-before edges — build the system under test inside the context,
    or unguarded accesses ordered by a pre-existing (untracked) lock will
    be reported as races.
    """
    san = RaceSanitizer()

    targets: dict[type, frozenset] = {}
    for mod in modules:
        for obj in vars(mod).values():
            if isinstance(obj, type):
                declared = obj.__dict__.get("_RACETRACE_ATTRS")
                if declared:
                    targets[obj] = frozenset(declared)
    for cls, attrs in (watch or {}).items():
        targets[cls] = targets.get(cls, frozenset()) | frozenset(attrs)

    def make_lock() -> TrackedLock:
        return TrackedLock(san, _creation_site(skip_prefixes))

    def make_condition(lock=None):
        if lock is None:
            lock = make_lock()
        return _REAL_CONDITION(lock)

    real_start = threading.Thread.start
    real_run = threading.Thread.run
    real_join = threading.Thread.join

    def start(thread):
        san.note_thread_start(thread)
        return real_start(thread)

    def run(thread):
        san.note_thread_run(thread)
        try:
            real_run(thread)
        finally:
            san.note_thread_done(thread)

    def join(thread, timeout=None):
        real_join(thread, timeout)
        if not thread.is_alive():
            san.note_thread_joined(thread)

    undos = [_instrument_class(cls, attrs, san) for cls, attrs in targets.items()]
    threading.Lock = make_lock  # type: ignore[assignment]
    threading.Condition = make_condition  # type: ignore[assignment]
    threading.Thread.start = start  # type: ignore[method-assign]
    threading.Thread.run = run  # type: ignore[method-assign]
    threading.Thread.join = join  # type: ignore[method-assign]
    try:
        yield san
    finally:
        threading.Lock = _REAL_LOCK  # type: ignore[assignment]
        threading.Condition = _REAL_CONDITION  # type: ignore[assignment]
        threading.Thread.start = real_start  # type: ignore[method-assign]
        threading.Thread.run = real_run  # type: ignore[method-assign]
        threading.Thread.join = real_join  # type: ignore[method-assign]
        for undo in undos:
            undo()


@contextmanager
def sanitize_locks(
    skip_prefixes: tuple[str, ...] = ("threading.py", "sanitizer.py", "queue.py")
):
    """Context manager: track all locks created inside; yields the sanitizer.

    ``threading.Condition`` keeps its stdlib implementation but, created
    with no argument, now wraps a ``TrackedLock`` — the stdlib Condition
    handles foreign locks via its documented ``acquire(0)``/default
    ``_release_save`` fallbacks, so ``with cv:`` and ``cv.wait()`` record
    acquire/release events like any other tracked lock. Waiter locks are
    ``_thread.allocate_lock`` internals and stay untracked.
    """
    san = LockOrderSanitizer()

    def make_lock() -> TrackedLock:
        return TrackedLock(san, _creation_site(skip_prefixes))

    def make_condition(lock=None):
        if lock is None:
            lock = make_lock()
        return _REAL_CONDITION(lock)

    threading.Lock = make_lock  # type: ignore[assignment]
    threading.Condition = make_condition  # type: ignore[assignment]
    try:
        yield san
    finally:
        threading.Lock = _REAL_LOCK  # type: ignore[assignment]
        threading.Condition = _REAL_CONDITION  # type: ignore[assignment]
